"""Driver benchmark: ResNet-50 train-step throughput on the attached chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline (BASELINE.md): the reference's only measured training throughput is
~800 img/s aggregate on 8 GPUs (ResNet-34 log timestamps,
ResNet/pytorch/logs/resnet34-yanjiali-010319.log) ⇒ ~100 img/s/chip; the
driver metric is "ResNet-50 ILSVRC2012 images/sec/chip" so vs_baseline
divides by 100.
"""

from __future__ import annotations

import functools
import json
import time

import jax
import jax.numpy as jnp

BASELINE_IMG_PER_SEC_PER_CHIP = 100.0


def main():
    from deep_vision_tpu.core.optim import OptimizerConfig, build_optimizer
    from deep_vision_tpu.core.state import TrainState
    from deep_vision_tpu.models.resnet import ResNet50
    from deep_vision_tpu.tasks.classification import ClassificationTask

    batch, size = 256, 224
    model = ResNet50(dtype=jnp.bfloat16)
    task = ClassificationTask(1000)
    tx = build_optimizer(OptimizerConfig(
        name="sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4))

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (batch, size, size, 3), jnp.float32)
    y = jax.random.randint(rng, (batch,), 0, 1000)

    variables = jax.jit(functools.partial(model.init, train=False))(
        {"params": rng}, x[:1])
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"], tx=tx,
        batch_stats=variables["batch_stats"], rng=rng)

    @functools.partial(jax.jit, donate_argnums=0)
    def train_step(state, image, label):
        def loss_fn(params):
            out, new_vars = state.apply_fn(
                {"params": params, "batch_stats": state.batch_stats},
                image, train=True, mutable=["batch_stats"])
            loss, _ = task.loss(out, {"label": label})
            return loss, new_vars["batch_stats"]

        (loss, new_bs), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state.params)
        return state.apply_gradients(grads, batch_stats=new_bs), loss

    # compile + warmup (device_get, not block_until_ready: the latter can
    # return early through the axon tunnel)
    state, loss = train_step(state, x, y)
    for _ in range(3):
        state, loss = train_step(state, x, y)
    float(jax.device_get(loss))

    steps = 20
    t0 = time.perf_counter()
    for _ in range(steps):
        state, loss = train_step(state, x, y)
    float(jax.device_get(loss))  # drains the async dispatch chain
    dt = time.perf_counter() - t0

    n_chips = jax.device_count()
    img_per_sec_per_chip = steps * batch / dt / n_chips
    print(json.dumps({
        "metric": "resnet50_train_images_per_sec_per_chip",
        "value": round(img_per_sec_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 2),
    }))


if __name__ == "__main__":
    main()
