"""Driver benchmark: ResNet-50 train-step throughput on the attached chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...} —
extra keys report achieved TFLOP/s and MFU (model FLOPs utilization,
%-of-peak for the chip's bf16 matmul rate).

Baseline (BASELINE.md): the reference's only measured training throughput is
~800 img/s aggregate on 8 GPUs (ResNet-34 log timestamps,
ResNet/pytorch/logs/resnet34-yanjiali-010319.log) ⇒ ~100 img/s/chip; the
driver metric is "ResNet-50 ILSVRC2012 images/sec/chip" so vs_baseline
divides by 100.

Modes:
    python bench.py              # train-step throughput + MFU (driver mode)
    python bench.py --pipeline   # host input-pipeline throughput (JPEG
                                 # decode+augment through ImageNetLoader)
    python bench.py --profile    # also write a jax.profiler trace
    python bench.py --task yolo  # one task's train step at production shape
    python bench.py --all        # every task, one subprocess each
"""

from __future__ import annotations

import argparse
import functools
import json
import random
import time

import jax
import jax.numpy as jnp

BASELINE_IMG_PER_SEC_PER_CHIP = 100.0

# the peak-TFLOP/s spec table moved to obs/mfu.py — one source of truth
# for the training MFU here and the serving MFU gauge (/metrics);
# PEAK_BF16_TFLOPS stays importable from bench for existing callers
from deep_vision_tpu.obs.mfu import (  # noqa: E402
    PEAK_BF16_TFLOPS,
    compiled_flops as _compiled_flops,
    peak_tflops as _peak_tflops,
)


def bench_train_step(batch: int = 256, size: int = 224, steps: int = 20,
                     profile: bool = False, scan_steps: int = 40,
                     ema_decay: float = 0.0, grad_accum: int = 1,
                     momentum_dtype: str | None = None) -> dict:
    """Sustained ResNet-50 train-step throughput.

    ``scan_steps`` mirrors the Trainer's multi-step dispatch
    (``TrainConfig.scan_steps`` / ``--scan-steps``, core/trainer.py): K
    optimizer updates per device program via ``lax.scan``, which amortizes
    the ~2 ms/step host-dispatch overhead of the tunneled chip (~4%
    throughput at K=40; measured flat beyond).  ``scan_steps=1`` measures
    the step-per-dispatch path.

    ``ema_decay``/``grad_accum`` mirror the Trainer's recipe arithmetic
    (--ema-decay / --grad-accum): the EMA warmup FMA over params after
    each update, and sequential interleaved microbatches with grad
    averaging — so their throughput cost is measured, not assumed
    (VERDICT r3 #3).  The metric name gains _ema/_gaN suffixes.  Note
    this is the same LEAN step as the base row (no divergence guard, no
    per-microbatch rng fold), so the DELTA between rows isolates the
    recipe's cost; the coupled cli.train run in docs/PERF.md carries the
    full Trainer step.
    """
    from deep_vision_tpu.core.optim import OptimizerConfig, build_optimizer
    from deep_vision_tpu.core.state import TrainState
    from deep_vision_tpu.models.resnet import ResNet50
    from deep_vision_tpu.tasks.classification import ClassificationTask

    model = ResNet50(dtype=jnp.bfloat16)
    task = ClassificationTask(1000)
    tx = build_optimizer(OptimizerConfig(
        name="sgd", learning_rate=0.1, momentum=0.9, weight_decay=1e-4,
        momentum_dtype=momentum_dtype))

    rng = jax.random.PRNGKey(0)
    x = jax.random.normal(rng, (batch, size, size, 3), jnp.float32)
    y = jax.random.randint(rng, (batch,), 0, 1000)

    variables = jax.jit(functools.partial(model.init, train=False))(
        {"params": rng}, x[:1])
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"], tx=tx,
        batch_stats=variables["batch_stats"], rng=rng,
        ema=ema_decay > 0)

    def grad_one(state, params, batch_stats, image, label):
        def loss_fn(params):
            out, new_vars = state.apply_fn(
                {"params": params, "batch_stats": batch_stats},
                image, train=True, mutable=["batch_stats"])
            loss, _ = task.loss(out, {"label": label})
            return loss, new_vars["batch_stats"]

        return jax.value_and_grad(loss_fn, has_aux=True)(params)

    def one_step(state, image, label):
        if grad_accum == 1:
            (loss, new_bs), grads = grad_one(
                state, state.params, state.batch_stats, image, label)
        else:
            # trainer-exact microbatching: interleaved split, stats
            # threaded sequentially, grads averaged (core/trainer.py)
            def split(x):
                return jnp.swapaxes(
                    x.reshape(x.shape[0] // grad_accum, grad_accum,
                              *x.shape[1:]), 0, 1)

            mi, ml = split(image), split(label)
            gzero = jax.tree_util.tree_map(jnp.zeros_like, state.params)

            def body(carry, xs):
                bs, gsum = carry
                im, lb = xs
                (l, bs), g = grad_one(state, state.params, bs, im, lb)
                return (bs, jax.tree_util.tree_map(jnp.add, gsum, g)), l

            (new_bs, gsum), losses = jax.lax.scan(
                body, (state.batch_stats, gzero), (mi, ml))
            grads = jax.tree_util.tree_map(lambda g: g / grad_accum, gsum)
            loss = jnp.mean(losses)
        new_state = state.apply_gradients(grads, batch_stats=new_bs)
        if ema_decay:
            t = new_state.step.astype(jnp.float32)
            d = jnp.minimum(ema_decay, (1.0 + t) / (10.0 + t))
            new_state = new_state.replace(
                ema_params=jax.tree_util.tree_map(
                    lambda e, p: d * e + (1 - d) * p,
                    new_state.ema_params, new_state.params))
        return new_state, loss

    K = max(1, scan_steps)

    @functools.partial(jax.jit, donate_argnums=0)
    def train_block(state, image, label):
        def body(s, _):
            s, loss = one_step(s, image, label)
            return s, loss

        # unroll=2: halves the loop-trip overhead and lets XLA overlap
        # step i's optimizer update with step i+1's first convs — measured
        # 99.6 ms/step vs 101.1 unrolled=1 vs 105 per-dispatch
        state, losses = jax.lax.scan(body, state, None, length=K, unroll=2)
        return state, losses[-1]

    # AOT compiles.  The FLOP count (honest MFU numerator, no hand-derived
    # constants) comes from XLA's cost analysis of the SINGLE-step
    # executable — the scan executable reports its loop body only once
    # regardless of trip count, so it can't be used directly.
    step_flops = _cost_flops(jax.jit(one_step).lower(state, x, y).compile())
    compiled = train_block.lower(state, x, y).compile()
    hbm_gib = _hbm_gib(compiled)

    # warmup (device_get, not block_until_ready: the latter can return
    # early through the axon tunnel)
    state, loss = compiled(state, x, y)
    float(jax.device_get(loss))

    blocks = max(1, steps // K) if K > 1 else steps
    if profile:
        jax.profiler.start_trace("/tmp/bench_profile")
    t0 = time.perf_counter()
    for _ in range(blocks):
        state, loss = compiled(state, x, y)
    float(jax.device_get(loss))  # drains the async dispatch chain
    dt = time.perf_counter() - t0
    steps = blocks * K
    if profile:
        jax.profiler.stop_trace()
        print("# trace written to /tmp/bench_profile")

    # normalize by the devices the step ACTUALLY spans (a plain jit runs on
    # one device regardless of how many chips the host exposes)
    n_chips = len({d for arr in jax.tree_util.tree_leaves(state)
                   for d in arr.devices()}) or 1
    img_per_sec_per_chip = steps * batch / dt / n_chips
    suffix = ("_ema" if ema_decay else "") + \
        (f"_ga{grad_accum}" if grad_accum > 1 else "") + \
        ("_bf16mom" if momentum_dtype == "bfloat16" else "")
    out = {
        "metric": "resnet50_train_images_per_sec_per_chip" + suffix,
        "value": round(img_per_sec_per_chip, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(
            img_per_sec_per_chip / BASELINE_IMG_PER_SEC_PER_CHIP, 2),
    }
    # cost analysis counts a lax.scan body once regardless of trip count,
    # so the microbatch scan inside a grad-accum step under-reports FLOPs
    # ~accum-fold — suppress the derived fields there (img/s is the metric)
    if step_flops and grad_accum == 1:
        achieved = step_flops * steps / dt / n_chips / 1e12
        out["tflops_per_chip"] = round(achieved, 1)
        out["mfu_pct"] = round(100.0 * achieved / _peak_tflops(), 1)
    out["device_kind"] = jax.devices()[0].device_kind
    out["batch"] = batch
    out["scan_steps"] = K
    if ema_decay:
        out["ema_decay"] = ema_decay
    if grad_accum > 1:
        out["grad_accum"] = grad_accum
    if hbm_gib:
        out["hbm_gib"] = hbm_gib
    return out


def _peak_hbm_gib() -> float | None:
    """Process-lifetime peak device-memory use, GiB (per-model when each
    task bench runs in its own process — what ``--all`` does).  Returns
    None where the runtime doesn't expose allocator stats (the tunneled
    axon client does not)."""
    try:
        stats = jax.devices()[0].memory_stats() or {}
        peak = stats.get("peak_bytes_in_use")
        return round(peak / 2**30, 2) if peak else None
    except Exception:
        return None


def _hbm_gib(compiled) -> float | None:
    """Static HBM footprint of one executable from XLA's own memory
    analysis: live arguments + outputs (minus donated aliases) + compiler
    temp arena.  Available even when allocator stats are not."""
    try:
        ma = compiled.memory_analysis()
        b = (ma.argument_size_in_bytes + ma.output_size_in_bytes
             - ma.alias_size_in_bytes + ma.temp_size_in_bytes)
        return round(b / 2**30, 2) if b else None
    except Exception:
        return None


def _cost_flops(compiled) -> float | None:
    """FLOPs of one executable per XLA's cost analysis (honest MFU
    numerator — no hand-derived constants); shared with the serving
    registry via obs/mfu.py."""
    return _compiled_flops(compiled)


def _finish(out: dict, compiled, dt: float, n_steps: int, batch_size: int,
            baseline: float | None = None) -> None:
    """Shared result assembly for the task benches."""
    rate = n_steps * batch_size / dt
    out["value"] = round(rate, 1)
    if baseline:
        out["vs_baseline"] = round(rate / baseline, 2)
    step_flops = _cost_flops(compiled)
    if step_flops:
        out["tflops_per_chip"] = round(step_flops * n_steps / dt / 1e12, 1)
    hbm = _hbm_gib(compiled)
    if hbm:
        out["hbm_gib"] = hbm
    out["ms_per_step"] = round(dt / n_steps * 1e3, 1)
    out["batch"] = batch_size


def _time_step(compiled, args, steps: int, loss_of, profile: bool = False):
    """Warm once, then time ``steps`` sequential dispatches, draining the
    async chain through a scalar fetch (block_until_ready can return early
    through the axon tunnel)."""
    out = compiled(*args)
    float(jax.device_get(loss_of(out)))
    if profile:
        jax.profiler.start_trace("/tmp/bench_profile")
    t0 = time.perf_counter()
    for _ in range(steps):
        out = compiled(*(out[:1] + args[1:]))
    float(jax.device_get(loss_of(out)))
    dt = time.perf_counter() - t0
    if profile:
        jax.profiler.stop_trace()
        print("# trace written to /tmp/bench_profile")
    return dt


def bench_task(name: str, steps: int | None = None,
               batch: int | None = None, profile: bool = False) -> dict:
    """Train-step throughput for one non-classification task at the
    REFERENCE's production shapes (VERDICT r02 item 4):

    - ``yolo``       YOLOv3-Darknet53 416², per-chip batch 16 (the
                     reference's per-GPU batch, YOLO/tensorflow/train.py:282)
    - ``centernet``  CenterNet (2-stack hourglass) 256² batch 32
                     (zoo/centernet.py — the stack the reference left broken)
    - ``hourglass``  Stacked Hourglass-104 256² batch 16, 16 joints @64²
    - ``cyclegan``   ResNet-9 G ×2 + PatchGAN D ×2, 256² batch 1
                     (CycleGAN/tensorflow/train.py batch_size=1)
    - ``dcgan``      28²×1 MNIST GAN, batch 256 (DCGAN/tensorflow/main.py)

    Each model trains bf16-compute / f32-params like the ResNet bench; the
    step is the same math the Trainer/AdversarialTrainer jits.  Reports
    images/sec/chip and process-peak HBM.
    """
    import numpy as np

    from deep_vision_tpu.core.optim import OptimizerConfig, build_optimizer
    from deep_vision_tpu.core.state import TrainState

    rng = jax.random.PRNGKey(0)
    out: dict = {"metric": f"{name}_train_images_per_sec_per_chip",
                 "unit": "images/sec/chip"}

    def single_state_run(model, task, batch, opt, n_steps, batch_size,
                         baseline=None):
        variables = jax.jit(functools.partial(model.init, train=False))(
            {"params": rng}, batch["image"][:1])
        state = TrainState.create(
            apply_fn=model.apply, params=variables["params"],
            tx=build_optimizer(opt),
            batch_stats=variables.get("batch_stats", {}), rng=rng)

        def one_step(state, batch):
            def loss_fn(params):
                outputs, new_vars = state.apply_fn(
                    {"params": params, "batch_stats": state.batch_stats},
                    batch["image"], train=True, mutable=["batch_stats"])
                loss, _ = task.loss(outputs, batch)
                return loss, new_vars["batch_stats"]

            (loss, bs), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(state.params)
            return state.apply_gradients(grads, batch_stats=bs), loss

        compiled = jax.jit(one_step, donate_argnums=0).lower(
            state, batch).compile()
        dt = _time_step(compiled, (state, batch), n_steps, lambda o: o[1],
                        profile=profile)
        _finish(out, compiled, dt, n_steps, batch_size, baseline)

    if name == "yolo":
        from deep_vision_tpu.models.yolo import YoloV3
        from deep_vision_tpu.tasks.detection import MAX_BOXES, YoloTask

        B, S = batch or 16, 416
        npr = np.random.default_rng(0)
        batch = {"image": jnp.asarray(
                     npr.normal(size=(B, S, S, 3)).astype(np.float32)),
                 "boxes": jnp.asarray(np.clip(
                     npr.uniform(0, 1, (B, MAX_BOXES, 4)), 0, 1)
                     .astype(np.float32)),
                 "boxes_mask": jnp.asarray(
                     (np.arange(MAX_BOXES) < 8)[None]
                     .repeat(B, 0).astype(np.float32))}
        for s, g in enumerate((52, 26, 13)):
            y = np.zeros((B, g, g, 3, 85), np.float32)
            # a few positive cells so every loss branch executes
            y[:, g // 2, g // 2, 0, 0:4] = (0.5, 0.5, 0.1, 0.1)
            y[:, g // 2, g // 2, 0, 4] = 1.0
            y[:, g // 2, g // 2, 0, 5] = 1.0
            batch[f"y_true_{s}"] = jnp.asarray(y)
        # reference: ~180 img/s aggregate on 8×V100 ⇒ 22.5 img/s/chip
        single_state_run(
            YoloV3(num_classes=80, dtype=jnp.bfloat16), YoloTask(80), batch,
            OptimizerConfig(name="sgd", learning_rate=1e-3, momentum=0.9),
            steps or 20, B, baseline=22.5)
    elif name == "centernet":
        from deep_vision_tpu.models.centernet import CenterNet
        from deep_vision_tpu.tasks.centernet import (CenterNetTask,
                                                     encode_centernet_labels)

        B, S = batch or 32, 256  # zoo/centernet.py: batch 32 @ 256²
        npr = np.random.default_rng(0)
        enc = [encode_centernet_labels(
            np.array([[0.3 + 0.4 * npr.random(), 0.3 + 0.4 * npr.random(),
                       0.2, 0.2]], np.float32),
            np.array([int(npr.integers(0, 80))]), 80, grid=S // 4)
            for _ in range(B)]
        batch = {k: jnp.asarray(np.stack([e[k] for e in enc]))
                 for k in enc[0]}
        batch["image"] = jnp.asarray(
            npr.normal(size=(B, S, S, 3)).astype(np.float32))
        single_state_run(
            CenterNet(num_classes=80, dtype=jnp.bfloat16),
            CenterNetTask(80), batch,
            OptimizerConfig(name="adam", learning_rate=2.5e-4),
            steps or 20, B)
    elif name == "hourglass":
        from deep_vision_tpu.models.hourglass import StackedHourglass
        from deep_vision_tpu.tasks.pose import PoseTask

        B = batch or 16
        batch = {"image": jax.random.normal(rng, (B, 256, 256, 3)),
                 "heatmaps": jnp.clip(
                     jax.random.normal(rng, (B, 64, 64, 16)), 0, 1)}
        single_state_run(
            StackedHourglass(num_stack=4, num_heatmap=16,
                             dtype=jnp.bfloat16),
            PoseTask(), batch,
            OptimizerConfig(name="adam", learning_rate=2.5e-4),
            steps or 20, B)
    elif name in ("cyclegan", "dcgan"):
        if name == "cyclegan":
            from deep_vision_tpu.models import gan as gan_models
            from deep_vision_tpu.tasks.gan import CycleGANTask

            B = batch or 1
            task = CycleGANTask(
                lambda: gan_models.CycleGANGenerator(dtype=jnp.bfloat16),
                lambda: gan_models.PatchGANDiscriminator(
                    dtype=jnp.bfloat16))
            host = {"image_a": np.random.default_rng(0).normal(
                        size=(B, 256, 256, 3)).astype(np.float32),
                    "image_b": np.random.default_rng(1).normal(
                        size=(B, 256, 256, 3)).astype(np.float32)}
            n_steps = steps or 40
        else:
            from deep_vision_tpu.models.gan import (DCGANDiscriminator,
                                                    DCGANGenerator)
            from deep_vision_tpu.tasks.gan import DCGANTask

            B = batch or 256
            task = DCGANTask(DCGANGenerator(dtype=jnp.bfloat16),
                             DCGANDiscriminator(dtype=jnp.bfloat16))
            host = {"image": np.random.default_rng(0).normal(
                size=(B, 28, 28, 1)).astype(np.float32)}
            n_steps = steps or 200
        states = task.init_states(rng, host)
        batch = jax.tree_util.tree_map(
            jnp.asarray, task.host_prepare(dict(host)))
        compiled = jax.jit(task.train_step, donate_argnums=0).lower(
            states, batch, rng).compile()
        dt = _time_step(compiled, (states, batch, rng), n_steps,
                        lambda o: next(iter(o[2].values())), profile=profile)
        _finish(out, compiled, dt, n_steps, B)
    else:
        raise SystemExit(f"unknown --task {name}")
    peak = _peak_hbm_gib()
    if peak:
        out["peak_hbm_gib"] = peak
    out["device_kind"] = jax.devices()[0].device_kind
    return out


def bench_infer(name: str = "resnet50", steps: int | None = None,
                batch: int | None = None) -> dict:
    """Forward-only (serving) throughput:

    - ``resnet50``  batch-256 bf16 classification forward;
    - ``yolo``      batch-16 416² forward INCLUDING the full on-device
                    postprocess (3-scale decode + score filter + batched
                    NMS, ops/boxes.py) — the reference runs NMS in host
                    Python per image (YOLO/tensorflow/postprocess.py).
    """
    import numpy as np

    rng = jax.random.PRNGKey(0)
    if name == "resnet50":
        from deep_vision_tpu.models.resnet import ResNet50

        B = batch or 256
        model = ResNet50(dtype=jnp.bfloat16)
        x = jax.random.normal(rng, (B, 224, 224, 3), jnp.float32)
        variables = jax.jit(functools.partial(model.init, train=False))(
            {"params": rng}, x[:1])

        def fwd(variables, x):
            logits = model.apply(variables, x, train=False)
            return jnp.argmax(logits, -1)

    elif name == "yolo":
        from deep_vision_tpu.models.yolo import YoloV3
        from deep_vision_tpu.tasks.detection import YoloTask

        B = batch or 16
        model = YoloV3(num_classes=80, dtype=jnp.bfloat16)
        task = YoloTask(80)
        x = jax.random.normal(rng, (B, 416, 416, 3), jnp.float32)
        variables = jax.jit(functools.partial(model.init, train=False))(
            {"params": rng}, x[:1])

        def fwd(variables, x):
            from deep_vision_tpu.tasks.detection import postprocess

            outputs = model.apply(variables, x, train=False)
            boxes, scores, classes, valid = postprocess(
                outputs, 80, anchors=np.asarray(task.anchors),
                masks=task.masks)
            return scores

    else:
        raise SystemExit(f"unknown --infer target {name}")

    compiled = jax.jit(fwd).lower(variables, x).compile()
    n_steps = steps or (20 if name == "yolo" else 40)
    out_first = compiled(variables, x)
    float(jax.device_get(out_first.reshape(-1)[0]))
    t0 = time.perf_counter()
    for _ in range(n_steps):
        o = compiled(variables, x)
    float(jax.device_get(o.reshape(-1)[0]))
    dt = time.perf_counter() - t0
    out = {"metric": f"{name}_infer_images_per_sec_per_chip",
           "value": round(n_steps * B / dt, 1),
           "unit": "images/sec/chip",
           "ms_per_batch": round(dt / n_steps * 1e3, 1), "batch": B}
    hbm = _hbm_gib(compiled)
    if hbm:
        out["hbm_gib"] = hbm
    out["device_kind"] = jax.devices()[0].device_kind
    return out


def bench_serve(model_name: str = "lenet5", loads: tuple = (1, 8),
                duration_s: float = 2.0, max_batch: int = 8,
                max_wait_ms: float = 2.0, pipeline_depth: int = 2,
                faults: str = "", fault_seed: int = 0,
                serve_devices: int = 1,
                serve_mesh: tuple | None = None,
                mesh_min_shard_dim: int = 1024,
                wire_dtype: str = "float32",
                infer_dtype: str = "float32",
                calib_batches: int = 2,
                trace: bool = True) -> dict:
    """Closed-loop load generator against the dynamic-batching engine
    (``deep_vision_tpu/serve``): C client threads each submit one image,
    wait for the answer, repeat — so C is the offered load (concurrency),
    and the engine's batcher decides how requests coalesce into bucketed
    device batches.  One JSON line reports p50/p95/p99 request latency
    and sustained img/s at every load point — the knee where latency
    rises faster than throughput is the max_wait/bucket tuning signal
    (docs/SERVING.md) — plus the pipelined executor's overlap block
    (device-idle fraction, in-flight high-water mark, staged-buffer
    reuse, bulk D2H bytes) so serving regressions are trackable the way
    BENCH_r0*.json tracks training.  ``--serve-pipeline-depth 1`` is the
    synchronous comparison run.

    ``--faults`` (a deterministic spec, docs/SERVING.md) exercises the
    failure paths under load — each load point then also reports its
    error count, and the JSON gains a ``health`` block (state machine,
    retries, quarantines, watchdog restarts) so fault-tolerance overhead
    and behavior are benchmarkable, not just unit-tested.

    ``serve_devices > 1`` replicates the engine over that many local
    devices (serve/replicas.py) and the JSON gains ``replicas`` —
    per-replica batches, img/s, and in-flight high-water — plus the
    routing counters; ``bench.py --serve --serve-devices N`` sweeps
    replica counts 1, 2, 4, ... N and emits the device-scaling table
    (docs/PERF.md).

    ``serve_mesh=(D, M)`` instead builds ONE engine on a D×M
    data×model mesh (registry ``for_mesh``): batches split D ways,
    params shard M ways (first-divisible-axis fallback at
    ``mesh_min_shard_dim``), and the JSON gains ``mesh`` /
    ``param_shard_bytes`` / ``param_global_bytes`` — the per-chip HBM
    column of the ``--serve-mesh`` sweep (``bench_serve_mesh``).

    ``wire_dtype``/``infer_dtype`` select the serving wire format and
    on-device compute dtype (docs/SERVING.md); the JSON records both
    plus the ``h2d`` block (transfers, MiB, per-bucket bytes) and the
    resident ``weight_hbm_bytes`` so BENCH_* trajectories track
    transfer volume and weight footprint alongside latency —
    ``bench.py --serve --serve-wire`` runs the full 6-cell comparison
    (``bench_serve_wire``); ``infer_dtype="int8"`` calibrates with
    ``calib_batches`` synthetic batches (serve/quant.py).

    ``trace`` toggles per-request span collection (obs/trace.py): the
    JSON gains ``serving_mfu``/``mfu`` (analytic-FLOPs utilization,
    docs/OBSERVABILITY.md) and ``stages`` (mean per-stage milliseconds
    across traced requests); ``bench.py --serve --serve-obs`` runs
    trace-off then trace-on and reports the overhead deltas.
    """
    import sys
    import tempfile
    import threading

    import numpy as np

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state
    from deep_vision_tpu.obs.trace import Tracer
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.admission import Shed
    from deep_vision_tpu.serve.faults import FaultPlane, Quarantined
    from deep_vision_tpu.serve.registry import CheckpointServingModel

    cfg = get_config(model_name)
    with tempfile.TemporaryDirectory() as td:
        # random-init fallback: serving latency is weight-agnostic
        model, state = load_state(cfg, td,
                                  log=lambda m: print(m, file=sys.stderr))
    sm = CheckpointServingModel(model_name, cfg, model, state,
                                wire_dtype=wire_dtype,
                                infer_dtype=infer_dtype,
                                calib_batches=calib_batches)
    if sm.wire_dtype == np.uint8:
        img = np.random.RandomState(0).randint(
            0, 256, size=sm.input_shape, dtype=np.uint8)
    else:
        img = np.random.RandomState(0).randn(
            *sm.input_shape).astype(np.float32)
    tracer = Tracer(enabled=trace)
    if serve_mesh is not None:
        from deep_vision_tpu.parallel.mesh import make_mesh
        from deep_vision_tpu.serve.engine import sharded_buckets
        from deep_vision_tpu.serve.replicas import local_devices

        n_data, n_model = int(serve_mesh[0]), int(serve_mesh[1])
        mesh = make_mesh({"data": n_data, "model": n_model},
                         devices=local_devices(n_data * n_model))
        engine_ctx = BatchingEngine(
            sm.for_mesh(mesh, min_shard_dim=mesh_min_shard_dim),
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            buckets=sharded_buckets(max_batch, n_data),
            pipeline_depth=pipeline_depth,
            faults=FaultPlane(faults, fault_seed), tracer=tracer)
    elif serve_devices > 1:
        from deep_vision_tpu.serve.replicas import (ReplicatedEngine,
                                                    local_devices)

        engine_ctx = ReplicatedEngine(
            sm, devices=local_devices(serve_devices),
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            pipeline_depth=pipeline_depth,
            faults=FaultPlane(faults, fault_seed), tracer=tracer)
    else:
        engine_ctx = BatchingEngine(
            sm, max_batch=max_batch, max_wait_ms=max_wait_ms,
            pipeline_depth=pipeline_depth,
            faults=FaultPlane(faults, fault_seed), tracer=tracer)
    points = []
    with engine_ctx as engine:
        engine.warmup()  # compiles excluded from every load point
        for clients in loads:
            latencies: list = []
            errors = [0]
            retries = [0]
            lock = threading.Lock()
            stop_at = time.perf_counter() + duration_s

            def client(seed):
                # a well-behaved closed-loop client: a queue-full shed
                # carries a Retry-After hint, so honor it with jittered
                # backoff (bounded) instead of polluting the error
                # column — only sheds that exhaust the retry budget, or
                # carry no hint (deadline/shutdown), count as errors
                rng = random.Random(seed)
                local, local_err, local_retry = [], 0, 0
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    r = None
                    try:
                        for _ in range(3):  # 1 attempt + 2 retries
                            r = engine.infer(img, timeout=60)
                            if not (isinstance(r, Shed)
                                    and r.retry_after_s):
                                break
                            local_retry += 1
                            time.sleep(min(r.retry_after_s, 0.25)
                                       * (0.5 + rng.random()))
                        if isinstance(r, (Shed, Quarantined)):
                            local_err += 1
                            continue
                    except Exception:  # noqa: BLE001 — injected faults
                        local_err += 1
                        continue
                    local.append(time.perf_counter() - t0)
                with lock:
                    latencies.extend(local)
                    errors[0] += local_err
                    retries[0] += local_retry

            threads = [threading.Thread(target=client, args=(k,))
                       for k in range(clients)]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_start
            lat_ms = np.asarray(latencies) * 1e3
            points.append({
                "clients": clients, "requests": len(latencies),
                "errors": errors[0], "retries": retries[0],
                "img_per_sec": round(len(latencies) / elapsed, 1),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
                "p95_ms": round(float(np.percentile(lat_ms, 95)), 2),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 2)})
        stats = engine.stats()
    pipe = stats["pipeline"]
    staging = pipe["staging"]
    health = stats["health"]
    out = {"metric": f"serve_{model_name}_img_per_sec",
            "value": points[-1]["img_per_sec"], "unit": "img/s",
            "model": model_name, "max_batch": max_batch,
            "max_wait_ms": max_wait_ms, "buckets": stats["buckets"],
            "pipeline_depth": pipeline_depth,
            "wire_dtype": stats["wire_dtype"],
            "infer_dtype": stats["infer_dtype"],
            "weight_hbm_bytes": stats.get("weight_hbm_bytes"),
            "calib_batches": (calib_batches
                              if infer_dtype == "int8" else None),
            "faults": faults or None,
            "loads": points,
            "h2d": {
                "transfers": pipe["h2d_transfers"],
                "mib": round(pipe["h2d_bytes"] / 2**20, 3),
                "bytes_per_batch": round(
                    pipe["h2d_bytes"] / max(1, pipe["h2d_transfers"])),
                "bytes_by_bucket": pipe["h2d_bytes_by_bucket"]},
            "health": {
                "state": health["state"],
                "batch_failures": health["batch_failures"],
                "retry_executions": health["retry_executions"],
                "quarantined": health["quarantined"],
                "watchdog_restarts": health["watchdog_restarts"],
                "exec_timeouts": health["exec_timeouts"],
                **({"faults": health["faults"]}
                   if "faults" in health else {})},
            "engine": {"batches": stats["batches"],
                       "compiles": stats["compiles"],
                       "padded_images": stats["padded_images"]},
            "overlap": {
                "device_idle_frac": pipe["device_idle_frac"],
                "max_inflight": pipe["max_inflight"],
                "bulk_transfers": pipe["bulk_transfers"],
                "bulk_transfer_mib": round(
                    pipe["bulk_transfer_bytes"] / 2**20, 3),
                "staged_buffers_allocated": staging["allocated"],
                "staged_buffer_reuses": staging["reused"],
                "exec_ewma_ms_by_bucket":
                    stats["admission"]["exec_ewma_ms_by_bucket"]},
            "device_kind": jax.devices()[0].device_kind}
    mfu = stats.get("mfu") or {}
    out["serving_mfu"] = mfu.get("serving_mfu")
    out["mfu"] = {k: mfu.get(k) for k in
                  ("serving_mfu", "flops_source", "flops_total",
                   "compute_s", "unknown_flops_batches",
                   "peak_flops_per_s")}
    tr = stats.get("trace") or {}
    out["trace_enabled"] = trace
    if tr.get("enabled"):
        out["stages"] = {"stage_ms_avg": tr.get("stage_ms_avg"),
                         "traces_finished": tr.get("finished"),
                         "slow_sampled": tr.get("slow_sampled")}
    if serve_mesh is not None:
        out["mesh"] = stats.get("mesh_shape")
        out["param_shard_bytes"] = stats.get("param_shard_bytes")
        out["param_global_bytes"] = stats.get("param_global_bytes")
    if "replicas" in stats:
        out["serve_devices"] = serve_devices
        out["replicas"] = [
            {"replica": r["replica"], "device": r["device"],
             "state": r["state"], "batches": r["batches"],
             "routed_batches": r["routed_batches"],
             "img_per_sec": r["img_per_sec"],
             "max_inflight": r["max_inflight"]}
            for r in stats["replicas"]]
        out["routing"] = stats["routing"]
        out["admission_free_replicas"] = \
            stats["admission"]["free_replicas"]
    return out


def bench_serve_scaling(serve_devices: int, **kwargs) -> dict:
    """Device-scaling sweep: run the serve bench at replica counts
    1, 2, 4, ... ``serve_devices`` and emit one JSON with the scaling
    table (img/s + p99 at the top load point per count) plus the full
    detail of the widest run.  On real multi-chip hardware 1→2 replicas
    should show >1.6× offered-throughput capacity (docs/PERF.md); on a
    single shared host device the table measures routing overhead
    instead."""
    counts, c = [], 1
    while c < serve_devices:
        counts.append(c)
        c *= 2
    counts.append(serve_devices)
    table, last = [], None
    for k in counts:
        last = bench_serve(serve_devices=k, **kwargs)
        top = last["loads"][-1]
        table.append({"replicas": k,
                      "img_per_sec": top["img_per_sec"],
                      "p50_ms": top["p50_ms"], "p99_ms": top["p99_ms"],
                      "errors": top["errors"]})
    base = table[0]["img_per_sec"] or 1.0
    for row in table:
        row["speedup_vs_1"] = round(row["img_per_sec"] / base, 2)
    last["scaling"] = table
    return last


def bench_serve_mesh(mesh_devices: int = 4,
                     mesh_min_shard_dim: int = 64, **kwargs) -> dict:
    """Mesh-cell sweep (``bench.py --serve-mesh N``; docs/PERF.md
    "Mesh scaling"): the serve bench across the 1×1 baseline, the pure
    data-parallel N×1, the pure model-parallel 1×N, and the squarest
    2-D D×M factorization of N — img/s, p99, and per-chip
    ``param_shard_bytes`` per cell, so the throughput cost and HBM
    saving of each layout are measured side by side.  On forced host
    devices the throughput columns measure GSPMD partitioning overhead
    on one shared chip (the HBM column is layout-true everywhere);
    real ICI separates the cells.  ``mesh_min_shard_dim`` defaults low
    (64) so the zoo's small models actually shard — production keeps
    the registry's 1024 floor."""
    n = int(mesh_devices)
    cells = [(1, 1), (n, 1), (1, n)]
    d = max((k for k in range(2, n) if n % k == 0 and k * k <= n),
            default=None)
    if d is not None:
        cells.append((max(d, n // d), min(d, n // d)))
    table, last = [], None
    for n_data, n_model in cells:
        last = bench_serve(serve_mesh=(n_data, n_model),
                           mesh_min_shard_dim=mesh_min_shard_dim,
                           **kwargs)
        top = last["loads"][-1]
        shard = last.get("param_shard_bytes")
        glob = last.get("param_global_bytes")
        table.append({
            "mesh": f"{n_data}x{n_model}",
            "img_per_sec": top["img_per_sec"],
            "p50_ms": top["p50_ms"], "p99_ms": top["p99_ms"],
            "errors": top["errors"],
            "param_shard_bytes": shard,
            "param_global_bytes": glob,
            "hbm_frac_of_replicated": round(shard / glob, 4)
            if shard and glob else None})
    last["mesh_sweep"] = table
    return last


def bench_serve_batch(model_name: str = "lenet5", n_images: int = 256,
                      shard_size: int | None = None, max_batch: int = 8,
                      max_wait_ms: float = 2.0, pipeline_depth: int = 2,
                      mesh: tuple = (2, 2),
                      mesh_min_shard_dim: int = 64,
                      loads: tuple = (2, 8),
                      duration_s: float = 2.0) -> dict:
    """Offline batch tier bench (``bench.py --serve-batch``; docs/PERF.md
    "Batch tier"): a bulk job drained through the trough-filling
    scheduler (serve/batch_sched.py) on a forced-host 2×2 data×model
    mesh engine, two phases:

    1. *Bulk-only drain*: one ``n_images`` job with no interactive
       traffic — sustained batch img/s, the drain-phase compute
       occupancy (Δcompute_s / Δwall from the MFU meter, window-free),
       and the occupancy-weighted MFU — the sustained-throughput
       figure the batch tier exists to maximize.
    2. *Interference sweep*: for each closed-loop interactive load C,
       interactive p50/p99 WITHOUT any batch work vs WITH a bulk job
       draining behind the priority band — the p99 ratio is the
       acceptance number (≈1.0: the band admits shards only into
       troughs), alongside the batch throughput the troughs yielded.

    On forced host devices every cell shares one chip, so absolute
    img/s undersells real hardware — the occupancy, MFU, and p99-ratio
    columns are the transferable numbers."""
    import sys
    import tempfile
    import threading

    import numpy as np

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state
    from deep_vision_tpu.obs.mfu import round_mfu
    from deep_vision_tpu.parallel.mesh import make_mesh
    from deep_vision_tpu.serve.admission import Shed
    from deep_vision_tpu.serve.batch_sched import BatchScheduler
    from deep_vision_tpu.serve.faults import Quarantined
    from deep_vision_tpu.serve.engine import (BatchingEngine,
                                              sharded_buckets)
    from deep_vision_tpu.serve.jobs import JobStore
    from deep_vision_tpu.serve.registry import CheckpointServingModel
    from deep_vision_tpu.serve.replicas import local_devices

    cfg = get_config(model_name)
    with tempfile.TemporaryDirectory() as td:
        model, state = load_state(cfg, td,
                                  log=lambda m: print(m, file=sys.stderr))
    sm = CheckpointServingModel(model_name, cfg, model, state,
                                wire_dtype="uint8")
    img = np.random.RandomState(0).randint(
        0, 256, size=sm.input_shape, dtype=np.uint8)
    n_data, n_model = int(mesh[0]), int(mesh[1])
    grid = make_mesh({"data": n_data, "model": n_model},
                     devices=local_devices(n_data * n_model))
    shard = int(shard_size or max_batch)

    def manifest(n):
        return [{"pixels": np.random.RandomState(i).randint(
            0, 256, size=sm.input_shape).tolist()} for i in range(n)]

    def mfu_snap(engine):
        m = engine.stats().get("mfu") or {}
        return (m.get("flops_total") or 0.0, m.get("compute_s") or 0.0,
                m.get("peak_flops_per_s"))

    with BatchingEngine(
            sm.for_mesh(grid, min_shard_dim=mesh_min_shard_dim),
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            buckets=sharded_buckets(max_batch, n_data),
            pipeline_depth=pipeline_depth) as engine:
        engine.warmup()  # compiles excluded from both phases

        def run_job(n, sched_kwargs=None):
            store = JobStore(shard_size=shard)
            sched = BatchScheduler(store, lambda name: (sm, engine),
                                   interval_s=0.002,
                                   **(sched_kwargs or {}))
            jid = store.submit(model_name, sm.workload.verb,
                               manifest(n))["job_id"]
            sched.start()
            return store, sched, jid

        def interactive_window(clients):
            latencies: list = []
            errors = [0]
            lock = threading.Lock()
            stop_at = time.perf_counter() + duration_s

            def client():
                local, local_err = [], 0
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    r = engine.infer(img, timeout=60)
                    if isinstance(r, (Shed, Quarantined)):
                        local_err += 1
                        continue
                    local.append(time.perf_counter() - t0)
                with lock:
                    latencies.extend(local)
                    errors[0] += local_err

            threads = [threading.Thread(target=client)
                       for _ in range(clients)]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_start
            if not latencies:  # every request shed: report the errors
                return {"requests": 0, "errors": errors[0],
                        "img_per_sec": 0.0, "p50_ms": None,
                        "p99_ms": None}
            lat_ms = np.asarray(latencies) * 1e3
            return {"requests": len(latencies), "errors": errors[0],
                    "img_per_sec": round(len(latencies) / elapsed, 1),
                    "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
                    "p99_ms": round(float(np.percentile(lat_ms, 99)), 2)}

        # -- phase 1: bulk-only drain ---------------------------------
        f0, c0, peak = mfu_snap(engine)
        store, sched, jid = run_job(n_images)
        t0 = time.perf_counter()
        while store.status(jid)["state"] not in ("done", "failed"):
            time.sleep(0.005)
        drain_s = time.perf_counter() - t0
        sched.stop()
        st = store.status(jid)
        assert st["state"] == "done", st
        f1, c1, peak = mfu_snap(engine)
        occ_drain = min(1.0, (c1 - c0) / drain_s) if drain_s > 0 else None
        mfu_drain = ((f1 - f0) / (c1 - c0)) / peak \
            if peak and c1 > c0 else None
        sched_stats = sched.stats()
        bulk = {
            "img_per_sec": round(n_images / drain_s, 1),
            "drain_s": round(drain_s, 3),
            "occupancy": round(occ_drain, 4)
            if occ_drain is not None else None,
            "occupancy_rolling": engine.stats()["pipeline"]["occupancy"],
            "serving_mfu": round_mfu(mfu_drain)
            if mfu_drain is not None else None,
            "mfu_occupancy_weighted": round_mfu(mfu_drain * occ_drain)
            if mfu_drain is not None and occ_drain is not None else None,
            "shards_done": sched_stats["shards_done"],
            "shards_shed": sched_stats["shards_shed"],
            "deferred": sched_stats["deferred"]}

        # -- phase 2: interactive-vs-batch interference sweep ---------
        table = []
        for clients in loads:
            base = interactive_window(clients)
            store, sched, jid = run_job(4 * n_images)
            done_before = store.status(jid)["images_done"]
            contended = interactive_window(clients)
            sched.stop()
            batch_done = store.status(jid)["images_done"] - done_before
            contended["batch_img_per_sec"] = round(
                batch_done / duration_s, 1)
            ratio = None
            if base["p99_ms"] and contended["p99_ms"]:
                ratio = round(contended["p99_ms"] / base["p99_ms"], 3)
            table.append({
                "clients": clients, "baseline": base,
                "with_batch": contended, "p99_ratio": ratio})
        stats = engine.stats()
    return {"metric": f"serve_batch_{model_name}_img_per_sec",
            "value": bulk["img_per_sec"], "unit": "img/s",
            "model": model_name, "mesh": f"{n_data}x{n_model}",
            "n_images": n_images, "shard_size": shard,
            "max_batch": max_batch, "buckets": stats["buckets"],
            "wire_dtype": stats["wire_dtype"],
            "bulk": bulk, "interference": table,
            "param_shard_bytes": stats.get("param_shard_bytes"),
            "device_kind": jax.devices()[0].device_kind}


def bench_serve_wire(**kwargs) -> dict:
    """Wire-format comparison sweep (``make bench-serve-wire``): the
    serve bench across all six wire × compute cells — f32/uint8 wire ×
    f32/bf16/int8 device compute — so the uint8 wire's 4× H2D-byte cut,
    bf16's latency effect, and int8's ~4× weight-HBM cut are measured
    side by side (docs/PERF.md "Serving wire format").  Emits the full
    detail of the last cell (uint8 + int8, the smallest-footprint
    configuration) plus ``wire_sweep``: p50/p95/p99, img/s, H2D
    bytes/batch, and resident weight bytes per cell.
    ``weight_hbm_ratio_int8_over_f32`` is the acceptance number for the
    int8 quantization path (≤ 0.27 expected; serve/quant.py keeps
    biases and BN f32, so the ratio sits just above 0.25)."""
    table, last = [], None
    for wire in ("float32", "uint8"):
        for infer in ("float32", "bfloat16", "int8"):
            last = bench_serve(wire_dtype=wire, infer_dtype=infer,
                               **kwargs)
            top = last["loads"][-1]
            table.append({
                "wire_dtype": wire, "infer_dtype": infer,
                "img_per_sec": top["img_per_sec"],
                "p50_ms": top["p50_ms"], "p95_ms": top["p95_ms"],
                "p99_ms": top["p99_ms"], "errors": top["errors"],
                "h2d_mib": last["h2d"]["mib"],
                "h2d_bytes_per_batch": last["h2d"]["bytes_per_batch"],
                "weight_hbm_bytes": last.get("weight_hbm_bytes"),
                "calib_batches": last.get("calib_batches")})
    f32w = [r for r in table if r["wire_dtype"] == "float32"]
    u8w = [r for r in table if r["wire_dtype"] == "uint8"]
    if f32w and u8w and u8w[0]["h2d_bytes_per_batch"]:
        last["h2d_bytes_ratio_f32_over_u8"] = round(
            f32w[0]["h2d_bytes_per_batch"]
            / u8w[0]["h2d_bytes_per_batch"], 2)
    f32c = [r for r in table if r["infer_dtype"] == "float32"
            and r["weight_hbm_bytes"]]
    i8c = [r for r in table if r["infer_dtype"] == "int8"
           and r["weight_hbm_bytes"]]
    if f32c and i8c:
        last["weight_hbm_ratio_int8_over_f32"] = round(
            i8c[0]["weight_hbm_bytes"] / f32c[0]["weight_hbm_bytes"], 4)
    last["wire_sweep"] = table
    return last


def bench_serve_obs(**kwargs) -> dict:
    """Observability-overhead comparison (``bench.py --serve
    --serve-obs``; docs/PERF.md "Observability overhead"): the serve
    bench twice — per-request tracing OFF, then ON — same engine
    parameters, fresh engine each run.  Emits the traced run's full
    detail plus ``obs_overhead``: img/s and p99 at the top load point
    for both runs and the on-vs-off deltas in percent (the acceptance
    bar is < 2% on both)."""
    kwargs.pop("trace", None)
    off = bench_serve(trace=False, **kwargs)
    on = bench_serve(trace=True, **kwargs)
    t_off, t_on = off["loads"][-1], on["loads"][-1]
    on["obs_overhead"] = {
        "img_per_sec_off": t_off["img_per_sec"],
        "img_per_sec_on": t_on["img_per_sec"],
        "img_per_sec_delta_pct": round(
            100.0 * (t_off["img_per_sec"] - t_on["img_per_sec"])
            / max(1e-9, t_off["img_per_sec"]), 2),
        "p99_ms_off": t_off["p99_ms"],
        "p99_ms_on": t_on["p99_ms"],
        "p99_delta_pct": round(
            100.0 * (t_on["p99_ms"] - t_off["p99_ms"])
            / max(1e-9, t_off["p99_ms"]), 2)}
    return on


def bench_serve_mix(models: tuple = ("lenet5", "yolov3_toy",
                                     "hourglass_toy", "dcgan"),
                    loads: tuple = (8,), duration_s: float = 2.0,
                    max_batch: int = 8, max_wait_ms: float = 2.0,
                    pipeline_depth: int = 2,
                    hbm_budget_mb: float = 0.0,
                    zipf_s: float = 1.1,
                    cascade: str | None = None, **_ignored) -> dict:
    """Mixed-WORKLOAD serving mix (``bench.py --serve-mix``): every
    model in ``models`` deployed behind one control plane
    (serve/models.py) sharing a weight cache, closed-loop clients
    picking a model per request from a Zipf-ish popularity
    distribution (weight ∝ 1/rank^s in list order — the first model
    is the hot one, the tail is the long tail that keeps getting
    evicted).  The default mix spans ALL FOUR workloads — classify
    (lenet5), detect (yolov3_toy), pose (hourglass_toy), generate
    (dcgan) — so the bench exercises the workload adapters' input
    codecs (latent vectors for DCGAN) and fused epilogues
    (serve/workloads.py).  The JSON reports per-model/per-workload
    p50/p95/p99 + img/s per load point, per-engine D2H bytes/batch
    (where generate's on-device uint8 encode shows its 4× output-wire
    win and detect's fused decode ships K boxes instead of the dense
    pyramid), and the weight
    cache's hit rate / eviction / spill counters, so the latency tax
    of serving more models than the HBM budget holds is a tracked
    number, not folklore (docs/SERVING.md "Model lifecycle & weight
    cache", "Workloads").  ``hbm_budget_mb`` is the experiment knob:
    0 = uncapped (baseline), small enough to hold one model =
    worst-case thrash.

    ``cascade='front:big'`` (both names in ``models``) routes the big
    name's Zipf slot through the cascade router (serve/cascade.py):
    its requests land in a dedicated ``cascade`` column of the table —
    NOT under either tier — so per-model client img/s never counts a
    cascaded request twice; the engine-side table still shows each
    tier's own served counts."""
    import sys
    import tempfile
    import threading

    import numpy as np

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state
    from deep_vision_tpu.serve.admission import (AdmissionController,
                                                 Shed)
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.faults import Quarantined
    from deep_vision_tpu.serve.models import (ModelControlPlane,
                                              WeightCache)
    from deep_vision_tpu.serve.registry import (CheckpointServingModel,
                                                ModelRegistry)

    registry = ModelRegistry()
    admissions: dict = {}

    cas_front = cas_big = None
    if cascade:
        cas_front, _, cas_big = str(cascade).partition(":")
        if cas_front not in models or cas_big not in models:
            raise ValueError(
                f"--cascade tiers {cascade!r} must both be in the mix "
                f"{list(models)}")

    def admission_for(name):
        if name not in admissions:
            admissions[name] = AdmissionController(name=name)
        return admissions[name]

    def engine_factory(sm):
        return BatchingEngine(sm, max_batch=max_batch,
                              max_wait_ms=max_wait_ms,
                              pipeline_depth=pipeline_depth,
                              admission=admission_for(sm.name))

    cache = WeightCache(int(float(hbm_budget_mb) * 2**20))
    plane = ModelControlPlane(registry, engine_factory, cache=cache,
                              admission_factory=admission_for)
    imgs = {}
    try:
        for name in models:
            cfg = get_config(name)
            with tempfile.TemporaryDirectory() as td:
                model, state = load_state(
                    cfg, td, log=lambda m: print(m, file=sys.stderr))
            sm = CheckpointServingModel(name, cfg, model, state)
            if name == cas_front:
                sm.cascade_topk = 5  # fuse the confidence epilogue
            plane.deploy(sm)
            # workload-aware input synthesis: the serving input shape
            # may be a latent vector (generate) and the wire dtype is
            # the model's, not assumed float32
            wire = np.dtype(str(sm.wire_dtype))
            rng0 = np.random.RandomState(0)
            if wire.kind in "ui":
                imgs[name] = rng0.randint(
                    0, 256, sm.input_shape).astype(wire)
            else:
                imgs[name] = rng0.randn(*sm.input_shape).astype(wire)
        plane.warmup()  # compiles excluded from every load point
        router = None
        if cascade:
            from deep_vision_tpu.serve.cascade import (CascadeRouter,
                                                       CascadeSpec)
            # small min_sample: the router calibrates organically from
            # its own dual-run sampling during the first load point
            router = CascadeRouter(plane, CascadeSpec(
                cas_front, cas_big, min_sample=30, sample_period=10,
                min_agreement=0.9))
        # the cascade column owns the big name's Zipf slot: a cascaded
        # request is recorded there and ONLY there (never under either
        # tier), so per-model client img/s can't double-count it
        cols = list(models) + (["cascade"] if cascade else [])

        # Zipf-ish popularity: weight ∝ 1/rank^s in `models` order
        weights = [1.0 / (r + 1) ** zipf_s for r in range(len(models))]
        total_w = sum(weights)
        cum, acc = [], 0.0
        for w in weights:
            acc += w / total_w
            cum.append(acc)

        def pick(rng):
            u = rng.random()
            for name, edge in zip(models, cum):
                if u <= edge:
                    return name
            return models[-1]

        points = []
        for clients in loads:
            per_model: dict = {name: [] for name in cols}
            errors = [0]
            retries = [0]
            lock = threading.Lock()
            stop_at = time.perf_counter() + duration_s

            def client(seed):
                # same well-behaved closed-loop client as bench_serve:
                # honor queue-full Retry-After hints with jittered
                # bounded backoff before counting an error
                rng = random.Random(seed)
                local = {name: [] for name in cols}
                local_err, local_retry = 0, 0
                while time.perf_counter() < stop_at:
                    name = pick(rng)
                    col = "cascade" if router is not None \
                        and name == cas_big else name
                    t0 = time.perf_counter()
                    r = None
                    try:
                        for _ in range(3):  # 1 attempt + 2 retries
                            if col == "cascade":
                                r = router.infer(imgs[name],
                                                 timeout=60)[1]
                            else:
                                r = plane.infer(name, imgs[name],
                                                timeout=60)
                            if not (isinstance(r, Shed)
                                    and r.retry_after_s):
                                break
                            local_retry += 1
                            time.sleep(min(r.retry_after_s, 0.25)
                                       * (0.5 + rng.random()))
                        if isinstance(r, (Shed, Quarantined)):
                            local_err += 1
                            continue
                    except Exception:  # noqa: BLE001
                        local_err += 1
                        continue
                    local[col].append(time.perf_counter() - t0)
                with lock:
                    for name in cols:
                        per_model[name].extend(local[name])
                    errors[0] += local_err
                    retries[0] += local_retry

            threads = [threading.Thread(target=client, args=(k,))
                       for k in range(clients)]
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_start
            total = sum(len(v) for v in per_model.values())
            row = {"clients": clients, "requests": total,
                   "errors": errors[0], "retries": retries[0],
                   "img_per_sec": round(total / elapsed, 1),
                   "models": {}}
            for name in cols:
                lat = np.asarray(per_model[name]) * 1e3
                if not len(lat):
                    row["models"][name] = {"requests": 0}
                    continue
                row["models"][name] = {
                    "workload": f"cascade({cascade})"
                    if name == "cascade"
                    else registry.get(name).workload.verb,
                    "requests": int(len(lat)),
                    "share": round(len(lat) / max(1, total), 3),
                    "p50_ms": round(float(np.percentile(lat, 50)), 2),
                    "p95_ms": round(float(np.percentile(lat, 95)), 2),
                    "p99_ms": round(float(np.percentile(lat, 99)), 2)}
            points.append(row)
        stats = plane.stats()
        cas_stats = router.stats() if router is not None else None
    finally:
        plane.stop()
    cstats = stats["cache"]
    lookups = cstats["hits"] + cstats["misses"]
    out = {"metric": "serve_mix_img_per_sec",
           "value": points[-1]["img_per_sec"], "unit": "img/s",
           "models": list(models), "zipf_s": zipf_s,
           "hbm_budget_mb": hbm_budget_mb,
           "max_batch": max_batch, "max_wait_ms": max_wait_ms,
           "pipeline_depth": pipeline_depth,
           "loads": points,
           "cache": {
               "budget_bytes": cstats["budget_bytes"],
               "resident_bytes": cstats["resident_bytes"],
               "hits": cstats["hits"], "misses": cstats["misses"],
               "hit_rate": round(cstats["hits"] / lookups, 3)
               if lookups else None,
               "evictions": cstats["evictions"],
               "admits": cstats["admits"],
               "over_budget": cstats["over_budget"],
               "spilled_mib": round(
                   cstats["spilled_bytes_total"] / 2**20, 3),
               "models": cstats["models"]},
           "plane": stats["plane"],
           "engines": {
               name: {"workload": m["engine"].get("workload"),
                      "batches": m["engine"]["batches"],
                      "compiles": m["engine"]["compiles"],
                      "served": m["engine"]["served"],
                      "admitted": m["engine"]["admission"]["admitted"],
                      # D2H payload of the bulk device_get, per batch
                      # and per served image — generate's fused uint8
                      # epilogue is 4× smaller than an f32 output here
                      "d2h_bytes_per_batch": round(
                          m["engine"]["pipeline"]["d2h_bytes"]
                          / max(1, m["engine"]["batches"]), 1),
                      "d2h_bytes_per_img": round(
                          m["engine"]["pipeline"]["d2h_bytes"]
                          / max(1, m["engine"]["served"]), 1)}
               for name, m in stats["models"].items()},
           "device_kind": jax.devices()[0].device_kind}
    if cas_stats is not None:
        out["cascade"] = {
            "front": cas_stats["front"], "big": cas_stats["big"],
            "threshold": cas_stats["threshold"],
            "calibrated": cas_stats["calibrated"],
            "served": cas_stats["served"],
            "escalations": cas_stats["escalations"],
            "escalation_rate": cas_stats["escalation_rate"],
            "samples": cas_stats["samples"]}
    return out


def bench_serve_cascade(front: str = "lenet5", big: str = "lenet5_big",
                        tiers: tuple | None = None,
                        quant_front: bool = False,
                        loads: tuple = (4, 8), duration_s: float = 2.0,
                        max_batch: int = 8, max_wait_ms: float = 2.0,
                        pipeline_depth: int = 2,
                        min_agreement: float = 0.95,
                        sample_period: int = 10,
                        min_sample: int = 50,
                        train_epochs: int = 2,
                        synthetic_size: int = 1024,
                        holdout: int = 256, **_ignored) -> dict:
    """Confidence-routed cascade A/B (``bench.py --serve-cascade``):
    big-model-only serving vs the cascade router (serve/cascade.py)
    over the same control plane, at matched top-1 quality.

    ``tiers`` names the whole chain (default the 2-tier
    ``front``/``big`` pair; ``--tiers 3`` on the CLI picks
    lenet5_nano:lenet5:lenet5_big) and ``quant_front`` serves tier 0
    int8-resident (``--cascade-quant-front``, synthetic-calibrated PTQ
    — the production boot path).

    Every tier TRAINS first (subprocess ``cli.train --synthetic``, a
    couple of epochs on the blob dataset) — an untrained chain has no
    meaningful agreement structure, so the calibration story would be
    vacuous.  The cascade then calibrates EVERY hop from live dual-run
    samples exactly as in production (no histogram backdoor), a
    labeled held-out set scores top-1 accuracy for big-only vs cascade
    (the matched-quality check), and closed-loop clients sweep
    ``loads`` twice per point — big-only, then cascade — for the
    img/s ratio.  Reports escalation rate, per-hop thresholds,
    per-tier p50/p99, and the accuracy deltas; docs/PERF.md records
    the methodology."""
    import os
    import subprocess
    import sys
    import tempfile
    import threading

    import numpy as np

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.data.synthetic import synthetic_classification
    from deep_vision_tpu.serve.admission import (AdmissionController,
                                                 Shed)
    from deep_vision_tpu.serve.cascade import CascadeRouter, CascadeSpec
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.faults import Quarantined
    from deep_vision_tpu.serve.models import ModelControlPlane
    from deep_vision_tpu.serve.registry import ModelRegistry
    from deep_vision_tpu.serve.workloads import ClassifyWorkload

    top1 = ClassifyWorkload.top1
    if tiers is None:
        tiers = (front, big)
    tiers = tuple(tiers)
    front, big = tiers[0], tiers[-1]
    registry = ModelRegistry()
    admissions: dict = {}

    def admission_for(name):
        if name not in admissions:
            admissions[name] = AdmissionController(name=name)
        return admissions[name]

    def engine_factory(sm):
        return BatchingEngine(sm, max_batch=max_batch,
                              max_wait_ms=max_wait_ms,
                              pipeline_depth=pipeline_depth,
                              admission=admission_for(sm.name))

    plane = ModelControlPlane(registry, engine_factory,
                              admission_factory=admission_for)
    out: dict = {"metric": "serve_cascade_speedup", "unit": "x",
                 "front": front, "big": big, "tiers": list(tiers),
                 "quant_front": bool(quant_front),
                 "train_epochs": train_epochs,
                 "min_agreement": min_agreement,
                 "sample_period": sample_period,
                 "min_sample": min_sample,
                 "max_batch": max_batch, "max_wait_ms": max_wait_ms}
    with tempfile.TemporaryDirectory() as wd:
        for name in tiers:
            t0 = time.perf_counter()
            subprocess.run(
                [sys.executable, "-m", "deep_vision_tpu.cli.train",
                 "-m", name, "--synthetic",
                 "--synthetic-size", str(synthetic_size),
                 "--epochs", str(train_epochs),
                 "--workdir", os.path.join(wd, name)],
                check=True, stdout=subprocess.DEVNULL,
                stderr=subprocess.DEVNULL)
            print(f"[cascade] trained {name} in "
                  f"{time.perf_counter() - t0:.1f}s", file=sys.stderr)
        # float32 wire: the tiers see the exact training distribution
        # (the synthetic blobs are float images, not 0-255 pixels).
        # Non-final tiers carry the fused confidence epilogue; tier 0
        # optionally serves int8-resident (synthetic-calibrated PTQ)
        sms = []
        for i, name in enumerate(tiers):
            sms.append(registry.load_checkpoint(
                name, os.path.join(wd, name),
                cascade_topk=5 if i < len(tiers) - 1 else 0,
                infer_dtype="int8" if quant_front and i == 0
                else "float32"))
        cfg = get_config(big)
        try:
            for sm in sms:
                plane.deploy(sm)
            plane.warmup()
            spec = CascadeSpec(*tiers,
                               min_agreement=min_agreement,
                               sample_period=sample_period,
                               min_sample=min_sample)
            router = CascadeRouter(plane, spec)
            data = synthetic_classification(
                holdout, cfg.image_size, cfg.channels,
                cfg.num_classes, seed=7)
            imgs = [np.ascontiguousarray(x) for x in data["image"]]
            labels = [int(y) for y in data["label"]]

            # -- quality: big-only reference answers ------------------
            big_cls = []
            for x in imgs:
                r = plane.infer(big, x, timeout=120)
                big_cls.append(top1(r)[0])
            big_acc = sum(c == y for c, y in zip(big_cls, labels)) \
                / len(labels)

            # -- calibrate EVERY hop through the REAL sampling path ---
            def uncalibrated():
                return [h.index for h in router.hops
                        if h.threshold is None]

            warm = 0
            cap = 40 * sample_period * min_sample * len(router.hops)
            while uncalibrated() and warm < cap:
                router.infer(imgs[warm % len(imgs)], timeout=120)
                warm += 1
            out["calibrated"] = not uncalibrated()
            out["threshold"] = router.threshold
            out["hop_thresholds"] = [h.threshold for h in router.hops]
            out["warm_requests"] = warm

            # -- quality: cascade answers on the same held-out set ----
            cas_cls, tier_counts = [], {}
            for x in imgs:
                tier, row = router.infer(x, timeout=120)
                tier_counts[tier] = tier_counts.get(tier, 0) + 1
                cas_cls.append(top1(row)[0])
            cas_acc = sum(c == y for c, y in zip(cas_cls, labels)) \
                / len(labels)
            matched = sum(c == b for c, b in zip(cas_cls, big_cls)) \
                / len(big_cls)
            out["quality"] = {
                "holdout": len(imgs),
                "big_top1_acc": round(big_acc, 4),
                "cascade_top1_acc": round(cas_acc, 4),
                "matched_top1": round(matched, 4),
                "holdout_tiers": tier_counts}

            # -- throughput: big-only vs cascade per load point -------
            def sweep(infer_one):
                lat: list = []
                errors = [0]
                lock = threading.Lock()
                stop_at = time.perf_counter() + duration_s

                def client(seed):
                    rng = random.Random(seed)
                    local, errs = [], 0
                    while time.perf_counter() < stop_at:
                        x = imgs[rng.randrange(len(imgs))]
                        t0 = time.perf_counter()
                        try:
                            r = infer_one(x)
                        except Exception:  # noqa: BLE001
                            errs += 1
                            continue
                        if isinstance(r, (Shed, Quarantined)):
                            errs += 1
                            continue
                        local.append(time.perf_counter() - t0)
                    with lock:
                        lat.extend(local)
                        errors[0] += errs
                threads = [threading.Thread(target=client, args=(k,))
                           for k in range(clients)]
                t_start = time.perf_counter()
                for t in threads:
                    t.start()
                for t in threads:
                    t.join()
                elapsed = time.perf_counter() - t_start
                arr = np.asarray(lat) * 1e3
                return {"requests": len(lat), "errors": errors[0],
                        "img_per_sec": round(len(lat) / elapsed, 1),
                        "p50_ms": round(float(np.percentile(arr, 50)), 2)
                        if len(lat) else None,
                        "p99_ms": round(float(np.percentile(arr, 99)), 2)
                        if len(lat) else None}

            points = []
            for clients in loads:
                ref = sweep(lambda x: plane.infer(big, x, timeout=120))
                cas = sweep(
                    lambda x: router.infer(x, timeout=120)[1])
                speedup = cas["img_per_sec"] / ref["img_per_sec"] \
                    if ref["img_per_sec"] else None
                points.append({"clients": clients,
                               "big_only": ref, "cascade": cas,
                               "speedup": round(speedup, 2)
                               if speedup else None})
            rstats = router.stats()
            out.update({
                "value": points[-1]["speedup"],
                "loads": points,
                "cascade": {
                    "threshold": rstats["threshold"],
                    "served": rstats["served"],
                    "escalations": rstats["escalations"],
                    "escalation_rate": rstats["escalation_rate"],
                    "samples": rstats["samples"],
                    "agreement": rstats["agreement"],
                    "hops": [{"hop": h["hop"], "tier": h["tier"],
                              "threshold": h["threshold"],
                              "agreement": h["agreement"],
                              "escalations": h["escalations"]}
                             for h in rstats["hops"]],
                    "latency": rstats["latency"]},
                "device_kind": jax.devices()[0].device_kind})
        finally:
            plane.stop()
    return out


def bench_gateway(model_name: str = "lenet5", loads: tuple = (1, 8),
                  duration_s: float = 2.0, max_batch: int = 8,
                  max_wait_ms: float = 2.0, pipeline_depth: int = 2,
                  backends: int = 2, **_ignored) -> dict:
    """Gateway failover bench (``bench.py --gateway``): N in-process
    backend serve stacks (engine + HTTP front-end each) behind one
    ``serve/gateway.py`` front tier, closed-loop HTTP clients through
    the gateway — then, a third of the way into the TOP load point,
    backend 0 is hard-killed (sockets die mid-flight, the SIGKILL
    shape) while the load keeps running.

    The JSON's ``failover`` block is the methodology output
    (docs/PERF.md "Gateway failover latency"): client-visible errors
    after the kill (the contract says 0 — every admitted request fails
    over), how long until the breaker stopped routing to the corpse,
    and the worst client latency inside the 1 s post-kill window (the
    failover tax: connect-fail detection + jittered backoff + the
    retry on the survivor).  Load points carry ``errors`` and
    ``retries`` like the ``--serve`` bench, plus the gateway's own
    counters (retries, failovers, breaker transitions, hedges)."""
    import http.client
    import sys
    import tempfile
    import threading

    import numpy as np

    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.gateway import Gateway, GatewayServer
    from deep_vision_tpu.serve.http import ServeServer
    from deep_vision_tpu.serve.registry import (CheckpointServingModel,
                                                ModelRegistry)

    cfg = get_config(model_name)
    with tempfile.TemporaryDirectory() as td:
        model, state = load_state(cfg, td,
                                  log=lambda m: print(m, file=sys.stderr))
    sm = CheckpointServingModel(model_name, cfg, model, state)
    registry = ModelRegistry()
    registry.add(sm)
    img = np.random.RandomState(0).randn(
        *sm.input_shape).astype(np.float32)
    body = json.dumps({"pixels": img.tolist()}).encode()
    engines = [BatchingEngine(sm, max_batch=max_batch,
                              max_wait_ms=max_wait_ms,
                              pipeline_depth=pipeline_depth).start()
               for _ in range(backends)]
    for eng in engines:
        eng.warmup()
    servers = [ServeServer(registry, {sm.name: eng},
                           port=0).start_background()
               for eng in engines]
    gw = Gateway([f"127.0.0.1:{s.port}" for s in servers],
                 probe_interval_s=0.05, retry_budget=3,
                 breaker_threshold=2, breaker_cooldown_s=30.0).start()
    gsrv = GatewayServer(gw, port=0).start_background()
    points = []
    failover: dict = {}
    try:
        for li, clients in enumerate(loads):
            kill_point = li == len(loads) - 1  # chaos at the top load
            latencies: list = []
            errors = [0]
            retries = [0]
            lock = threading.Lock()
            t_base = time.perf_counter()
            stop_at = t_base + duration_s
            t_kill = [None]

            def client(seed):
                rng = random.Random(seed)
                local, local_err, local_retry = [], 0, 0
                # ONE persistent keep-alive connection per worker (it
                # reconnects lazily after close()): the bench pays the
                # TCP handshake once, not once per request, matching
                # how production clients drive the edge
                conn = http.client.HTTPConnection(
                    "127.0.0.1", gsrv.port, timeout=60)
                while time.perf_counter() < stop_at:
                    t0 = time.perf_counter()
                    try:
                        for _ in range(3):
                            try:
                                conn.request(
                                    "POST", "/v1/classify", body,
                                    {"Content-Type":
                                     "application/json"})
                                r = conn.getresponse()
                                r.read()
                            except (OSError,
                                    http.client.HTTPException):
                                conn.close()  # stale conn: redial
                                raise
                            if r.will_close:
                                conn.close()
                            if r.status == 200:
                                break
                            if r.status != 429:
                                raise RuntimeError(f"HTTP {r.status}")
                            # cooperative retry budget: the gateway
                            # reports its remaining per-backend retry
                            # tokens on every response — when IT is out
                            # of budget, the client stops adding its
                            # own retries on top, so the two layers
                            # never jointly multiply offered load
                            # (docs/SERVING.md "Retry budgets")
                            budget = r.headers.get("X-DVT-Retry-Budget")
                            if budget is not None \
                                    and float(budget) < 1.0:
                                raise RuntimeError(
                                    "429 with retry budget exhausted")
                            local_retry += 1
                            ra = float(r.headers.get(
                                "Retry-After") or 1)
                            time.sleep(min(ra, 0.25)
                                       * (0.5 + rng.random()))
                        else:
                            local_err += 1
                            continue
                    except Exception:  # noqa: BLE001 — failover misses
                        local_err += 1
                        continue
                    local.append((t0 - t_base,
                                  time.perf_counter() - t0))
                conn.close()
                with lock:
                    latencies.extend(local)
                    errors[0] += local_err
                    retries[0] += local_retry

            def killer():
                time.sleep(duration_s / 3)
                t_kill[0] = time.perf_counter() - t_base
                servers[0].httpd.shutdown()
                servers[0].httpd.server_close()
                engines[0].stop(timeout=1)
                # breaker-open latency: poll until routing excludes it
                t0 = time.perf_counter()
                while gw.backends[0].routable() \
                        and time.perf_counter() - t0 < 5:
                    time.sleep(0.002)
                failover["breaker_open_ms"] = round(
                    (time.perf_counter() - t0) * 1e3, 1)

            threads = [threading.Thread(target=client, args=(k,))
                       for k in range(clients)]
            if kill_point:
                threads.append(threading.Thread(target=killer))
            t_start = time.perf_counter()
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            elapsed = time.perf_counter() - t_start
            lat_ms = np.asarray([x[1] for x in latencies]) * 1e3
            points.append({
                "clients": clients, "requests": len(latencies),
                "errors": errors[0], "retries": retries[0],
                "img_per_sec": round(len(latencies) / elapsed, 1),
                "p50_ms": round(float(np.percentile(lat_ms, 50)), 2),
                "p95_ms": round(float(np.percentile(lat_ms, 95)), 2),
                "p99_ms": round(float(np.percentile(lat_ms, 99)), 2)})
            if kill_point and t_kill[0] is not None:
                after = [x for x in latencies if x[0] >= t_kill[0]]
                window = [x[1] * 1e3 for x in after
                          if x[0] < t_kill[0] + 1.0]
                failover.update({
                    "kill_at_s": round(t_kill[0], 3),
                    "requests_after_kill": len(after),
                    "errors_after_kill": errors[0],
                    "max_ms_in_1s_window": round(max(window), 2)
                    if window else None})
        counters = gw.counters()
        reports = {b.name: b.report() for b in gw.backends}
    finally:
        gsrv.shutdown()
        gw.stop()
        for srv in servers[1:]:
            srv.shutdown()
        for eng in engines[1:]:
            eng.stop()
    return {"metric": f"gateway_{model_name}_img_per_sec",
            "value": points[-1]["img_per_sec"], "unit": "img/s",
            "model": model_name, "backends": backends,
            "max_batch": max_batch, "max_wait_ms": max_wait_ms,
            "pipeline_depth": pipeline_depth,
            "loads": points, "failover": failover,
            "gateway": counters, "backend_reports": reports,
            "device_kind": jax.devices()[0].device_kind}


def _serve_stack(model_name: str, max_batch: int, max_wait_ms: float,
                 pipeline_depth: int):
    """One warmed engine + registry for the HTTP edge benches — built
    once and shared across server variants so the A/B isolates the
    front-end, not the compile."""
    import contextlib
    import sys
    import tempfile

    import numpy as np

    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.registry import ModelRegistry

    registry = ModelRegistry()
    with tempfile.TemporaryDirectory() as td, \
            contextlib.redirect_stdout(sys.stderr):
        # load_checkpoint (not bare load_state): it stamps
        # params_digest, without which the response cache has no
        # version identity and stays silently cold.  Its random-init
        # warning prints to stdout, which must stay JSON-only here.
        sm = registry.load_checkpoint(model_name, td)
    img = np.random.RandomState(0).randn(
        *sm.input_shape).astype(np.float32)
    body = json.dumps({"pixels": img.tolist()}).encode()
    eng = BatchingEngine(sm, max_batch=max_batch,
                         max_wait_ms=max_wait_ms,
                         pipeline_depth=pipeline_depth).start()
    eng.warmup()
    return registry, sm, eng, body


def bench_serve_edge(model_name: str = "lenet5",
                     loads: tuple = (4, 16, 32),
                     duration_s: float = 2.0, max_batch: int = 8,
                     max_wait_ms: float = 2.0,
                     pipeline_depth: int = 2, **_ignored) -> dict:
    """Edge A/B (``bench.py --serve-edge``): the selector event loop
    vs the thread-per-request baseline, same engine, real HTTP.

    For each front-end, C closed-loop clients with persistent
    keep-alive connections sweep the load points (p50/p99, img/s), and
    a single-threaded churn probe measures requests/s with a FRESH
    connection per request vs reusing one — the per-connection tax
    (accept + thread spawn on the baseline; accept only on the edge).
    The methodology claim (docs/PERF.md): the edge sustains the top
    load point at equal-or-better p99 without spawning a thread per
    connection, and its churn overhead is the smaller delta."""
    import http.client
    import threading

    import numpy as np

    from deep_vision_tpu.serve.http import ServeServer

    registry, sm, eng, body = _serve_stack(
        model_name, max_batch, max_wait_ms, pipeline_depth)
    variants = []
    try:
        for edge in (True, False):
            srv = ServeServer(registry, {sm.name: eng},
                              port=0, edge=edge).start_background()
            points = []
            try:
                for clients in loads:
                    latencies: list = []
                    errors = [0]
                    lock = threading.Lock()
                    stop_at = time.perf_counter() + duration_s

                    def client():
                        conn = http.client.HTTPConnection(
                            "127.0.0.1", srv.port, timeout=60)
                        local, local_err = [], 0
                        while time.perf_counter() < stop_at:
                            t0 = time.perf_counter()
                            try:
                                conn.request(
                                    "POST", "/v1/classify", body,
                                    {"Content-Type":
                                     "application/json"})
                                r = conn.getresponse()
                                r.read()
                                if r.will_close:
                                    conn.close()
                                if r.status != 200:
                                    local_err += 1
                                    continue
                            except (OSError,
                                    http.client.HTTPException):
                                conn.close()
                                local_err += 1
                                continue
                            local.append(time.perf_counter() - t0)
                        conn.close()
                        with lock:
                            latencies.extend(local)
                            errors[0] += local_err

                    threads = [threading.Thread(target=client)
                               for _ in range(clients)]
                    t_start = time.perf_counter()
                    for t in threads:
                        t.start()
                    for t in threads:
                        t.join()
                    elapsed = time.perf_counter() - t_start
                    lat = np.asarray(latencies) * 1e3
                    points.append({
                        "clients": clients,
                        "requests": len(latencies),
                        "errors": errors[0],
                        "img_per_sec": round(len(lat) / elapsed, 1),
                        "p50_ms": round(float(np.percentile(lat, 50)),
                                        2),
                        "p99_ms": round(float(np.percentile(lat, 99)),
                                        2)})
                # churn probe: sequential healthz, fresh vs reused conn
                churn = {}
                for mode in ("fresh", "reused"):
                    conn = None
                    n = 0
                    t0 = time.perf_counter()
                    while time.perf_counter() - t0 < min(duration_s,
                                                         1.0):
                        if conn is None or mode == "fresh":
                            if conn is not None:
                                conn.close()
                            conn = http.client.HTTPConnection(
                                "127.0.0.1", srv.port, timeout=10)
                        conn.request("GET", "/v1/healthz")
                        conn.getresponse().read()
                        n += 1
                    conn.close()
                    churn[f"{mode}_req_per_sec"] = round(
                        n / (time.perf_counter() - t0), 1)
                churn["overhead_pct"] = round(
                    (1 - churn["fresh_req_per_sec"]
                     / churn["reused_req_per_sec"]) * 100, 1)
                edge_stats = srv.httpd.stats() if edge else None
            finally:
                srv.shutdown()
            variants.append({
                "front_end": "edge" if edge else "thread",
                "loads": points, "churn": churn, "edge": edge_stats})
    finally:
        eng.stop()
    top = {v["front_end"]: v["loads"][-1] for v in variants}
    return {"metric": f"serve_edge_{model_name}_img_per_sec",
            "value": top["edge"]["img_per_sec"], "unit": "img/s",
            "model": model_name, "max_batch": max_batch,
            "max_wait_ms": max_wait_ms, "variants": variants,
            "top_load": top,
            "device_kind": jax.devices()[0].device_kind}


def bench_serve_trace(model_name: str = "lenet5",
                      duration_s: float = 4.0, rate: float = 60.0,
                      dup_frac: float = 0.4, max_batch: int = 8,
                      max_wait_ms: float = 2.0,
                      pipeline_depth: int = 2,
                      cache_mb: float = 64.0, **_ignored) -> dict:
    """Trace-driven OPEN-LOOP bench (``bench.py --serve-trace``):
    requests arrive on a generated schedule whether or not earlier ones
    finished — a diurnal sine envelope over the base ``rate`` with a 4×
    burst in the middle third, Poisson inter-arrivals throughout.

    ``dup_frac`` of arrivals draw from a small hot payload pool (the
    content-addressed cache's hit source, ≥30% per the methodology);
    the rest are unique.  Tenants split premium/standard/best_effort
    (2:6:2) through ``X-DVT-Tenant`` against a QoS spec whose
    best-effort knee is lowest.  Latency is measured from SCHEDULED
    arrival (queueing delay included — the open-loop honesty), per
    class.  The JSON carries per-class p50/p99 + sheds, the server's
    cache hit rate, and the edge's connection counters (accepted vs
    keep-alive reuses = churn avoided)."""
    import http.client
    import math
    import threading

    import numpy as np

    from deep_vision_tpu.serve.admission import TENANT_HEADER, TenantQoS
    from deep_vision_tpu.serve.cache import ResponseCache
    from deep_vision_tpu.serve.http import ServeServer

    registry, sm, eng, _ = _serve_stack(
        model_name, max_batch, max_wait_ms, pipeline_depth)
    qos = TenantQoS.parse(
        "premium:rate=0,shed_at=1.0,tenants=tenant-p;"
        "standard:rate=0,shed_at=0.85;"
        "best_effort:rate=0,shed_at=0.6,tenants=tenant-b;"
        "default=standard")
    srv = ServeServer(
        registry, {sm.name: eng}, port=0,
        response_cache=ResponseCache(int(cache_mb * 2**20)),
        qos=qos).start_background()

    rng = random.Random(0)
    n_hot = 4  # hot payload pool: what the response cache can reuse
    pool = []
    for i in range(n_hot + 1):
        img = np.random.RandomState(i).randn(
            *sm.input_shape).astype(np.float32)
        pool.append(json.dumps({"pixels": img.tolist()}).encode())
    unique_base = np.random.RandomState(99).randn(
        *sm.input_shape).astype(np.float32)

    # arrival schedule: diurnal sine envelope + midday burst, Poisson
    arrivals = []
    t = 0.0
    while t < duration_s:
        envelope = 0.55 + 0.45 * math.sin(
            2 * math.pi * t / duration_s - math.pi / 2)
        r = rate * envelope
        if duration_s / 3 <= t < duration_s * 2 / 3:
            r *= 4.0  # the burst window
        t += rng.expovariate(max(r, 1e-3))
        if t >= duration_s:
            break
        tenant = rng.choices(
            ["tenant-p", "tenant-s", "tenant-b"],
            weights=(2, 6, 2))[0]
        if rng.random() < dup_frac:
            body = pool[rng.randrange(n_hot)]
        else:
            # unique payload: mutate one pixel deterministically
            u = unique_base.copy()
            u.flat[len(arrivals) % u.size] += len(arrivals) + 1
            body = json.dumps({"pixels": u.tolist()}).encode()
        arrivals.append((t, tenant, body))

    results: dict = {c: {"lat": [], "shed": 0, "errors": 0}
                     for c in ("premium", "standard", "best_effort")}
    cls_of = {"tenant-p": "premium", "tenant-s": "standard",
              "tenant-b": "best_effort"}
    lock = threading.Lock()
    conns = threading.local()

    # service latency (send → response, excluding open-loop queueing)
    # split by the X-DVT-Cache header: the hit-vs-compute comparison
    hit_svc: list = []
    miss_svc: list = []

    def fire(t_sched, tenant, body, t_base):
        try:
            conn = getattr(conns, "c", None)
            if conn is None:
                conn = conns.c = http.client.HTTPConnection(
                    "127.0.0.1", srv.port, timeout=60)
            t_send = time.perf_counter()
            try:
                conn.request("POST", "/v1/classify", body,
                             {"Content-Type": "application/json",
                              TENANT_HEADER: tenant})
                r = conn.getresponse()
                r.read()
                if r.will_close:
                    conn.close()
                    conns.c = None
            except (OSError, http.client.HTTPException):
                conn.close()
                conns.c = None
                raise
            done = time.perf_counter()
            with lock:
                row = results[cls_of[tenant]]
                if r.status == 200:
                    row["lat"].append(done - t_base - t_sched)
                    if r.headers.get("X-DVT-Cache") == "hit":
                        hit_svc.append(done - t_send)
                    else:
                        miss_svc.append(done - t_send)
                elif r.status == 429:
                    row["shed"] += 1
                else:
                    row["errors"] += 1
        except Exception:  # noqa: BLE001 — open loop: count, continue
            with lock:
                results[cls_of[tenant]]["errors"] += 1

    from concurrent.futures import ThreadPoolExecutor

    futures = []
    try:
        with ThreadPoolExecutor(max_workers=64) as pool_exec:
            t_base = time.perf_counter()
            for t_sched, tenant, body in arrivals:
                delay = t_sched - (time.perf_counter() - t_base)
                if delay > 0:
                    time.sleep(delay)
                futures.append(pool_exec.submit(
                    fire, t_sched, tenant, body, t_base))
            for f in futures:
                f.result()
        import urllib.request
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/v1/stats",
                timeout=10) as r:
            stats = json.loads(r.read())
    finally:
        srv.shutdown()
        eng.stop()
    classes = {}
    for name, row in results.items():
        lat = np.asarray(row["lat"]) * 1e3
        classes[name] = {
            "served": len(lat), "shed": row["shed"],
            "errors": row["errors"],
            "p50_ms": round(float(np.percentile(lat, 50)), 2)
            if len(lat) else None,
            "p99_ms": round(float(np.percentile(lat, 99)), 2)
            if len(lat) else None}
    edge_stats = stats.get("edge", {})
    cache_stats = stats.get("response_cache", {})

    def _svc(vals):
        a = np.asarray(vals) * 1e3
        return {"count": len(a),
                "p50_ms": round(float(np.percentile(a, 50)), 2)
                if len(a) else None,
                "p99_ms": round(float(np.percentile(a, 99)), 2)
                if len(a) else None}

    return {"metric": f"serve_trace_{model_name}_cache_hit_rate",
            "value": round(cache_stats.get("hit_rate", 0.0), 3),
            "unit": "hit_rate", "model": model_name,
            "offered": len(arrivals), "rate": rate,
            "dup_frac": dup_frac, "duration_s": duration_s,
            "classes": classes,
            "service": {"cache_hit": _svc(hit_svc),
                        "compute": _svc(miss_svc)},
            "cache": cache_stats,
            "edge": {k: edge_stats.get(k) for k in
                     ("accepted", "keepalive_reuses", "requests",
                      "open_connections")},
            "qos": stats.get("qos", {}),
            "device_kind": jax.devices()[0].device_kind}


def bench_deploy(model_name: str = "lenet5",
                 watch_interval_s: float = 0.05, **_ignored) -> dict:
    """Continuous-deploy reaction bench (``bench.py --deploy``).

    Two numbers, both end to end (docs/PERF.md "Deploy reaction"):

    ``deploy_reaction_ms``  a REAL async-Orbax checkpoint becomes
        durable mid-load → the new version is ACTIVE and serving: the
        watcher's two-poll debounce, the candidate restore, the
        synthetic accuracy-gate eval, and the shadow/canary/promote
        rollout under a live closed-loop client (the canary gates need
        traffic to clear).  The structural floor is 2× the watch
        interval (debounce) plus the canary dwell.  The ledger's
        wall-clock timestamps decompose the total.

    ``scale_up_reaction_ms`` / ``scale_down_reaction_ms``  sustained
        queue pressure → ``add_replica()`` returned, and first
        observed idle → ``remove_replica()`` drained and returned.
        The autoscaler is driven synchronously (``tick()`` per
        interval, the documented bench seam) so the numbers measure
        the hysteresis windows + the engine's replica build/drain
        cost, not a daemon thread's scheduling jitter."""
    import os
    import sys
    import tempfile
    import threading

    import numpy as np

    from deep_vision_tpu.core.checkpoint import Checkpointer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.core.restore import load_state
    from deep_vision_tpu.deploy import (AccuracyGate, CheckpointWatcher,
                                        DeploymentHistory,
                                        ReplicaAutoscaler)
    from deep_vision_tpu.serve.admission import Shed
    from deep_vision_tpu.serve.engine import BatchingEngine
    from deep_vision_tpu.serve.models import (CanaryPolicy,
                                              ModelControlPlane,
                                              WeightCache)
    from deep_vision_tpu.serve.registry import ModelRegistry
    from deep_vision_tpu.serve.replicas import ReplicatedEngine

    out: dict = {"metric": "deploy_reaction_ms", "unit": "ms",
                 "model": model_name,
                 "watch_interval_s": watch_interval_s,
                 "debounce_floor_ms": round(2 * watch_interval_s * 1e3,
                                            1),
                 "device_kind": jax.devices()[0].device_kind}

    # -- part 1: checkpoint durable → new version ACTIVE ---------------
    reg = ModelRegistry()
    with tempfile.TemporaryDirectory() as workdir:
        sm = reg.load_checkpoint(model_name, workdir)
        plane = ModelControlPlane(
            reg, lambda m: BatchingEngine(m, buckets=[8], max_wait_ms=2),
            cache=WeightCache(budget_bytes=0),
            policy=CanaryPolicy(canary_frac=0.5, min_requests=3,
                                max_p99_ratio=None, phase_timeout_s=60.0))
        plane.deploy(sm, workdir=workdir)
        history = DeploymentHistory()
        watcher = CheckpointWatcher(
            plane, history, interval_s=watch_interval_s,
            gate=AccuracyGate()).watch(model_name)
        img = np.random.RandomState(0).randn(
            *sm.input_shape).astype(np.float32)
        errors: list = []
        stop = threading.Event()

        def load_loop():
            while not stop.is_set():
                try:
                    r = plane.infer(model_name, img, timeout=30)
                    if isinstance(r, Shed):
                        errors.append(repr(r))
                except Exception as e:  # noqa: BLE001 — every failure is a lost request
                    errors.append(repr(e))

        client = threading.Thread(target=load_loop, daemon=True)
        client.start()
        ckpt = None
        try:
            # warm the infer path before the clock starts
            time.sleep(0.2)
            cfg = get_config(model_name)
            with tempfile.TemporaryDirectory() as seed_dir:
                _, state = load_state(cfg, seed_dir,
                                      log=lambda *a, **k: None)
            ckpt = Checkpointer(os.path.join(workdir, "checkpoints"))
            watcher.start()
            ckpt.save(1, state)
            ckpt.wait_until_finished()
            t0 = time.perf_counter()
            deadline = t0 + 120.0
            while plane.active_version(model_name).version < 2:
                if time.perf_counter() > deadline:
                    raise SystemExit("deploy bench: promotion timed out")
                time.sleep(0.002)
            out["value"] = round((time.perf_counter() - t0) * 1e3, 1)
            out["deploy_reaction_ms"] = out["value"]
            # the ledger's wall-clock stamps decompose the reaction:
            # durable→candidate is debounce+restore, candidate→
            # gate_passed the held-out eval, gate_passed→promoted the
            # shadow/canary rollout.  The promoted record lands just
            # after the version flips, so give it a beat
            t_led = time.perf_counter() + 5.0
            while history.last_outcome(model_name) != "promoted" \
                    and time.perf_counter() < t_led:
                time.sleep(0.002)
            ts = {e["outcome"]: e["ts"]
                  for e in history.entries(model_name)}
            if {"candidate", "gate_passed", "promoted"} <= ts.keys():
                out["gate_eval_ms"] = round(
                    (ts["gate_passed"] - ts["candidate"]) * 1e3, 1)
                out["rollout_ms"] = round(
                    (ts["promoted"] - ts["gate_passed"]) * 1e3, 1)
        finally:
            stop.set()
            client.join(30)
            watcher.stop()
            if ckpt is not None:
                ckpt.close()
            plane.stop(drain_deadline=5.0)
        if errors:
            print(f"# deploy bench: {len(errors)} client errors: "
                  f"{errors[:3]}", file=sys.stderr)
        out["client_errors"] = len(errors)

    # -- part 2: load step → replica added, idle → replica drained -----
    if len(jax.devices()) < 2:
        # add_replica() needs a spare device; main() forces 2 host
        # devices, so this only trips when the backend initialized
        # before the flag could land
        out["autoscale_skipped"] = \
            f"{len(jax.devices())} device(s): add_replica needs a spare"
        return out
    # fresh model: part 1's v1 weights were freed when the promoted v2
    # retired it (the plane reclaims retired versions' HBM)
    with tempfile.TemporaryDirectory() as td:
        sm = ModelRegistry().load_checkpoint(model_name, td)
    tick_s = 0.02
    scaler_cfg = dict(min_replicas=1, max_replicas=2, interval_s=tick_s,
                      high_water_ms=5.0, up_window=3, down_window=10,
                      cooldown_s=0.2, drain_deadline_s=10.0)
    eng = ReplicatedEngine(sm, devices=jax.devices()[:1], buckets=[8],
                           max_wait_ms=2).start()
    eng.warmup()
    scaler = ReplicaAutoscaler(eng, name=model_name, **scaler_cfg)
    futures: list = []
    feeding = threading.Event()
    feeding.set()

    def feeder():
        # keep a standing backlog so pressure survives the ticks — the
        # bench measures the scaler's reaction, not a burst's drain
        while feeding.is_set():
            if eng._queue.qsize() < 32:
                try:
                    futures.append(eng.submit(img))
                except Exception:  # noqa: BLE001 — shed under pressure is expected here
                    pass
            else:
                time.sleep(0.001)

    feed = threading.Thread(target=feeder, daemon=True)
    feed.start()
    try:
        t_load = time.perf_counter()
        deadline = t_load + 60.0
        action = None
        while action is None or action["action"] != "scale_up":
            if time.perf_counter() > deadline:
                raise SystemExit("deploy bench: scale-up timed out")
            action = scaler.tick()
            time.sleep(tick_s)
        out["scale_up_reaction_ms"] = round(
            (time.perf_counter() - t_load) * 1e3, 1)
        out["scale_up_floor_ms"] = round(
            scaler_cfg["up_window"] * tick_s * 1e3, 1)
        feeding.clear()
        feed.join(10)
        for f in futures:
            f.result(timeout=30)
        while eng._queue.qsize() or eng.total_inflight():
            time.sleep(0.002)
        t_idle = time.perf_counter()
        deadline = t_idle + 60.0
        action = None
        while action is None or action["action"] != "scale_down":
            if time.perf_counter() > deadline:
                raise SystemExit("deploy bench: scale-down timed out")
            action = scaler.tick()
            time.sleep(tick_s)
        out["scale_down_reaction_ms"] = round(
            (time.perf_counter() - t_idle) * 1e3, 1)
        # the cooldown usually elapses during the drain, so the
        # structural floor is the hysteresis window alone
        out["scale_down_floor_ms"] = round(
            scaler_cfg["down_window"] * tick_s * 1e3, 1)
        out["autoscaler"] = {k: scaler_cfg[k] for k
                             in ("up_window", "down_window",
                                 "cooldown_s", "high_water_ms")}
        out["autoscaler"]["tick_s"] = tick_s
        out["scale_requests"] = len(futures)
    finally:
        feeding.clear()
        eng.stop()
    return out


def bench_all() -> list[dict]:
    """Run every task bench in its own subprocess (fresh process ⇒
    per-model peak-HBM stats and no cross-compile interference)."""
    import subprocess
    import sys

    results, failed = [], []
    for task in ("resnet50", "yolo", "centernet", "hourglass", "cyclegan",
                 "dcgan", "infer:resnet50", "infer:yolo"):
        if task == "resnet50":
            extra = []
        elif task.startswith("infer:"):
            extra = ["--infer", task.split(":", 1)[1]]
        else:
            extra = ["--task", task]
        cmd = [sys.executable, __file__] + extra
        proc = subprocess.run(cmd, capture_output=True, text=True)
        line = next((ln for ln in reversed(proc.stdout.splitlines())
                     if ln.startswith("{")), None)
        if line is None:
            failed.append(task)
            print(f"# {task} FAILED:\n{proc.stderr[-2000:]}", flush=True)
            continue
        results.append(json.loads(line))
        print(line, flush=True)
    if failed:
        raise SystemExit(f"task benches failed: {', '.join(failed)}")
    return results


def _make_synthetic_imagenet(tmp: str, n_images: int, jpeg_size: int,
                             val_images: int = 0) -> tuple[str, str, str]:
    """Synthetic flat-ImageNet tree shared by the pipeline/coupled benches:
    8 synsets, 8 distinct base images saved as JPEGs, labels.txt.
    Returns (train_dir, labels_path, val_dir_or_empty)."""
    import os

    import numpy as np
    from PIL import Image

    root = os.path.join(tmp, "train")
    os.makedirs(root)
    rng = np.random.default_rng(0)
    synsets = [f"n{i:08d}" for i in range(8)]
    labels = os.path.join(tmp, "labels.txt")
    with open(labels, "w") as f:
        for sn in synsets:
            f.write(f"{sn} synthetic\n")
    base = rng.integers(0, 255, (8, jpeg_size, jpeg_size, 3), dtype=np.uint8)
    for i in range(n_images):
        Image.fromarray(base[i % 8]).save(
            os.path.join(root, f"{synsets[i % 8]}_{i}.JPEG"), quality=85)
    val_root = ""
    if val_images:
        val_root = os.path.join(tmp, "val")
        os.makedirs(val_root)
        for i in range(val_images):
            Image.fromarray(base[i % 8]).save(
                os.path.join(val_root, f"{synsets[i % 8]}_{i}.JPEG"),
                quality=85)
    return root, labels, val_root


def bench_coupled(batch: int = 256, epochs: int = 13,
                  n_images: int = 10240, image_size: int = 224) -> dict:
    """The COUPLED end-to-end number (VERDICT r3 #2): a real ``cli.train``
    run — raw-store dvrec records → host batch assembly → H2D prefetch →
    scan-dispatched train steps → logging → per-epoch eval + checkpoint —
    not a decoupled step bench.  Sustained rate = images trained in
    epochs 2..N over the wall time from epoch 2's first log record to the
    run's last record (epoch 1 absorbs compiles; with one scan group per
    epoch the first post-epoch-1 record lands at epoch 2's END, so the
    window covers epochs 3..N), INCLUDING eval and checkpoint pauses.

    Defaults: 10,240 synthetic 400² JPEGs packed once with
    ``prepare_data imagenet --store raw`` (40 steps/epoch = one
    scan_steps=40 group), EMA on — the production recipe shape.
    """
    import os
    import shutil
    import tempfile

    tmp = tempfile.mkdtemp(prefix="bench_coupled_")
    try:
        root, labels, val_root = _make_synthetic_imagenet(
            tmp, n_images, 400, val_images=1024)

        from deep_vision_tpu.data.prep import prepare_imagenet
        from deep_vision_tpu.data.transforms import imagenet_resize_for

        recs = os.path.join(tmp, "recs")
        for split, src in (("train", root), ("val", val_root)):
            prepare_imagenet(src, labels, recs,
                             split=split, num_shards=8, num_workers=1,
                             store="raw",
                             resize=imagenet_resize_for(image_size))
        shutil.rmtree(root)
        shutil.rmtree(val_root)

        from deep_vision_tpu.cli.train import main as train_main

        workdir = os.path.join(tmp, "run")
        rc = train_main([
            "-m", "resnet50", "--data-root", recs, "--data-format",
            "records", "--epochs", str(epochs), "--batch-size", str(batch),
            "--image-size", str(image_size),
            "--scan-steps", "40", "--ema-decay", "0.9999",
            "--num-workers", "0", "--workdir", workdir])
        assert rc == 0, f"cli.train failed rc={rc}"

        # parse metrics.jsonl: epoch-1 records absorb compiles; measure
        # from the FIRST record whose step falls in epoch 2 to the last
        # record of the run (includes evals, checkpoints, logging)
        recs_log = []
        with open(os.path.join(workdir, "metrics.jsonl")) as f:
            recs_log = [json.loads(ln) for ln in f if ln.strip()]
        steps_per_epoch = n_images // batch
        first = min((r for r in recs_log if r["step"] > steps_per_epoch),
                    key=lambda r: r["time"])
        t_end = max(r["time"] for r in recs_log)
        last_step = max(r["step"] for r in recs_log)
        # scan-mode logs land at each group's END, so the first record
        # past epoch 1 already includes its own steps' wall time — count
        # images only from that record's step to keep window and
        # numerator aligned
        images = (last_step - first["step"]) * batch
        rate = images / (t_end - first["time"])
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "metric": "resnet50_coupled_train_images_per_sec",
        "value": round(rate, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(rate / BASELINE_IMG_PER_SEC_PER_CHIP, 2),
        "epochs_measured": (last_step - first["step"]) // steps_per_epoch,
        "steps_measured": last_step - first["step"],
        "batch": batch,
        "image_size": image_size,
        "ema_decay": 0.9999,
        "scan_steps": 40,
        "includes": "host pipeline + prefetch + logging + eval + checkpoint",
    }


def bench_cyclegan_live(steps: int = 20, size: int = 256,
                        batch: int = 1) -> dict:
    """LIVE CycleGAN rate: real ``AdversarialTrainer`` steps INCLUDING
    the per-step host ImagePool exchange (host_prepare → jitted 4-network
    step → host_update fetch of both fake batches), which the pure step
    bench excludes — replaces PERF.md's "a live run is somewhat slower
    still" caveat with a number (VERDICT r3 #6b)."""
    import numpy as np

    from deep_vision_tpu.core.adversarial import AdversarialTrainer
    from deep_vision_tpu.core.config import get_config
    from deep_vision_tpu.data.gan import UnpairedLoader, synthetic_unpaired
    from deep_vision_tpu.models.gan import (
        CycleGANGenerator,
        PatchGANDiscriminator,
    )
    from deep_vision_tpu.parallel import make_mesh
    from deep_vision_tpu.tasks.gan import CycleGANTask

    cfg = get_config("cyclegan")
    cfg.batch_size = batch
    cfg.image_size = size
    a, b = synthetic_unpaired(max(4 * batch, 8), size)
    loader = UnpairedLoader(a, b, batch, seed=0)
    # bf16 like the step bench (bench_task "cyclegan"), so live-vs-step
    # deltas isolate the host exchange, not a dtype change
    task = CycleGANTask(lambda: CycleGANGenerator(dtype=jnp.bfloat16),
                        lambda: PatchGANDiscriminator(dtype=jnp.bfloat16))
    mesh = make_mesh({"data": 1}, devices=jax.devices()[:1])
    import tempfile

    with tempfile.TemporaryDirectory() as wd:
        trainer = AdversarialTrainer(cfg, task, mesh=mesh, workdir=wd)
        rng = jax.random.PRNGKey(0)
        states = trainer.init_states(next(iter(loader)))
        batches = []
        it = iter(loader)
        while len(batches) < steps + 3:
            try:
                batches.append(next(it))
            except StopIteration:
                it = iter(loader)

        def one(states, rng, batch):
            rng, step_rng = jax.random.split(rng)
            batch = task.host_prepare(batch)
            states, outputs, metrics = trainer.train_step(
                states, batch, step_rng)
            task.host_update(outputs)  # device_get of both fake batches
            return states, rng, metrics

        for warm in batches[:3]:  # compile + pool warm
            states, rng, m = one(states, rng, warm)
        float(jax.device_get(m["g_loss"]))
        t0 = time.perf_counter()
        for bt in batches[3:3 + steps]:
            states, rng, m = one(states, rng, bt)
        float(jax.device_get(m["g_loss"]))
        dt = time.perf_counter() - t0
    rate = steps * batch / dt
    return {
        "metric": "cyclegan_live_images_per_sec",
        "value": round(rate, 2),
        "unit": "images/sec/chip",
        "vs_baseline": 0.0,
        "steps": steps,
        "batch": batch,
        "image_size": size,
        "ms_per_step": round(1000 * dt / steps, 1),
        "includes": "host ImagePool exchange (host_prepare/host_update)",
    }


def bench_recipe(batch: int | None = None, steps: int | None = None):
    """Recipe-overhead rows at the ResNet-50 shape: what EMA and
    gradient accumulation actually COST (VERDICT r3 #3) — one fresh
    process per combo so compile caches don't cross-talk."""
    import subprocess
    import sys

    combos = [[],
              ["--ema-decay", "0.9999"],
              ["--grad-accum", "2"],
              ["--grad-accum", "4"],
              ["--ema-decay", "0.9999", "--grad-accum", "2"]]
    common = []
    if batch:
        common += ["--batch", str(batch)]
    if steps:
        common += ["--steps", str(steps)]
    failed = []
    for extra in combos:
        cmd = [sys.executable, __file__] + common + extra
        proc = subprocess.run(cmd, capture_output=True, text=True)
        line = next((ln for ln in reversed(proc.stdout.splitlines())
                     if ln.startswith("{")), None)
        if line is None:
            failed.append(" ".join(extra) or "base")
            print(f"# {extra or 'base'} FAILED:\n{proc.stderr[-2000:]}",
                  flush=True)
            continue
        print(line, flush=True)
    if failed:
        raise SystemExit(f"recipe benches failed: {', '.join(failed)}")


def bench_pipeline(num_workers: int = 16, batch: int = 256,
                   n_images: int = 4096, jpeg_size: int = 400,
                   image_size: int = 224,
                   device_normalize: bool = True,
                   source: str = "raw") -> dict:
    """Host input-pipeline throughput: synthetic images on disk through the
    REAL ImageNetLoader (read + [decode] + augment + batch assembly), no
    device work.

    SURVEY §7 hard-part #1: this number must meet or beat the chip's
    train-step rate or the chip starves.  ``source`` picks the storage:

    - ``raw``     train-ready uint8 dvrec shards (``prepare_data imagenet
                  --store raw``) — decode-free reads, the production path
                  for 1-core TPU-VM hosts;
    - ``records`` sanitized-JPEG dvrec shards (archival format);
    - ``folder``  flat JPEG dir (the reference's torch-loader layout).
    """
    import os
    import shutil
    import tempfile

    from deep_vision_tpu.data.imagenet import ImageNetLoader

    tmp = tempfile.mkdtemp(prefix="bench_pipeline_")
    try:
        # realistic decode cost: ImageNet train JPEGs average ~400×350
        root, labels_path, _ = _make_synthetic_imagenet(
            tmp, n_images, jpeg_size)

        common = dict(train=True, image_size=image_size,
                      num_workers=num_workers, process_index=0,
                      process_count=1, device_normalize=device_normalize)
        if source in ("raw", "records"):
            from deep_vision_tpu.data.prep import prepare_imagenet

            recs = os.path.join(tmp, "recs")
            prepare_imagenet(root, labels_path, recs,
                             split="train", num_shards=8,
                             num_workers=min(8, os.cpu_count() or 1),
                             store="jpeg" if source == "records" else "raw")
            loader = ImageNetLoader.from_records(recs, "train", batch,
                                                 **common)
        else:
            loader = ImageNetLoader(
                root, labels_path, batch, **common)
        # warm one batch (pool spin-up), then measure a full epoch
        it = iter(loader)
        next(it)
        t0 = time.perf_counter()
        n = batch  # the warm batch came from this epoch's budget
        for b in it:
            n += len(b["label"])
        dt = time.perf_counter() - t0
        loader.close()
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    img_per_sec = (n - batch) / dt
    return {
        "metric": "imagenet_pipeline_images_per_sec",
        "value": round(img_per_sec, 1),
        "unit": "images/sec/host",
        "vs_baseline": round(img_per_sec / BASELINE_IMG_PER_SEC_PER_CHIP, 2),
        "source": source,
        "num_workers": num_workers,
        "jpeg_size": jpeg_size,
        "device_normalize": device_normalize,
        "host_cores": os.cpu_count(),
    }


def bench_input(batch: int = 64, size: int = 96, steps: int = 24,
                depths: tuple = (1, 2, 4), workers: tuple = (0,),
                jpeg_size: int = 160) -> dict:
    """Train-input goodput sweep: wire dtype × prefetch depth × workers.

    Every cell drives the SAME jitted conv step through a
    ``DevicePrefetcher`` (data/pipeline.py) and reports the trainer's
    input-goodput block per cell: sustained img/s, ``input_stall_frac``
    (fraction of consumer wall time spent waiting on input), and H2D
    bytes/step split by batch key.  The only things that change between
    cells are what crosses the wire (uint8 bytes vs host-normalized
    float32 — 4.0× the image DMA) and how many batches are staged ahead.

    ``workers=0`` cells stream in-memory synthetic classification
    arrays (pure wire/prefetch plumbing, no decode cost); ``workers>0``
    cells read synthetic JPEGs through the real ``ImageNetLoader``
    decode/augment pool, so the depth axis shows whether staging hides
    a real producer.
    """
    import os
    import shutil
    import tempfile

    import numpy as np

    from deep_vision_tpu.data.pipeline import DevicePrefetcher
    from deep_vision_tpu.parallel import make_mesh

    mesh = make_mesh()
    n = max(2 * batch, 256)

    from deep_vision_tpu.data.synthetic import synthetic_classification

    data = synthetic_classification(n, size, 3, 10, seed=0)
    lo, span = data["image"].min(), np.ptp(data["image"]) + 1e-9
    u8 = np.round((data["image"] - lo) / span * 255).astype(np.uint8)
    wires = {"uint8": u8, "float32": u8.astype(np.float32) / 255.0}
    labels = data["label"]

    rng = np.random.default_rng(0)
    w0 = jnp.asarray(rng.normal(0, 0.1, (3, 3, 3, 16)).astype(np.float32))

    @functools.partial(jax.jit, donate_argnums=(1,))
    def step(w, b):
        x = b["image"]
        if x.dtype == jnp.uint8:
            x = x.astype(jnp.float32) / 255.0
        else:
            x = x.astype(jnp.float32)
        y = jax.lax.conv_general_dilated(
            x, w, (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        return jnp.mean(y * y) + 0.0 * jnp.sum(b["label"])

    def memory_batches(images):
        for i in range(steps):
            s = (i * batch) % (n - batch + 1)
            yield {"image": images[s:s + batch],
                   "label": labels[s:s + batch]}

    def run_cell(batch_iter_factory, depth):
        pf = DevicePrefetcher(mesh, depth=depth)
        try:
            # warm compile outside the timed window
            jax.block_until_ready(step(
                w0, next(iter(batch_iter_factory()))))
            t0 = time.perf_counter()
            stream = pf.iterate(batch_iter_factory())
            last, n_batches = None, 0
            for b in stream:
                last = step(w0, b)
                n_batches += 1
            jax.block_until_ready(last)
            dt = time.perf_counter() - t0
            st = stream.stats()
        finally:
            pf.close()
        per_key = {k: int(v / max(1, n_batches))
                   for k, v in st["h2d_bytes_by_key"].items()}
        return {
            "images_per_sec": round(n_batches * batch / dt, 1),
            "input_stall_frac": round(st["input_stall_frac"], 4),
            "h2d_bytes_per_step": int(st["h2d_bytes_per_step"]),
            "h2d_bytes_per_step_by_key": per_key,
            "batches": n_batches,
        }

    cells = []
    tmp = None
    try:
        for nw in workers:
            if nw == 0:
                for wire, images in wires.items():
                    for depth in depths:
                        cell = run_cell(
                            lambda im=images: memory_batches(im), depth)
                        cell.update(wire=wire, depth=depth, workers=0)
                        cells.append(cell)
                continue
            # real decode/augment pool over synthetic JPEGs
            from deep_vision_tpu.data.imagenet import ImageNetLoader

            if tmp is None:
                tmp = tempfile.mkdtemp(prefix="bench_input_")
                root, labels_path, _ = _make_synthetic_imagenet(
                    tmp, max(2 * batch, 256), jpeg_size)
            for wire in ("uint8", "float32"):
                for depth in depths:
                    loader = ImageNetLoader(
                        root, labels_path, batch, train=True,
                        image_size=size, num_workers=nw,
                        device_normalize=wire == "uint8")
                    try:
                        cell = run_cell(lambda ld=loader: iter(ld), depth)
                    finally:
                        loader.close()
                    cell.update(wire=wire, depth=depth, workers=nw)
                    cells.append(cell)
    finally:
        if tmp is not None:
            shutil.rmtree(tmp, ignore_errors=True)

    def _img_bytes(wire, nw):
        for c in cells:
            if c["wire"] == wire and c["workers"] == nw:
                return c["h2d_bytes_per_step_by_key"].get("image", 0)
        return 0

    ratios = {nw: round(_img_bytes("float32", nw)
                        / max(1, _img_bytes("uint8", nw)), 2)
              for nw in workers}
    return {
        "metric": "train_input_goodput",
        "unit": "images/sec",
        "batch": batch, "image_size": size, "steps": steps,
        "backend": jax.default_backend(),
        "host_cores": os.cpu_count(),
        # acceptance: uint8 image DMA is exactly 1/4 of the f32 wire
        "f32_over_u8_image_h2d_ratio": ratios,
        "cells": cells,
    }


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--pipeline", action="store_true",
                   help="measure host input-pipeline throughput instead")
    p.add_argument("--input", action="store_true",
                   help="train-input goodput sweep: wire dtype × prefetch "
                        "depth × workers → img/s, input_stall_frac, H2D "
                        "bytes/step (docs/PERF.md 'Input pipeline')")
    p.add_argument("--input-depths", default="1,2,4",
                   help="prefetch depths to sweep with --input")
    p.add_argument("--input-workers", default="0",
                   help="decode-pool sizes to sweep with --input (0 = "
                        "in-memory arrays, >0 = ImageNetLoader JPEG pool)")
    p.add_argument("--profile", action="store_true")
    p.add_argument("--batch", type=int, default=None,
                   help="per-chip batch (default: 256 for the ResNet "
                        "bench/pipeline; per-model defaults for "
                        "--task/--infer)")
    p.add_argument("--steps", type=int, default=None,
                   help="total train steps to time (default: 80 for the "
                        "ResNet bench, rounded down to whole scan blocks; "
                        "per-task defaults for --task)")
    p.add_argument("--scan-steps", type=int, default=40,
                   help="steps per device dispatch (1 = per-step dispatch)")
    p.add_argument("--num-workers", type=int, default=None,
                   help="worker processes (default: 0 for --source raw — "
                   "decode-free reads are faster inline than through pool "
                   "IPC — else 16)")
    p.add_argument("--host-normalize", action="store_true")
    p.add_argument("--source", choices=("raw", "records", "folder"),
                   default="raw", help="--pipeline storage variant")
    p.add_argument("--task", choices=("yolo", "centernet", "hourglass",
                                      "cyclegan", "dcgan"), default=None,
                   help="bench one non-classification task's train step at "
                        "its reference production shape")
    p.add_argument("--all", action="store_true",
                   help="bench every task (one subprocess each; one JSON "
                        "line per task)")
    p.add_argument("--infer", choices=("resnet50", "yolo"), default=None,
                   help="forward-only serving throughput (yolo includes "
                        "on-device decode + NMS)")
    p.add_argument("--serve", action="store_true",
                   help="closed-loop load generator against the dynamic-"
                        "batching engine (deep_vision_tpu/serve): "
                        "p50/p95/p99 latency + img/s per offered load")
    p.add_argument("--serve-model", default="lenet5",
                   help="config to serve (--serve)")
    p.add_argument("--serve-loads", default="1,8",
                   help="comma-separated closed-loop client counts "
                        "(--serve offered-load points)")
    p.add_argument("--serve-duration", type=float, default=2.0,
                   help="seconds per offered-load point (--serve)")
    p.add_argument("--faults", default="",
                   help="fault-injection spec for --serve (e.g. "
                        "'compute:exception:p=0.05'): benchmark the "
                        "failure paths under load (docs/SERVING.md)")
    p.add_argument("--fault-seed", type=int, default=0,
                   help="seed for probabilistic fault firing (--faults)")
    p.add_argument("--serve-pipeline-depth", type=int, default=2,
                   help="in-flight batch window (--serve): 1 = the "
                        "synchronous comparison path, 2 = overlap batch "
                        "formation/H2D with device compute")
    p.add_argument("--serve-obs", action="store_true",
                   help="observability-overhead comparison (--serve): "
                        "tracing off then on at identical parameters, "
                        "one JSON with the on-run detail + img/s and "
                        "p99 deltas (docs/PERF.md)")
    p.add_argument("--serve-no-trace", action="store_true",
                   help="disable per-request span collection for a "
                        "single --serve run")
    p.add_argument("--serve-wire", action="store_true",
                   help="wire-format comparison sweep (--serve): f32 vs "
                        "uint8 wire x f32 vs bf16 compute, one JSON "
                        "with per-cell latency/throughput/H2D bytes "
                        "(make bench-serve-wire)")
    p.add_argument("--wire-dtype", choices=("float32", "uint8"),
                   default="float32",
                   help="serving wire format for a single --serve run "
                        "(uint8 = raw pixels, on-device normalization)")
    p.add_argument("--infer-dtype", choices=("float32", "bfloat16"),
                   default="float32",
                   help="on-device compute dtype for a single --serve "
                        "run (outputs stay float32)")
    p.add_argument("--serve-mix", action="store_true",
                   help="mixed-workload mix bench: every "
                        "--serve-mix-models config behind one control "
                        "plane sharing a --hbm-budget-mb weight cache, "
                        "Zipf-distributed model popularity; per-model/"
                        "per-workload p99 + D2H bytes/batch + cache "
                        "hit rate per load point (docs/SERVING.md)")
    p.add_argument("--serve-mix-models",
                   default="lenet5,yolov3_toy,hourglass_toy,dcgan",
                   help="comma-separated configs for --serve-mix "
                        "(list order = popularity rank; default spans "
                        "all four workloads: classify/detect/pose/"
                        "generate)")
    p.add_argument("--hbm-budget-mb", type=float, default=0.0,
                   help="weight-cache device-byte budget for "
                        "--serve-mix (0 = uncapped)")
    p.add_argument("--zipf-s", type=float, default=1.1,
                   help="Zipf exponent for --serve-mix model "
                        "popularity (higher = hotter head)")
    p.add_argument("--serve-cascade", action="store_true",
                   help="confidence-routed cascade A/B: train both "
                        "tiers on synthetic data, calibrate the "
                        "escalation threshold from live dual-run "
                        "samples, then sweep --serve-loads big-only vs "
                        "cascaded — img/s ratio at matched held-out "
                        "top-1, escalation rate, per-tier p50/p99 "
                        "(docs/PERF.md, serve/cascade.py)")
    p.add_argument("--cascade", default="",
                   help="'t0:...:big' chain — the tiers for "
                        "--serve-cascade (default lenet5:lenet5_big, "
                        "or the 3-tier nano chain with --tiers 3) "
                        "and, when set, the cascade column source for "
                        "--serve-mix (both names must be in "
                        "--serve-mix-models; '' = no cascade column)")
    p.add_argument("--tiers", type=int, default=2,
                   help="chain length for --serve-cascade when "
                        "--cascade is unset: 3 picks "
                        "lenet5_nano:lenet5:lenet5_big, anything else "
                        "the 2-tier pair")
    p.add_argument("--cascade-quant-front", action="store_true",
                   help="serve the --serve-cascade tier 0 "
                        "int8-resident (PTQ at load, synthetic "
                        "calibration) — the --cascade-quant-front "
                        "production boot path")
    p.add_argument("--cascade-min-agreement", type=float, default=0.95,
                   help="calibration agreement floor for "
                        "--serve-cascade")
    p.add_argument("--cascade-sample-period", type=int, default=10,
                   help="dual-run every Nth request per hop during "
                        "--serve-cascade calibration (larger = less "
                        "sampling tax, slower calibration)")
    p.add_argument("--cascade-min-sample", type=int, default=50,
                   help="dual-run samples a --serve-cascade hop needs "
                        "before it derives a threshold")
    p.add_argument("--cascade-train-epochs", type=int, default=2,
                   help="synthetic training epochs per tier for "
                        "--serve-cascade (more epochs tightens "
                        "front-vs-big agreement)")
    p.add_argument("--serve-edge", action="store_true",
                   help="HTTP front-end A/B: selector event loop "
                        "(keep-alive + pipelining + bounded conns) vs "
                        "thread-per-request baseline on one shared "
                        "engine, plus a fresh-vs-reused connection "
                        "churn probe per variant (docs/PERF.md)")
    p.add_argument("--serve-trace", action="store_true",
                   help="trace-driven OPEN-LOOP bench: diurnal+burst "
                        "Poisson arrivals, duplicate-heavy payload "
                        "pool against the response cache, tenant mix "
                        "against QoS classes; per-class p50/p99 from "
                        "scheduled arrival + cache hit rate + edge "
                        "connection churn (docs/PERF.md)")
    p.add_argument("--trace-rate", type=float, default=60.0,
                   help="base arrival rate (req/s) for --serve-trace "
                        "before the diurnal envelope and burst apply")
    p.add_argument("--trace-dup-frac", type=float, default=0.4,
                   help="fraction of --serve-trace arrivals drawn from "
                        "the hot payload pool (the cache-hit source)")
    p.add_argument("--gateway", action="store_true",
                   help="gateway failover bench: backend serve stacks "
                        "behind serve/gateway.py, HTTP clients through "
                        "the gateway, one backend hard-killed mid-way "
                        "through the top load point; reports failover "
                        "latency + breaker-open time (docs/PERF.md)")
    p.add_argument("--gateway-backends", type=int, default=2,
                   help="backend count for --gateway")
    p.add_argument("--deploy", action="store_true",
                   help="continuous-deploy reaction bench: real async-"
                        "Orbax checkpoint durable → new version ACTIVE "
                        "under live load (watcher debounce + gate + "
                        "canary rollout), plus autoscale scale-up/"
                        "scale-down reaction times (docs/PERF.md)")
    p.add_argument("--watch-interval-s", type=float, default=0.05,
                   help="watcher poll interval for --deploy (the "
                        "debounce floor is 2x this)")
    p.add_argument("--serve-devices", type=int, default=1,
                   help="device-scaling sweep (--serve): bench replica "
                        "counts 1, 2, 4, ... N and emit the scaling "
                        "table (img/s + p99 per count) plus the "
                        "per-replica block of the widest run")
    p.add_argument("--serve-mesh", type=int, default=0,
                   help="mesh-cell sweep over N devices: 1×1 baseline, "
                        "N×1 data-parallel, 1×N model-parallel, and "
                        "the squarest 2-D data×model cell — img/s, "
                        "p99, per-chip param_shard_bytes per cell "
                        "(docs/PERF.md \"Mesh scaling\"); forces N "
                        "host devices when the platform exposes fewer")
    p.add_argument("--serve-batch", action="store_true",
                   help="offline batch tier bench on a forced-host 2x2 "
                        "data×model mesh: bulk-job drain (batch img/s, "
                        "occupancy, occupancy-weighted MFU) plus the "
                        "interactive-vs-batch interference sweep over "
                        "--serve-loads (docs/PERF.md \"Batch tier\", "
                        "docs/BATCH.md)")
    p.add_argument("--batch-images", type=int, default=256,
                   help="bulk-job manifest size for --serve-batch")
    p.add_argument("--ema-decay", type=float, default=0.0,
                   help="measure the train step with the params-EMA "
                        "update in it (the Trainer's --ema-decay)")
    p.add_argument("--grad-accum", type=int, default=1,
                   help="measure with N-microbatch gradient accumulation "
                        "(the Trainer's --grad-accum)")
    p.add_argument("--momentum-dtype", choices=("bfloat16",), default=None,
                   help="store the SGD momentum accumulator in bf16 "
                        "(OptimizerConfig.momentum_dtype) — the optimizer-"
                        "state bandwidth experiment, docs/PERF.md")
    p.add_argument("--recipe", action="store_true",
                   help="one line per recipe-overhead combo (base, EMA, "
                        "grad-accum 2/4, EMA+ga2), each in a fresh process")
    p.add_argument("--coupled", action="store_true",
                   help="full cli.train run on raw records (host pipeline "
                        "+ prefetch + eval + checkpoints), sustained img/s")
    p.add_argument("--live-gan", action="store_true",
                   help="live CycleGAN AdversarialTrainer steps incl. the "
                        "host ImagePool exchange")
    args = p.parse_args()
    from deep_vision_tpu.core.compile_cache import enable_compile_cache

    enable_compile_cache()
    if args.all:
        bench_all()
        return
    if args.recipe:
        bench_recipe(batch=args.batch, steps=args.steps)
        return
    if args.input:
        print(json.dumps(bench_input(
            batch=args.batch or 64, steps=args.steps or 24,
            depths=tuple(int(d) for d in args.input_depths.split(",")),
            workers=tuple(int(w) for w in args.input_workers.split(",")))))
        return
    if args.coupled:
        print(json.dumps(bench_coupled(batch=args.batch or 256)))
        return
    if args.live_gan:
        print(json.dumps(bench_cyclegan_live(steps=args.steps or 20,
                                             batch=args.batch or 1)))
        return
    if args.serve_mix:
        print(json.dumps(bench_serve_mix(
            models=tuple(m.strip() for m in
                         args.serve_mix_models.split(",") if m.strip()),
            loads=tuple(int(c) for c in args.serve_loads.split(",")),
            duration_s=args.serve_duration, max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth,
            hbm_budget_mb=args.hbm_budget_mb, zipf_s=args.zipf_s,
            cascade=args.cascade or None)))
        return
    if args.serve_cascade:
        if args.cascade:
            chain = tuple(t.strip() for t in args.cascade.split(":"))
        elif args.tiers >= 3:
            chain = ("lenet5_nano", "lenet5", "lenet5_big")
        else:
            chain = ("lenet5", "lenet5_big")
        print(json.dumps(bench_serve_cascade(
            tiers=chain, quant_front=args.cascade_quant_front,
            loads=tuple(int(c) for c in args.serve_loads.split(",")),
            duration_s=args.serve_duration, max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth,
            min_agreement=args.cascade_min_agreement,
            sample_period=args.cascade_sample_period,
            min_sample=args.cascade_min_sample,
            train_epochs=args.cascade_train_epochs)))
        return
    if args.deploy:
        # the autoscale half needs a spare device for add_replica();
        # force a second host device before the backend initializes
        # when the platform would otherwise expose one (the `make
        # serve-multi` trick, applied automatically)
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=2"
            ).strip()
        print(json.dumps(bench_deploy(
            model_name=args.serve_model,
            watch_interval_s=args.watch_interval_s)))
        return
    if args.serve_edge:
        print(json.dumps(bench_serve_edge(
            model_name=args.serve_model,
            loads=tuple(int(c) for c in args.serve_loads.split(",")),
            duration_s=args.serve_duration, max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth)))
        return
    if args.serve_trace:
        print(json.dumps(bench_serve_trace(
            model_name=args.serve_model,
            duration_s=args.serve_duration, rate=args.trace_rate,
            dup_frac=args.trace_dup_frac, max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth)))
        return
    if args.gateway:
        print(json.dumps(bench_gateway(
            model_name=args.serve_model,
            loads=tuple(int(c) for c in args.serve_loads.split(",")),
            duration_s=args.serve_duration, max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth,
            backends=args.gateway_backends)))
        return
    if args.serve_batch:
        # the 2x2 batch-tier mesh needs 4 addressable devices — force
        # host devices before the backend initializes (the --serve-mesh
        # trick), honoring an operator-set XLA_FLAGS
        import os
        flags = os.environ.get("XLA_FLAGS", "")
        if "--xla_force_host_platform_device_count" not in flags:
            os.environ["XLA_FLAGS"] = (
                flags + " --xla_force_host_platform_device_count=4"
            ).strip()
        print(json.dumps(bench_serve_batch(
            model_name=args.serve_model, n_images=args.batch_images,
            max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth,
            loads=tuple(int(c) for c in args.serve_loads.split(",")),
            duration_s=args.serve_duration)))
        return
    if args.serve or args.serve_mesh:
        serve_kwargs = dict(
            model_name=args.serve_model,
            loads=tuple(int(c) for c in args.serve_loads.split(",")),
            duration_s=args.serve_duration, max_batch=args.batch or 8,
            pipeline_depth=args.serve_pipeline_depth,
            faults=args.faults, fault_seed=args.fault_seed,
            trace=not args.serve_no_trace)
        if args.serve_mesh:
            # the sweep needs N addressable devices — force host
            # devices before the backend initializes (the --deploy
            # trick), honoring an operator-set XLA_FLAGS
            import os
            flags = os.environ.get("XLA_FLAGS", "")
            if "--xla_force_host_platform_device_count" not in flags:
                os.environ["XLA_FLAGS"] = (
                    flags + " --xla_force_host_platform_device_count="
                    f"{args.serve_mesh}").strip()
            print(json.dumps(bench_serve_mesh(
                args.serve_mesh, wire_dtype=args.wire_dtype,
                infer_dtype=args.infer_dtype, **serve_kwargs)))
        elif args.serve_obs:
            print(json.dumps(bench_serve_obs(**serve_kwargs)))
        elif args.serve_wire:
            print(json.dumps(bench_serve_wire(**serve_kwargs)))
        elif args.serve_devices > 1:
            print(json.dumps(bench_serve_scaling(
                args.serve_devices, wire_dtype=args.wire_dtype,
                infer_dtype=args.infer_dtype, **serve_kwargs)))
        else:
            print(json.dumps(bench_serve(wire_dtype=args.wire_dtype,
                                         infer_dtype=args.infer_dtype,
                                         **serve_kwargs)))
        return
    if args.infer:
        print(json.dumps(bench_infer(args.infer, steps=args.steps,
                                     batch=args.batch)))
        return
    if args.task:
        print(json.dumps(bench_task(args.task, steps=args.steps,
                                    batch=args.batch,
                                    profile=args.profile)))
        return
    if args.pipeline:
        nw = args.num_workers if args.num_workers is not None \
            else (0 if args.source == "raw" else 16)
        out = bench_pipeline(num_workers=nw, batch=args.batch or 256,
                             device_normalize=not args.host_normalize,
                             source=args.source)
    else:
        out = bench_train_step(batch=args.batch or 256,
                               steps=args.steps or 80,
                               profile=args.profile,
                               scan_steps=args.scan_steps,
                               ema_decay=args.ema_decay,
                               grad_accum=args.grad_accum,
                               momentum_dtype=args.momentum_dtype)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
