# Convenience targets — parity with the reference's per-directory Makefiles
# (ResNet/pytorch/Makefile train_*/resume_*, CycleGAN/tensorflow/Makefile).
# One Makefile, one CLI; jobs run in the foreground (use your own nohup/tmux
# where the reference baked `nohup ... &` in).

PY ?= python
DATA ?= ./data
WORKDIR ?= ./runs

# fast lane: excludes @slow (convergence / multi-epoch training) so it
# stays runnable-in-minutes on a 1-core TPU-VM host; test-all runs everything
test:
	$(PY) -m pytest tests/ -q -m "not slow"

test-all:
	$(PY) -m pytest tests/ -q

# dvtlint: the project's AST static analyzer (docs/ANALYSIS.md) — lock
# discipline, lock-order cycles, hot-path host syncs, traced-code side
# effects, wall-clock intervals, broad-except hygiene. --strict = CI
# mode: any finding (or parse failure) exits 1; escape hatches are
# counted and reported, never silent
lint:
	$(PY) -m deep_vision_tpu.analysis --strict

# the analyzer's own suite: per-rule fixtures both directions, the
# full-tree clean run, and the SanitizedLock deliberate-inversion proof
lint-test:
	$(PY) -m pytest tests/test_lint.py -q -m lint

# boot the HTTP serving stack on a random port against a LeNet fixture,
# issue one request, assert a 200 — once synchronous (pipeline_depth=1),
# once pipelined (depth=2), once fault-injected, and once replicated over
# 2 fake host devices (the cli.serve wiring, end to end; one bulk D2H
# per batch throughout); then the multi-model plane smoke (weight
# cache + hot reload under load), the gateway smoke (cross-host
# failover) and the observability smoke (/metrics, spans, id propagation)
# lint + lint-test gate the smoke: a serving-tier change that breaks the
# machine-checked invariants fails here before any engine boots;
# input_smoke.py rides the same chain so a train-input regression
# (staging-pool lifetime, wire bytes, fused-ingest parity) fails CI too
serve-smoke: lint lint-test
	$(PY) tests/input_smoke.py
	$(PY) tests/serve_smoke.py
	$(PY) tests/edge_smoke.py
	$(PY) tests/quant_smoke.py
	$(PY) tests/model_smoke.py
	$(PY) tests/deploy_smoke.py
	$(PY) tests/gateway_smoke.py
	$(PY) tests/obs_smoke.py
	$(PY) tests/mesh_smoke.py
	$(PY) tests/workload_smoke.py
	$(PY) tests/detect_smoke.py
	$(PY) tests/batch_smoke.py
	$(PY) tests/cascade_smoke.py
	$(PY) tests/brownout_smoke.py

# the async HTTP edge end to end over real sockets: keep-alive reuse
# visible in the connection counters, a content-addressed cache hit
# consuming zero engine capacity, the starved tenant class 429ing
# (Retry-After) while premium serves, a stalled body 408'd and a
# slow-loris closed silently by the deadline sweep
edge-smoke:
	$(PY) tests/edge_smoke.py

# the staged train-input pipeline end to end: uint8 batches through a
# DevicePrefetcher into a donated jitted step (two identical epochs),
# exactly 4x fewer image H2D bytes than the float32 wire, the fused
# Pallas train-ingest parity gate, and a leak-free close()
input-smoke:
	$(PY) tests/input_smoke.py

# the input-pipeline unit suite alone (wire parity, train_ingest
# interpret parity + fallback, staging-pool reuse bounds, goodput
# timers, abandoned-epoch cleanup, donation safety)
input-test:
	$(PY) -m pytest tests/test_input_pipeline.py -q -m input_pipeline

# the edge unit suite alone (selector loop, pipelining, bounded
# connections + eviction/accept-pause, cache lifecycle, tenant QoS,
# gateway connection pooling + payload affinity)
edge-test:
	$(PY) -m pytest tests/test_edge.py -q -m edge

# the int8 quantization path end to end: calibrate at load, serve
# int8-resident weights over real HTTP next to an f32 lane on the same
# weights, gate on top-1 agreement, the describe() quant block, and
# weight HBM <= 0.27x f32 (docs/SERVING.md "Int8 inference")
quant-smoke:
	$(PY) tests/quant_smoke.py

# the quantization unit/parity suite alone (per-channel roundtrip,
# calibration determinism, Pallas-vs-XLA ingest parity, weight-cache
# density, StableHLO rejection)
quant-test:
	$(PY) -m pytest tests/test_quant.py -q -m serve

# the multi-model control plane end to end: two models behind one plane
# on a weight-cache budget that holds only one of them (evict -> spill
# -> re-admit), a hot reload under live HTTP load (zero client errors,
# v2 promoted through the canary gates), /v1/models + plane-shaped
# /v1/stats, every /metrics line parsed (dvt_serve_model_up + cache)
model-smoke:
	$(PY) tests/model_smoke.py

# workload-generic serving end to end: pose + DCGAN behind the plane
# over real HTTP (fault-injected), the heatmap-decode / uint8-image
# epilogues compiled into the bucket programs, registry-driven verb
# routing (unknown verbs 404 with the supported list), a reload ->
# canary -> operator-promote rollout under live pose load with zero
# client errors, and dvt_serve_d2h_bytes_total per workload on /metrics
workload-smoke:
	$(PY) tests/workload_smoke.py

# the workload adapter unit suite alone (decode parity, epilogue D2H
# accounting, the exact 4x generate D2H win, cache/verb/agree gates)
workload-test:
	$(PY) -m pytest tests/test_workloads.py -q -m serve

# device-side detect decode end to end: YOLO behind the plane over
# real HTTP (fault-injected), the decode -> threshold -> top-k ->
# class-wise NMS epilogue compiled into the bucket programs (bulk D2H
# is exactly K fixed rows per image, not the dense anchor pyramid), a
# reload -> shadow (greedy-IoU agreement gate on live traffic) ->
# canary -> operator-promote rollout under detect load with zero
# client errors, and workload="detect" D2H accounting on /metrics
detect-smoke:
	$(PY) tests/detect_smoke.py

# the offline batch tier end to end: a bulk job POSTed over HTTP
# drains through the trough-filling scheduler while interactive
# requests keep answering 200, results stream back as chunked ndjson,
# and a second server over the same --jobs-dir resumes an unfinished
# job straight from its JSONL checkpoint (docs/BATCH.md)
batch-smoke:
	$(PY) tests/batch_smoke.py

# the batch-tier unit suite alone (job store replay + torn tails,
# priority-band starvation-freedom, restart resume exactly-once,
# interactive-p99 interference gate, occupancy autoscaling signal,
# chunked results stream on both HTTP front-ends)
batch-test:
	$(PY) -m pytest tests/test_batch.py -q -m batch

# the confidence-routed cascade end to end over HTTP: fail-closed
# all-big before calibration, live dual-run calibration flipping
# traffic to the front tier (X-DVT-Tier), an always-big QoS tenant
# pinned to the big tier, and a mid-load front reload resetting then
# REcalibrating the threshold with zero client errors
# (docs/SERVING.md "Cascaded serving")
cascade-smoke:
	$(PY) tests/cascade_smoke.py

# the cascade unit suite alone (deterministic threshold calibration,
# fail-closed thin samples, escalation bit-identity + deadline
# preservation, version-swap resets, always-big QoS routing)
cascade-test:
	$(PY) -m pytest tests/test_cascade.py -q -m models

# the continuous train->deploy loop end to end: a real async-Orbax
# checkpoint published mid-load auto-deploys through debounce -> gate
# -> canary -> promote with zero client errors, a NaN checkpoint is
# refused by the gate, and POST /v1/deploy/<name>/revert restores the
# previous promoted weights (docs/DEPLOY.md)
deploy-smoke:
	$(PY) tests/deploy_smoke.py

# the deploy unit suite alone (fingerprint tmp-skip, watcher debounce,
# gate pass/fail, revert under load, autoscaler hysteresis + drain)
deploy-test:
	$(PY) -m pytest tests/test_deploy.py -q -m deploy

# the model-plane unit suite alone (cache LRU/bit-identity, reload
# zero-loss, canary auto-rollback, shadow discard, lifecycle HTTP)
model-test:
	$(PY) -m pytest tests/test_models_plane.py -q -m models

# the observability surface alone: Prometheus /metrics on backend and
# gateway (every line parsed, counters monotonic between scrapes), a
# ?debug=1 span accounting for its full measured latency, the client's
# X-DVT-Request-Id crossing a real gateway hop into the backend's trace
# ring (docs/OBSERVABILITY.md)
obs-smoke:
	$(PY) tests/obs_smoke.py

# the observability unit/integration suite alone
obs-test:
	$(PY) -m pytest tests/test_obs.py -q -m obs

# the 2-D data×model mesh wiring end to end: cli.serve with a forced
# 2×2 mesh over 4 virtual host devices, fault-injected — 200s through
# bisect-retry, mesh shape + per-chip shard bytes in healthz/stats
# (strictly below the replicated footprint), and every /metrics line
# parsed including dvt_serve_mesh_shape / dvt_serve_param_shard_bytes
mesh-smoke:
	$(PY) tests/mesh_smoke.py

# the mesh unit suite alone (partition rules, strict tables, fallback
# sharder, mesh-cell parity, per-chip pricing, sharded cache spill)
mesh-test:
	$(PY) -m pytest tests/test_mesh_serving.py -q -m mesh

# the cross-host failover contract end to end: 2 backend serve
# SUBPROCESSES behind the in-process gateway, fault-injected load
# through the gateway, a real SIGKILL of one backend mid-run (zero
# client-visible errors, breaker opens), then /v1/drain on the survivor
# (healthz 503 draining -> gateway healthz 503)
gateway-smoke:
	$(PY) tests/gateway_smoke.py

# the gateway unit/chaos suite alone (stub + real in-process backends)
gateway-test:
	$(PY) -m pytest tests/test_gateway.py -q -m gateway

# just the multi-device pass: 2 forced host devices, a 2-replica engine
# at depth 2 with a fault-injected cohort (serve/replicas.py routing,
# per-replica health, recovery)
serve-multi:
	XLA_FLAGS=--xla_force_host_platform_device_count=2 \
		$(PY) tests/serve_smoke.py --multi

# the chaos lane alone: deterministic fault injection against a real
# engine — poison isolation, watchdog restarts, exec-timeout fast-fail,
# healthz 200→503→200 (docs/SERVING.md "Failure model & operations")
serve-chaos:
	DVT_SERVE_FAULT_SEED=0 $(PY) -m pytest tests/test_faults.py -q -m chaos

serve_%:
	$(PY) -m deep_vision_tpu.cli.serve -m $* --workdir $(WORKDIR)/$*

bench-serve:
	$(PY) bench.py --serve

# the synchronous comparison run: same loads, in-flight window of 1
bench-serve-sync:
	$(PY) bench.py --serve --serve-pipeline-depth 1

# device-scaling sweep: img/s + p99 at replica counts 1, 2, 4, 8
# (docs/PERF.md "Device scaling"); >1.6x at 1->2 expected on real
# multi-chip hardware, routing overhead on a single shared device
bench-serve-scaling:
	$(PY) bench.py --serve --serve-devices 8

# mesh-cell sweep: 1x1 / 4x1 / 1x4 / 2x2 data x model cells over 4
# (forced) host devices — img/s, p99, and per-chip param_shard_bytes
# per cell (docs/PERF.md "Mesh scaling"); the 1x4 cell must report
# per-chip bytes strictly below the replicated footprint
bench-serve-mesh:
	$(PY) bench.py --serve-mesh 4

# wire-format comparison: {float32, uint8} wire x {float32, bfloat16,
# int8} compute — p50/p95/p99, img/s, H2D bytes/batch, and resident
# weight bytes per cell (docs/PERF.md "Wire format & inference
# dtype"); the uint8 wire must show exactly 4x fewer H2D bytes than
# float32 and the int8 cells <= 0.27x the f32 weight HBM
bench-serve-wire:
	$(PY) bench.py --serve --serve-wire

# offline batch tier bench: bulk-job drain on the 2x2 mesh cell
# (batch img/s, occupancy, occupancy-weighted MFU) plus the
# interactive-vs-batch interference sweep (docs/PERF.md "Batch tier",
# docs/BATCH.md)
bench-serve-batch:
	$(PY) bench.py --serve-batch

# continuous-deploy reaction bench: checkpoint durable -> new version
# ACTIVE under live load (debounce + gate + canary), plus autoscale
# scale-up/scale-down reaction (docs/PERF.md "Deploy reaction")
bench-deploy:
	$(PY) bench.py --deploy

# gateway failover bench: backends behind serve/gateway.py, one
# hard-killed a third into the top load point — reports errors after
# the kill (contract: 0), breaker-open latency, and the worst client
# latency in the 1 s post-kill window (docs/PERF.md)
bench-gateway:
	$(PY) bench.py --gateway

bench:
	$(PY) bench.py

bench-all:
	$(PY) bench.py --all

bench-pipeline:
	$(PY) bench.py --pipeline

# train-input goodput sweep: {uint8, float32} wire x prefetch depth
# {1, 2, 4} through the staged DevicePrefetcher — img/s, input stall
# fraction, H2D bytes/step per cell (docs/PERF.md "Input pipeline");
# the uint8 wire must show exactly 4x fewer image H2D bytes
bench-input:
	$(PY) bench.py --input

train_%:
	$(PY) -m deep_vision_tpu.cli.train -m $* --data-root $(DATA) \
		--workdir $(WORKDIR)/$*

resume_%:
	$(PY) -m deep_vision_tpu.cli.train -m $* --data-root $(DATA) \
		--workdir $(WORKDIR)/$* --resume

smoke_%:
	$(PY) -m deep_vision_tpu.cli.train -m $* --synthetic --epochs 2 \
		--workdir /tmp/smoke_$*

eval_%:
	$(PY) -m deep_vision_tpu.cli.infer eval -m $* --data-root $(DATA) \
		--workdir $(WORKDIR)/$*

list:
	$(PY) -m deep_vision_tpu.cli.train --list -m x

.PHONY: test test-all bench bench-serve bench-serve-sync \
	bench-serve-scaling bench-serve-mesh bench-serve-wire \
	bench-serve-batch bench-gateway bench-deploy \
	bench-input serve-smoke \
	serve-multi serve-chaos gateway-smoke gateway-test obs-smoke \
	edge-smoke edge-test input-smoke input-test \
	obs-test model-smoke model-test quant-smoke quant-test \
	workload-smoke workload-test detect-smoke \
	mesh-smoke mesh-test \
	deploy-smoke deploy-test batch-smoke batch-test \
	cascade-smoke cascade-test lint lint-test list
