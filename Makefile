# Convenience targets — parity with the reference's per-directory Makefiles
# (ResNet/pytorch/Makefile train_*/resume_*, CycleGAN/tensorflow/Makefile).
# One Makefile, one CLI; jobs run in the foreground (use your own nohup/tmux
# where the reference baked `nohup ... &` in).

PY ?= python
DATA ?= ./data
WORKDIR ?= ./runs

# fast lane: excludes @slow (convergence / multi-epoch training) so it
# stays runnable-in-minutes on a 1-core TPU-VM host; test-all runs everything
test:
	$(PY) -m pytest tests/ -q -m "not slow"

test-all:
	$(PY) -m pytest tests/ -q

# boot the HTTP serving stack on a random port against a LeNet fixture,
# issue one request, assert a 200 — once synchronous (pipeline_depth=1)
# and once pipelined (depth=2), checking one bulk D2H per batch
# (the cli.serve wiring, end to end)
serve-smoke:
	$(PY) tests/serve_smoke.py

# the chaos lane alone: deterministic fault injection against a real
# engine — poison isolation, watchdog restarts, exec-timeout fast-fail,
# healthz 200→503→200 (docs/SERVING.md "Failure model & operations")
serve-chaos:
	DVT_SERVE_FAULT_SEED=0 $(PY) -m pytest tests/test_faults.py -q -m chaos

serve_%:
	$(PY) -m deep_vision_tpu.cli.serve -m $* --workdir $(WORKDIR)/$*

bench-serve:
	$(PY) bench.py --serve

# the synchronous comparison run: same loads, in-flight window of 1
bench-serve-sync:
	$(PY) bench.py --serve --serve-pipeline-depth 1

bench:
	$(PY) bench.py

bench-all:
	$(PY) bench.py --all

bench-pipeline:
	$(PY) bench.py --pipeline

train_%:
	$(PY) -m deep_vision_tpu.cli.train -m $* --data-root $(DATA) \
		--workdir $(WORKDIR)/$*

resume_%:
	$(PY) -m deep_vision_tpu.cli.train -m $* --data-root $(DATA) \
		--workdir $(WORKDIR)/$* --resume

smoke_%:
	$(PY) -m deep_vision_tpu.cli.train -m $* --synthetic --epochs 2 \
		--workdir /tmp/smoke_$*

eval_%:
	$(PY) -m deep_vision_tpu.cli.infer eval -m $* --data-root $(DATA) \
		--workdir $(WORKDIR)/$*

list:
	$(PY) -m deep_vision_tpu.cli.train --list -m x

.PHONY: test test-all bench bench-serve bench-serve-sync serve-smoke \
	serve-chaos list
