# Container recipe for the training CLI — the Hourglass Dockerfile role
# (Hourglass/tensorflow/Dockerfile:1-20: cuda base + pip deps + ENTRYPOINT
# main.py), re-based for TPU hosts: no CUDA base image needed, the TPU
# runtime comes with jax[tpu] wheels.
#
# Build:  docker build -t deep-vision-tpu .
# Smoke:  docker run --rm deep-vision-tpu -m resnet50 --synthetic --epochs 2
# TPU:    run on a TPU VM with --privileged --net=host (libtpu device access)
#         docker run --privileged --net=host -v /data:/data deep-vision-tpu \
#             -m resnet50 --data-root /data/imagenet --upload gs://bucket/run1

FROM python:3.12-slim

WORKDIR /app

# TPU wheels; on a non-TPU host jax falls back to CPU automatically.
RUN pip install --no-cache-dir \
    "jax[tpu]" -f https://storage.googleapis.com/jax-releases/libtpu_releases.html \
    flax optax orbax-checkpoint chex einops numpy pillow \
    opencv-python-headless

COPY deep_vision_tpu/ deep_vision_tpu/

ENTRYPOINT ["python", "-m", "deep_vision_tpu.cli.train"]
CMD ["--list", "-m", "x"]
