"""MobileNet V1 — parity with MobileNet/pytorch/models/mobilenet_v1.py:10-155
(``DepthwiseSeparableConv`` = depthwise 3×3 + pointwise 1×1, each with
BN+ReLU; width multiplier ``alpha``; the TF variant's custom SeparableConv2D
layer is MobileNet/tensorflow/models/mobilenet_v1.py:7-74).

TPU note: depthwise convs don't use the MXU (they're VPU work) but XLA fuses
BN+ReLU into them; the pointwise 1×1s are pure MXU matmuls and dominate the
FLOPs, which is exactly where we want them.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import ConvBN, global_avg_pool

# (pointwise-out, stride) plan after the stem, before the ×5 512 block
_PLAN = [(64, 1), (128, 2), (128, 1), (256, 2), (256, 1), (512, 2),
         (512, 1), (512, 1), (512, 1), (512, 1), (512, 1),
         (1024, 2), (1024, 1)]


class DepthwiseSeparable(nn.Module):
    features: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        in_ch = x.shape[-1]
        # depthwise: groups == channels.  Explicit (1,1) pad = torch's window
        # placement at stride 2 (XLA SAME pads low=0/high=1 at even sizes),
        # so reference-format checkpoints import numerically exact; identical
        # to SAME at stride 1.
        x = ConvBN(in_ch, (3, 3), (self.strides, self.strides),
                   padding=[(1, 1), (1, 1)], groups=in_ch,
                   dtype=self.dtype)(x, train)
        # pointwise
        x = ConvBN(self.features, (1, 1), dtype=self.dtype)(x, train)
        return x


class MobileNetV1(nn.Module):
    alpha: float = 1.0  # width multiplier
    num_classes: int = 1000
    dropout: float = 0.001  # reference TF config uses ~0 dropout
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def w(c):
            return max(8, int(c * self.alpha))

        x = x.astype(self.dtype)
        x = ConvBN(w(32), (3, 3), (2, 2), padding=[(1, 1), (1, 1)],
                   dtype=self.dtype)(x, train)                 # 224→112
        for features, stride in _PLAN:
            x = DepthwiseSeparable(w(features), stride,
                                   dtype=self.dtype)(x, train)
        x = global_avg_pool(x)
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
