"""AlexNet V1/V2 — parity with AlexNet/pytorch/models/alexnet_v1.py:11-125
(one-tower original: 96/256/384/384/256 filters, LRN after conv1-2) and
alexnet_v2.py:12-75 ("one weird trick" single-column: 64/192/384/384/256);
the TF variant's custom LRN layer (AlexNet/tensorflow/models/alexnet_v2.py:9-70)
is ``common.local_response_norm``.

Both share the classifier: dropout(0.5) → 4096 → 4096 → 1000.
TPU note: LRN is one reduce-window over the channel axis (NHWC) — XLA fuses
the square/divide epilogues; convs stay on the MXU.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import local_response_norm


class AlexNet(nn.Module):
    filters: Sequence[int] = (96, 256, 384, 384, 256)  # V1; V2 overrides
    use_lrn: bool = True
    num_classes: int = 1000
    dropout: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = self.filters
        x = x.astype(self.dtype)
        x = nn.relu(nn.Conv(f[0], (11, 11), (4, 4),
                            padding=[(2, 2), (2, 2)], dtype=self.dtype)(x))
        if self.use_lrn:
            # reference passes the FULL channel count as the window
            # (nn.LocalResponseNorm(96/64), alexnet_v1.py:41, alexnet_v2.py)
            x = local_response_norm(x, size=f[0])
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(nn.Conv(f[1], (5, 5), padding=[(2, 2), (2, 2)],
                            dtype=self.dtype)(x))
        if self.use_lrn:
            x = local_response_norm(x, size=f[1])
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = nn.relu(nn.Conv(f[2], (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(f[3], (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype)(x))
        x = nn.relu(nn.Conv(f[4], (3, 3), padding=[(1, 1), (1, 1)],
                            dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), (2, 2))
        x = x.reshape((x.shape[0], -1))  # 6×6×256 at 224² input
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def AlexNetV1(num_classes: int = 1000, dtype: Any = jnp.float32) -> AlexNet:
    return AlexNet(filters=(96, 256, 384, 384, 256), num_classes=num_classes,
                   dtype=dtype)


def AlexNetV2(num_classes: int = 1000, dtype: Any = jnp.float32) -> AlexNet:
    return AlexNet(filters=(64, 192, 384, 384, 256), num_classes=num_classes,
                   dtype=dtype)
