"""Pretrained-weight import: torch checkpoints → flax variables, for every
architecture the reference publishes an accuracy number for.

The reference downloads Keras ImageNet weights for its TF ResNet-50 V2
(ResNet/tensorflow/models/resnet50v2.py:137-153 ``load_model_weights``) and
publishes trained-model numbers in AlexNet/VGG/Inception/MobileNet/LeNet/
ResNet ``pytorch/README.md``s.  The TPU-native equivalent imports the torch
``state_dict`` formats those numbers live in — torchvision-style ResNet
(``conv1/bn1/layer{1..4}.{i}.conv{j}/bn{j}/downsample/fc``) plus the
reference's own sequential/module layouts — into the flax pytrees, so each
published number is verifiable via ``cli.infer eval --pretrained``.

Layout mapping (torch → flax):
- conv weight ``(O, I, kH, kW)`` → kernel ``(kH, kW, I, O)``
- fc weight ``(O, I)`` → Dense kernel ``(I, O)``
- bn ``weight/bias`` → BatchNorm ``scale/bias`` (params);
  ``running_mean/running_var`` → ``mean/var`` (batch_stats)
- torchvision block j of stage s → ``{Basic,Bottleneck}Block_k`` with k
  counting blocks across stages in call order (flax auto-naming).

Note: stride placement follows torchvision's "V1.5" convention (stride on
the 3×3 conv), which both this package's ``BottleneckBlock`` and every
published torchvision checkpoint use.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

STAGE_SIZES = {
    "resnet34": (3, 4, 6, 3),
    "resnet50": (3, 4, 6, 3),
    "resnet152": (3, 8, 36, 3),
}
BLOCK_NAME = {
    "resnet34": "BasicBlock",
    "resnet50": "BottleneckBlock",
    "resnet152": "BottleneckBlock",
}
CONVS_PER_BLOCK = {"BasicBlock": 2, "BottleneckBlock": 3}


def _np(t) -> np.ndarray:
    """torch tensor or array-like → numpy (no torch import needed)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _conv(t) -> np.ndarray:
    return _np(t).transpose(2, 3, 1, 0)  # (O,I,H,W) → (H,W,I,O)


def import_torch_resnet(state_dict: Mapping, arch: str = "resnet50",
                        include_fc: bool = True) -> dict:
    """torchvision-style ``state_dict`` → ``{"params": ..., "batch_stats":
    ...}`` for :class:`deep_vision_tpu.models.resnet.ResNet`.

    ``include_fc=False`` drops the classifier head (fine-tuning on a
    different class count; init the head fresh and merge).
    Raises ``KeyError`` with the missing torch key if the checkpoint
    doesn't match the architecture.
    """
    if arch not in STAGE_SIZES:
        raise ValueError(f"unknown arch '{arch}'; have {sorted(STAGE_SIZES)}")
    sd = state_dict
    block = BLOCK_NAME[arch]
    n_convs = CONVS_PER_BLOCK[block]
    params: dict = {"Conv_0": {"kernel": _conv(sd["conv1.weight"])}}
    stats: dict = {}

    def bn(torch_prefix: str, flax_parent: dict, stats_parent: dict,
           flax_name: str):
        flax_parent[flax_name] = {
            "scale": _np(sd[f"{torch_prefix}.weight"]),
            "bias": _np(sd[f"{torch_prefix}.bias"]),
        }
        stats_parent[flax_name] = {
            "mean": _np(sd[f"{torch_prefix}.running_mean"]),
            "var": _np(sd[f"{torch_prefix}.running_var"]),
        }

    bn("bn1", params, stats, "BatchNorm_0")

    k = 0  # flax block index, counted across stages
    for stage, num_blocks in enumerate(STAGE_SIZES[arch], start=1):
        for i in range(num_blocks):
            t = f"layer{stage}.{i}"
            name = f"{block}_{k}"
            p: dict = {}
            s: dict = {}
            for j in range(n_convs):
                p[f"Conv_{j}"] = {
                    "kernel": _conv(sd[f"{t}.conv{j + 1}.weight"])}
                bn(f"{t}.bn{j + 1}", p, s, f"BatchNorm_{j}")
            if f"{t}.downsample.0.weight" in sd:
                p[f"Conv_{n_convs}"] = {
                    "kernel": _conv(sd[f"{t}.downsample.0.weight"])}
                bn(f"{t}.downsample.1", p, s, f"BatchNorm_{n_convs}")
            params[name] = p
            stats[name] = s
            k += 1

    if include_fc:
        params["Dense_0"] = {"kernel": _np(sd["fc.weight"]).T,
                             "bias": _np(sd["fc.bias"])}
    return {"params": params, "batch_stats": stats}


def _linear(t, flatten_chw=None) -> np.ndarray:
    """torch Linear weight ``(O, I)`` → Dense kernel ``(I, O)``.

    ``flatten_chw=(C, H, W)``: the Linear consumes a flattened conv map.
    torch flattens NCHW (C-major); this package flattens NHWC — permute the
    input axis so the imported kernel matches the NHWC flatten order."""
    w = _np(t)
    if flatten_chw is not None and flatten_chw[1] * flatten_chw[2] > 1:
        c, h, wd = flatten_chw
        w = w.reshape(w.shape[0], c, h, wd).transpose(2, 3, 1, 0)
        return w.reshape(h * wd * c, -1)
    return w.T


def _seq_indices(sd: Mapping, prefix: str, ndim: int) -> list:
    """Sorted module indices under ``prefix.N.weight`` with ``ndim``-D
    weights (4 = conv, 2 = linear) — tolerant of interleaved ReLU/LRN/pool
    modules, so one scan covers both the reference's layouts and
    torchvision's (which number the same layers differently)."""
    out = []
    for k, v in sd.items():
        parts = k.split(".")
        if (len(parts) == 3 and parts[0] == prefix and parts[2] == "weight"
                and parts[1].isdigit() and _np(v).ndim == ndim):
            out.append(int(parts[1]))
    return sorted(out)


def import_torch_sequential(state_dict: Mapping, flatten_hw,
                            include_fc: bool = True,
                            features: str = "features",
                            classifier: str = "classifier") -> dict:
    """Generic importer for the reference's plain-sequential CNNs
    (``features`` convs + ``classifier`` linears): AlexNet V1/V2
    (AlexNet/pytorch/models/alexnet_v{1,2}.py), VGG-16/19
    (VGG/pytorch/models/vgg{16,19}.py), LeNet-5
    (LeNet/pytorch/models/lenet5.py) — and torchvision's alexnet/vgg
    checkpoints, which share the Sequential layout with different indices.

    ``flatten_hw``: spatial size at the conv→FC boundary (6×6 AlexNet,
    7×7 VGG at 224² input) for the NCHW→NHWC flatten-order permutation.
    ``include_fc=False`` drops the final classifier Dense (the class head).
    """
    sd = state_dict
    conv_idx = _seq_indices(sd, features, 4)
    fc_idx = _seq_indices(sd, classifier, 2)
    if not conv_idx or not fc_idx:
        raise ValueError(
            f"no '{features}.N.weight' convs / '{classifier}.N.weight' "
            "linears found — not a sequential-CNN checkpoint")
    if any(k.startswith(f"{features}.") and k.endswith(".running_mean")
           for k in sd):
        # a BN variant (e.g. torchvision vgg16_bn) would import its convs
        # and silently drop every BN — evaluating to garbage; refuse instead
        raise ValueError(
            "checkpoint carries BatchNorm stats — the zoo's sequential "
            "models (AlexNet/VGG/LeNet) are BN-free; use the plain "
            "(non-_bn) checkpoint variant")
    params: dict = {}
    for j, i in enumerate(conv_idx):
        p = {"kernel": _conv(sd[f"{features}.{i}.weight"])}
        if f"{features}.{i}.bias" in sd:
            p["bias"] = _np(sd[f"{features}.{i}.bias"])
        params[f"Conv_{j}"] = p
    last_conv_out = _np(sd[f"{features}.{conv_idx[-1]}.weight"]).shape[0]
    if not include_fc:
        fc_idx = fc_idx[:-1]
    for j, i in enumerate(fc_idx):
        chw = (last_conv_out,) + tuple(flatten_hw) if j == 0 else None
        params[f"Dense_{j}"] = {
            "kernel": _linear(sd[f"{classifier}.{i}.weight"], chw),
            "bias": _np(sd[f"{classifier}.{i}.bias"])}
    return {"params": params, "batch_stats": {}}


def import_torch_alexnet(state_dict: Mapping,
                         include_fc: bool = True) -> dict:
    """AlexNet V1/V2 (one Sequential layout, widths differ) → flax
    ``models.alexnet.AlexNet``.  Published numbers:
    AlexNet/pytorch/README.md."""
    n = len(_seq_indices(state_dict, "features", 4))
    if n != 5:
        raise ValueError(f"AlexNet has 5 convs; checkpoint has {n}")
    return import_torch_sequential(state_dict, (6, 6), include_fc)


def import_torch_vgg(state_dict: Mapping, include_fc: bool = True) -> dict:
    """VGG-16/19 → flax ``models.vgg.VGG`` (published numbers:
    VGG/pytorch/README.md)."""
    n = len(_seq_indices(state_dict, "features", 4))
    if n not in (13, 16):
        raise ValueError(f"VGG-16/19 has 13/16 convs; checkpoint has {n}")
    return import_torch_sequential(state_dict, (7, 7), include_fc)


def import_torch_lenet5(state_dict: Mapping,
                        include_fc: bool = True) -> dict:
    """LeNet-5 → flax ``models.lenet.LeNet5`` (flatten is 1×1×120, so no
    permutation arises).  Published number: LeNet/pytorch/README.md."""
    n = len(_seq_indices(state_dict, "features", 4))
    if n != 3:
        raise ValueError(f"LeNet-5 has 3 convs; checkpoint has {n}")
    return import_torch_sequential(state_dict, (1, 1), include_fc)


def _convbn(sd: Mapping, conv_key: str, bn_key: str) -> tuple:
    """(params, batch_stats) for one ConvBN submodule."""
    p = {"Conv_0": {"kernel": _conv(sd[f"{conv_key}.weight"])},
         "BatchNorm_0": {"scale": _np(sd[f"{bn_key}.weight"]),
                         "bias": _np(sd[f"{bn_key}.bias"])}}
    s = {"BatchNorm_0": {"mean": _np(sd[f"{bn_key}.running_mean"]),
                         "var": _np(sd[f"{bn_key}.running_var"])}}
    return p, s


def import_torch_mobilenet_v1(state_dict: Mapping,
                              include_fc: bool = True) -> dict:
    """Reference MobileNet V1 layout (MobileNet/pytorch/models/
    mobilenet_v1.py: ``features.0/1`` stem conv+bn, ``features.3..15``
    DepthwiseSeparableConv blocks each ``{dw,pw}.{conv,bn}``, ``linear``)
    → flax ``models.mobilenet.MobileNetV1``.  Published number:
    MobileNet/pytorch/README.md."""
    sd = state_dict
    if "features.0.weight" not in sd or "features.3.dw.conv.weight" not in sd:
        raise ValueError("not a reference-layout MobileNet V1 checkpoint "
                         "(expects features.0 stem + features.N.dw/pw blocks)")
    params: dict = {}
    stats: dict = {}
    params["ConvBN_0"], stats["ConvBN_0"] = _convbn(
        sd, "features.0", "features.1")
    # torch stores stem bn as a sibling Sequential entry; block bns nest
    for k in range(13):
        t = f"features.{k + 3}"
        dw_p, dw_s = _convbn(sd, f"{t}.dw.conv", f"{t}.dw.bn")
        pw_p, pw_s = _convbn(sd, f"{t}.pw.conv", f"{t}.pw.bn")
        name = f"DepthwiseSeparable_{k}"
        params[name] = {"ConvBN_0": dw_p, "ConvBN_1": pw_p}
        stats[name] = {"ConvBN_0": dw_s, "ConvBN_1": pw_s}
    if include_fc:
        params["Dense_0"] = {"kernel": _np(sd["linear.weight"]).T,
                             "bias": _np(sd["linear.bias"])}
    return {"params": params, "batch_stats": stats}


def _basic_conv(sd: Mapping, key: str) -> dict:
    """Reference BasicConv2d (conv + bias + ReLU) → flax BasicConv params."""
    return {"Conv_0": {"kernel": _conv(sd[f"{key}.conv.weight"]),
                       "bias": _np(sd[f"{key}.conv.bias"])}}


# reference inception module attr ↔ flax auto-name index within
# InceptionModule.  Flax numbers submodules in CONSTRUCTION order, and in
# ``conv(c3)(conv(c3r)(x))`` Python constructs the outer conv before
# evaluating its argument — so each branch's outer conv precedes its reducer.
_INCEPTION_BRANCHES = (
    ("branch1_conv1x1", 0), ("branch2_conv3x3", 1), ("branch2_conv1x1", 2),
    ("branch3_conv5x5", 3), ("branch3_conv1x1", 4), ("branch4_conv1x1", 5))
_INCEPTION_MODULES = ("inception_3a", "inception_3b", "inception_4a",
                      "inception_4b", "inception_4c", "inception_4d",
                      "inception_4e", "inception_5a", "inception_5b")


def import_torch_inception_v1(state_dict: Mapping,
                              include_fc: bool = True) -> dict:
    """Reference Inception V1 / GoogLeNet layout (Inception/pytorch/models/
    inception_v1.py: ``conv7x7/conv1x1/conv3x3``, ``inception_Nx`` modules
    with ``branchK_convJxJ`` BasicConv2d branches, ``aux1/aux2``,
    ``linear``) → flax ``models.inception.InceptionV1``.

    The aux heads' first Linear consumes a flattened 4×4×128 map — same
    NCHW→NHWC permutation as the sequential importer.  Published number:
    Inception/pytorch/README.md."""
    sd = state_dict
    if "conv7x7.conv.weight" not in sd:
        raise ValueError("not a reference-layout Inception V1 checkpoint "
                         "(expects conv7x7.conv.weight)")
    params: dict = {
        "BasicConv_0": _basic_conv(sd, "conv7x7"),
        "BasicConv_1": _basic_conv(sd, "conv1x1"),
        "BasicConv_2": _basic_conv(sd, "conv3x3"),
    }
    for m, mod in enumerate(_INCEPTION_MODULES):
        p: dict = {}
        for attr, j in _INCEPTION_BRANCHES:
            p[f"BasicConv_{j}"] = _basic_conv(sd, f"{mod}.{attr}")
        params[f"InceptionModule_{m}"] = p
    for a, aux in enumerate(("aux1", "aux2")):
        p = {"BasicConv_0": _basic_conv(sd, f"{aux}.features.1")}
        p["Dense_0"] = {
            "kernel": _linear(sd[f"{aux}.classifier.0.weight"], (128, 4, 4)),
            "bias": _np(sd[f"{aux}.classifier.0.bias"])}
        if include_fc:
            p["Dense_1"] = {
                "kernel": _np(sd[f"{aux}.classifier.3.weight"]).T,
                "bias": _np(sd[f"{aux}.classifier.3.bias"])}
        params[f"AuxClassifier_{a}"] = p
    if include_fc:
        params["Dense_0"] = {"kernel": _np(sd["linear.weight"]).T,
                             "bias": _np(sd["linear.bias"])}
    return {"params": params, "batch_stats": {}}


# config-registry name → importer.  Every architecture the reference
# publishes an accuracy number for (docs/ACCURACY.md) imports here, so each
# published number is one `cli.infer eval --pretrained` away from checkable.
ARCH_IMPORTERS = {
    "resnet34": lambda sd, fc: import_torch_resnet(sd, "resnet34", fc),
    "resnet50": lambda sd, fc: import_torch_resnet(sd, "resnet50", fc),
    "resnet152": lambda sd, fc: import_torch_resnet(sd, "resnet152", fc),
    "alexnet1": import_torch_alexnet,
    "alexnet2": import_torch_alexnet,
    "vgg16": import_torch_vgg,
    "vgg19": import_torch_vgg,
    "lenet5": import_torch_lenet5,
    "mobilenet1": import_torch_mobilenet_v1,
    "inception1": import_torch_inception_v1,
}


def load_state_dict(path: str) -> dict:
    """Load a ``.pth``/``.pt`` state_dict from disk.  Accepts both a bare
    state_dict and the common ``{"state_dict": ...}`` wrapper (with
    optional ``module.`` DataParallel prefixes)."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    return {k.removeprefix("module."): v for k, v in obj.items()}


def load_torch_checkpoint(path: str, arch: str = "resnet50",
                          include_fc: bool = True) -> dict:
    """Load from disk and convert.  ``arch`` is a config-registry name
    (see ``ARCH_IMPORTERS``)."""
    if arch not in ARCH_IMPORTERS:
        raise ValueError(
            f"no torch importer for '{arch}'; have {sorted(ARCH_IMPORTERS)}")
    return ARCH_IMPORTERS[arch](load_state_dict(path), include_fc)


def import_pretrained(path: str, arch: str, fresh: dict) -> tuple:
    """The shared CLI loader: load once, convert, merge onto freshly-
    initialized ``fresh`` variables.  Keeps the checkpoint's class head
    when it fits the model; on a head shape mismatch re-converts headless
    (fine-tuning on a different label space).  Returns
    ``(merged_variables, head_kept)``."""
    if arch not in ARCH_IMPORTERS:
        raise ValueError(
            f"no torch importer for '{arch}'; have {sorted(ARCH_IMPORTERS)}")
    sd = load_state_dict(path)
    try:
        return merge_pretrained(fresh, ARCH_IMPORTERS[arch](sd, True)), True
    except ValueError:
        # a backbone mismatch raises again here — only the head recovers
        return merge_pretrained(fresh, ARCH_IMPORTERS[arch](sd, False)), False


def merge_pretrained(variables: dict, imported: dict) -> dict:
    """Overlay imported weights onto freshly-initialized ``variables``
    (validates tree/shape agreement leaf by leaf)."""
    import jax

    def overlay(fresh, new):
        if not isinstance(new, dict):
            fresh_arr = np.asarray(fresh)
            new_arr = np.asarray(new)
            if fresh_arr.shape != new_arr.shape:
                raise ValueError(
                    f"shape mismatch: checkpoint {new_arr.shape} vs model "
                    f"{fresh_arr.shape}")
            return new_arr.astype(fresh_arr.dtype)
        out = dict(fresh)
        for k, v in new.items():
            if k not in fresh:
                raise KeyError(f"checkpoint key '{k}' not in model")
            out[k] = overlay(fresh[k], v)
        return out

    merged = {col: overlay(variables[col], imported.get(col, {}))
              for col in variables}
    return jax.tree_util.tree_map(np.asarray, merged)
