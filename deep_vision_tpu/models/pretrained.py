"""Pretrained-weight import: torch ResNet checkpoints → flax variables.

The reference downloads Keras ImageNet weights for its TF ResNet-50 V2
(ResNet/tensorflow/models/resnet50v2.py:137-153 ``load_model_weights``).
The TPU-native equivalent imports the de-facto standard checkpoint format
for these architectures — a torchvision-style ``state_dict``
(``conv1/bn1/layer{1..4}.{i}.conv{j}/bn{j}/downsample/fc``) — into the
flax ``ResNet`` pytree, so ``models.resnet.ResNet50`` can start from
published ImageNet weights instead of scratch.

Layout mapping (torch → flax):
- conv weight ``(O, I, kH, kW)`` → kernel ``(kH, kW, I, O)``
- fc weight ``(O, I)`` → Dense kernel ``(I, O)``
- bn ``weight/bias`` → BatchNorm ``scale/bias`` (params);
  ``running_mean/running_var`` → ``mean/var`` (batch_stats)
- torchvision block j of stage s → ``{Basic,Bottleneck}Block_k`` with k
  counting blocks across stages in call order (flax auto-naming).

Note: stride placement follows torchvision's "V1.5" convention (stride on
the 3×3 conv), which both this package's ``BottleneckBlock`` and every
published torchvision checkpoint use.
"""

from __future__ import annotations

from typing import Mapping, Sequence

import numpy as np

STAGE_SIZES = {
    "resnet34": (3, 4, 6, 3),
    "resnet50": (3, 4, 6, 3),
    "resnet152": (3, 8, 36, 3),
}
BLOCK_NAME = {
    "resnet34": "BasicBlock",
    "resnet50": "BottleneckBlock",
    "resnet152": "BottleneckBlock",
}
CONVS_PER_BLOCK = {"BasicBlock": 2, "BottleneckBlock": 3}


def _np(t) -> np.ndarray:
    """torch tensor or array-like → numpy (no torch import needed)."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _conv(t) -> np.ndarray:
    return _np(t).transpose(2, 3, 1, 0)  # (O,I,H,W) → (H,W,I,O)


def import_torch_resnet(state_dict: Mapping, arch: str = "resnet50",
                        include_fc: bool = True) -> dict:
    """torchvision-style ``state_dict`` → ``{"params": ..., "batch_stats":
    ...}`` for :class:`deep_vision_tpu.models.resnet.ResNet`.

    ``include_fc=False`` drops the classifier head (fine-tuning on a
    different class count; init the head fresh and merge).
    Raises ``KeyError`` with the missing torch key if the checkpoint
    doesn't match the architecture.
    """
    if arch not in STAGE_SIZES:
        raise ValueError(f"unknown arch '{arch}'; have {sorted(STAGE_SIZES)}")
    sd = state_dict
    block = BLOCK_NAME[arch]
    n_convs = CONVS_PER_BLOCK[block]
    params: dict = {"Conv_0": {"kernel": _conv(sd["conv1.weight"])}}
    stats: dict = {}

    def bn(torch_prefix: str, flax_parent: dict, stats_parent: dict,
           flax_name: str):
        flax_parent[flax_name] = {
            "scale": _np(sd[f"{torch_prefix}.weight"]),
            "bias": _np(sd[f"{torch_prefix}.bias"]),
        }
        stats_parent[flax_name] = {
            "mean": _np(sd[f"{torch_prefix}.running_mean"]),
            "var": _np(sd[f"{torch_prefix}.running_var"]),
        }

    bn("bn1", params, stats, "BatchNorm_0")

    k = 0  # flax block index, counted across stages
    for stage, num_blocks in enumerate(STAGE_SIZES[arch], start=1):
        for i in range(num_blocks):
            t = f"layer{stage}.{i}"
            name = f"{block}_{k}"
            p: dict = {}
            s: dict = {}
            for j in range(n_convs):
                p[f"Conv_{j}"] = {
                    "kernel": _conv(sd[f"{t}.conv{j + 1}.weight"])}
                bn(f"{t}.bn{j + 1}", p, s, f"BatchNorm_{j}")
            if f"{t}.downsample.0.weight" in sd:
                p[f"Conv_{n_convs}"] = {
                    "kernel": _conv(sd[f"{t}.downsample.0.weight"])}
                bn(f"{t}.downsample.1", p, s, f"BatchNorm_{n_convs}")
            params[name] = p
            stats[name] = s
            k += 1

    if include_fc:
        params["Dense_0"] = {"kernel": _np(sd["fc.weight"]).T,
                             "bias": _np(sd["fc.bias"])}
    return {"params": params, "batch_stats": stats}


def load_torch_checkpoint(path: str, arch: str = "resnet50",
                          include_fc: bool = True) -> dict:
    """Load a ``.pth``/``.pt`` state_dict from disk and convert.  Accepts
    both a bare state_dict and the common ``{"state_dict": ...}`` wrapper
    (with optional ``module.`` DataParallel prefixes)."""
    import torch

    obj = torch.load(path, map_location="cpu", weights_only=True)
    if isinstance(obj, dict) and "state_dict" in obj:
        obj = obj["state_dict"]
    obj = {k.removeprefix("module."): v for k, v in obj.items()}
    return import_torch_resnet(obj, arch, include_fc)


def merge_pretrained(variables: dict, imported: dict) -> dict:
    """Overlay imported weights onto freshly-initialized ``variables``
    (validates tree/shape agreement leaf by leaf)."""
    import jax

    def overlay(fresh, new):
        if not isinstance(new, dict):
            fresh_arr = np.asarray(fresh)
            new_arr = np.asarray(new)
            if fresh_arr.shape != new_arr.shape:
                raise ValueError(
                    f"shape mismatch: checkpoint {new_arr.shape} vs model "
                    f"{fresh_arr.shape}")
            return new_arr.astype(fresh_arr.dtype)
        out = dict(fresh)
        for k, v in new.items():
            if k not in fresh:
                raise KeyError(f"checkpoint key '{k}' not in model")
            out[k] = overlay(fresh[k], v)
        return out

    merged = {col: overlay(variables[col], imported.get(col, {}))
              for col in variables}
    return jax.tree_util.tree_map(np.asarray, merged)
