"""ResNet family — parity targets in /root/reference:

- ResNet-34 V1: ResNet/pytorch/models/resnet34.py (BasicBlock, stages 3/4/6/3)
- ResNet-50 V1: ResNet/pytorch/models/resnet50.py:96-165 (BottleneckBlock with
  projection shortcut), ``_make_blocks`` :64-82, He fan_out init :84-93
- ResNet-152 V1: ResNet/pytorch/models/resnet152.py (stages 3/8/36/3)
- ResNet-50 V2: ResNet/tensorflow/models/resnet50v2.py:18-170 (pre-activation:
  BN→ReLU before each conv, final BN→ReLU before pooling)

TPU-first design notes:
- NHWC + bf16 activations; params stay f32 (cast at use) so BN statistics and
  the optimizer see full precision while the MXU runs bf16 matmuls.
- The whole network is a static trace — stage loops unroll at trace time into
  one XLA program; residual adds fuse into the conv epilogues.
- V1 blocks' stride-2 3×3 convs use explicit (1,1) padding — torch's window
  placement, NOT XLA SAME (which pads low=0/high=1 at even sizes and would
  make imported torchvision checkpoints numerically wrong).  The V2 pre-act
  block keeps SAME deliberately: its parity target is TF, whose SAME matches
  XLA's.  Shapes stay static either way so XLA tiles every conv onto the MXU.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence, Type

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import conv_kernel_init, global_avg_pool


class BasicBlock(nn.Module):
    """Two 3×3 convs + identity/projection shortcut (ResNet-18/34)."""

    filters: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_kernel_init,
                       dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        shortcut = x
        # explicit (1,1) pad: identical to SAME at stride 1, and at stride 2
        # it keeps torch's window placement (torch pads both sides then floor-
        # crops ⇒ windows start at row −1; XLA SAME starts at 0) so imported
        # torchvision checkpoints stay numerically exact
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(x)
        y = nn.relu(bn()(y))
        y = conv(self.filters, (3, 3))(y)
        # zero-init the last BN scale: residual branch starts as identity
        # (the standard trick the 76% recipe needs; reference lacks it)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if self.strides != 1 or x.shape[-1] != self.filters:
            shortcut = conv(self.filters, (1, 1),
                            (self.strides, self.strides))(shortcut)
            shortcut = bn()(shortcut)
        return nn.relu(y + shortcut)


class BottleneckBlock(nn.Module):
    """1×1 reduce → 3×3 → 1×1 expand (×4), projection on stage entry —
    the reference's BottleneckBlock (ResNet/pytorch/models/resnet50.py:96-165)."""

    filters: int  # bottleneck width; output is 4×filters
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_kernel_init,
                       dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        shortcut = x
        y = nn.relu(bn()(conv(self.filters, (1, 1))(x)))
        # torch-exact stride-2 window placement (see BasicBlock)
        y = conv(self.filters, (3, 3), (self.strides, self.strides),
                 padding=[(1, 1), (1, 1)])(y)
        y = nn.relu(bn()(y))
        y = conv(4 * self.filters, (1, 1))(y)
        y = bn(scale_init=nn.initializers.zeros)(y)
        if self.strides != 1 or x.shape[-1] != 4 * self.filters:
            shortcut = conv(4 * self.filters, (1, 1),
                            (self.strides, self.strides))(shortcut)
            shortcut = bn()(shortcut)
        return nn.relu(y + shortcut)


class PreActBottleneckBlock(nn.Module):
    """V2 pre-activation bottleneck (BN→ReLU→conv ×3) —
    ResNet/tensorflow/models/resnet50v2.py:18-170."""

    filters: int
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(nn.Conv, use_bias=False, kernel_init=conv_kernel_init,
                       dtype=self.dtype)
        bn = partial(nn.BatchNorm, use_running_average=not train,
                     momentum=0.9, epsilon=1e-5, dtype=self.dtype)
        pre = nn.relu(bn()(x))
        # projection sees the pre-activated input (He et al. 2016, fig 4e)
        shortcut = x
        if self.strides != 1 or x.shape[-1] != 4 * self.filters:
            shortcut = conv(4 * self.filters, (1, 1),
                            (self.strides, self.strides))(pre)
        y = conv(self.filters, (1, 1))(pre)
        y = nn.relu(bn()(y))
        # SAME (not the V1 blocks' explicit pad) is deliberate: the parity
        # target is TF (resnet50v2.py), whose SAME == XLA's
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = nn.relu(bn()(y))
        y = conv(4 * self.filters, (1, 1))(y)
        return y + shortcut


class ResNet(nn.Module):
    """Generic ResNet: 7×7/2 stem → 3×3/2 maxpool → 4 stages → GAP → FC."""

    stage_sizes: Sequence[int]
    block_cls: Type[nn.Module] = BottleneckBlock
    num_classes: int = 1000
    preact: bool = False  # V2: final BN+ReLU after stages
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)],
                    use_bias=False, kernel_init=conv_kernel_init,
                    dtype=self.dtype)(x)                        # 224→112
        if not self.preact:
            x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype)(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding=[(1, 1), (1, 1)])  # →56
        for stage, num_blocks in enumerate(self.stage_sizes):
            filters = 64 * 2 ** stage
            for i in range(num_blocks):
                strides = 2 if stage > 0 and i == 0 else 1
                x = self.block_cls(
                    filters=filters, strides=strides,
                    dtype=self.dtype)(x, train=train)
        if self.preact:
            x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                     momentum=0.9, epsilon=1e-5,
                                     dtype=self.dtype)(x))
        x = global_avg_pool(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def ResNet34(num_classes: int = 1000, dtype: Any = jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BasicBlock,
                  num_classes=num_classes, dtype=dtype)


def ResNet50(num_classes: int = 1000, dtype: Any = jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype)


def ResNet152(num_classes: int = 1000, dtype: Any = jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 8, 36, 3), block_cls=BottleneckBlock,
                  num_classes=num_classes, dtype=dtype)


def ResNet50V2(num_classes: int = 1000, dtype: Any = jnp.float32) -> ResNet:
    return ResNet(stage_sizes=(3, 4, 6, 3), block_cls=PreActBottleneckBlock,
                  num_classes=num_classes, preact=True, dtype=dtype)
