"""Stacked Hourglass — parity with Hourglass/tensorflow/hourglass104.py:
pre-act BottleneckBlock :19-67 (BN→ReLU→1×1→3×3→1×1, 1×1-conv shortcut when
lifting channels), recursive order-4 HourglassModule :70-98, 4-stack network
with intermediate supervision + re-injection :113-159.

Also the CenterNet backbone variant (ObjectsAsPoints/tensorflow/model.py:17-32):
order-5 with per-order filter tables, 2 stacks.

Note: the reference's re-injection condition reuses a shadowed loop variable
(`for i in range(num_residual)` inside `for i in range(num_stack)`,
hourglass104.py:138-157) — implemented correctly here.

TPU notes: the recursion unrolls at trace time into a static U-shaped graph;
nearest upsample via jax.image.resize (fewer layout copies than repeat).  All heads return f32 heatmaps
for a stable MSE in bf16 training.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import conv_kernel_init


class PreActBottleneck(nn.Module):
    """BN→ReLU→(1×1 C/2 → 3×3 C/2 → 1×1 C); shortcut lifts channels."""

    filters: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=self.dtype)

        def conv(f, k):
            return nn.Conv(f, (k, k), padding="SAME",
                           kernel_init=conv_kernel_init, dtype=self.dtype)

        identity = x
        if x.shape[-1] != self.filters:
            identity = conv(self.filters, 1)(x)
        y = nn.relu(bn()(x))
        y = conv(self.filters // 2, 1)(y)
        y = nn.relu(bn()(y))
        y = conv(self.filters // 2, 3)(y)
        y = nn.relu(bn()(y))
        y = conv(self.filters, 1)(y)
        return identity + y


def _up2(x):
    # nearest-neighbor ×2; jax.image.resize compiles ~8% faster end-to-end
    # than the double jnp.repeat here (fewer layout copies, measured on
    # the 4-stack step: 38.1 → 35.0 ms)
    n, h, w, c = x.shape
    return jax.image.resize(x, (n, 2 * h, 2 * w, c), "nearest")


class HourglassModule(nn.Module):
    """Recursive U-module.  ``filters`` may be one int (classic hourglass)
    or a per-order table (CenterNet: model.py:17-32)."""

    order: int
    filters: Sequence[int] | int = 256
    num_residual: int = 1
    dtype: Any = jnp.float32

    def _f(self, depth: int) -> int:
        if isinstance(self.filters, int):
            return self.filters
        return self.filters[min(depth, len(self.filters) - 1)]

    @nn.compact
    def __call__(self, x, train: bool = False):
        f = self._f(0)
        f_next = self._f(1)
        up1 = x
        for _ in range(self.num_residual + 1):
            up1 = PreActBottleneck(f, self.dtype)(up1, train)
        low = nn.max_pool(x, (2, 2), (2, 2))
        for _ in range(self.num_residual):
            low = PreActBottleneck(f_next, self.dtype)(low, train)
        if self.order > 1:
            sub_filters = self.filters if isinstance(self.filters, int) \
                else list(self.filters[1:])
            low = HourglassModule(self.order - 1, sub_filters,
                                  self.num_residual, self.dtype)(low, train)
        else:
            for _ in range(self.num_residual):
                low = PreActBottleneck(f_next, self.dtype)(low, train)
        for _ in range(self.num_residual):
            low = PreActBottleneck(f, self.dtype)(low, train)
        return up1 + _up2(low)


class HourglassStack(nn.Module):
    """ONE hourglass stack as a standalone same-shape map — the pipeline
    stage unit for :func:`deep_vision_tpu.parallel.pipeline.pipeline_apply`.

    Maps a (B, H, W, filters) feature carry to (new_carry, heatmaps):
    hourglass → residual → 1×1 linear layer → heatmap head → prediction
    re-injection (hourglass104.py:138-157).  Unlike
    :class:`StackedHourglass` (which skips re-injection on the final
    stack), every stack is structurally identical — pipeline stages must
    share one parameter tree structure; the last stack's re-injection
    convs simply go unused downstream.
    """

    num_heatmap: int = 16
    filters: int = 256
    num_residual: int = 1
    order: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = HourglassModule(self.order, self.filters, self.num_residual,
                            self.dtype)(x, train)
        for _ in range(self.num_residual):
            y = PreActBottleneck(self.filters, self.dtype)(y, train)
        y = nn.Conv(self.filters, (1, 1), kernel_init=conv_kernel_init,
                    dtype=self.dtype)(y)
        y = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)(y))
        heat = nn.Conv(self.num_heatmap, (1, 1),
                       kernel_init=conv_kernel_init, dtype=self.dtype)(y)
        new_x = x + nn.Conv(self.filters, (1, 1), dtype=self.dtype)(y) \
            + nn.Conv(self.filters, (1, 1), dtype=self.dtype)(heat)
        return new_x, heat.astype(jnp.float32)


class HourglassStem(nn.Module):
    """The pre-stack head (hourglass104.py:121-130): 7×7/2 conv →
    bottleneck → 2×2 pool → two bottlenecks, H×W → H/4×W/4 at ``filters``.

    Factored out of :class:`StackedHourglass` for the pipelined variant;
    submodule auto-names (Conv_0, BatchNorm_0, PreActBottleneck_0-2) are
    kept IDENTICAL to the stem portion of the monolithic network so
    :func:`merge_stacked_variables` is a pure rename."""

    filters: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)(x))
        x = PreActBottleneck(128, self.dtype)(x, train)
        x = nn.max_pool(x, (2, 2), (2, 2))
        x = PreActBottleneck(128, self.dtype)(x, train)
        x = PreActBottleneck(self.filters, self.dtype)(x, train)
        return x


class StackedHourglass(nn.Module):
    """256²×3 input → ``num_stack`` heatmap predictions at 64² — the full
    Hourglass-104 when num_stack=4 (hourglass104.py:113-159)."""

    num_stack: int = 4
    num_heatmap: int = 16
    filters: int = 256
    num_residual: int = 1
    order: int = 4
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=self.dtype)

        x = x.astype(self.dtype)
        x = nn.Conv(64, (7, 7), (2, 2), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)  # →128
        x = nn.relu(bn()(x))
        x = PreActBottleneck(128, self.dtype)(x, train)
        x = nn.max_pool(x, (2, 2), (2, 2))                              # →64
        x = PreActBottleneck(128, self.dtype)(x, train)
        x = PreActBottleneck(self.filters, self.dtype)(x, train)

        outputs = []
        for s in range(self.num_stack):
            y = HourglassModule(self.order, self.filters, self.num_residual,
                                self.dtype)(x, train)
            for _ in range(self.num_residual):
                y = PreActBottleneck(self.filters, self.dtype)(y, train)
            # linear layer (1×1 conv + BN + ReLU)
            y = nn.Conv(self.filters, (1, 1), kernel_init=conv_kernel_init,
                        dtype=self.dtype)(y)
            y = nn.relu(bn()(y))
            heat = nn.Conv(self.num_heatmap, (1, 1),
                           kernel_init=conv_kernel_init,
                           dtype=self.dtype)(y)
            outputs.append(heat.astype(jnp.float32))
            if s < self.num_stack - 1:  # re-inject prediction (fixed bug)
                x = x + nn.Conv(self.filters, (1, 1), dtype=self.dtype)(y) \
                    + nn.Conv(self.filters, (1, 1), dtype=self.dtype)(heat)
        return tuple(outputs)


# --------------------------------------------------------------------------
# Variable-layout conversion: monolithic StackedHourglass <-> (HourglassStem
# + per-stage HourglassStack) — the layout the pipeline-parallel training
# mode uses (parallel/pipelined.py).  Both directions are pure renames of
# the SAME math: flax auto-names submodules in call order, so the mapping
# below mirrors the two ``__call__`` bodies line by line.

def _stage_name_map(s: int, num_stack: int, num_residual: int) -> dict:
    """HourglassStack submodule name → its name inside StackedHourglass
    for stack ``s``.  Monolithic call order per stack: HourglassModule,
    ``num_residual`` bottlenecks, linear Conv+BN, heatmap Conv, and (all
    but the last stack) two re-injection Convs — so the monolithic Conv
    counter advances 4 per stack (1 stem Conv before it) and the
    bottleneck counter ``num_residual`` per stack (3 stem bottlenecks)."""
    r = num_residual
    base = 1 + 4 * s
    m = {"HourglassModule_0": f"HourglassModule_{s}",
         "Conv_0": f"Conv_{base}",
         "BatchNorm_0": f"BatchNorm_{1 + s}",
         "Conv_1": f"Conv_{base + 1}"}
    for j in range(r):
        m[f"PreActBottleneck_{j}"] = f"PreActBottleneck_{3 + s * r + j}"
    if s < num_stack - 1:
        m["Conv_2"] = f"Conv_{base + 2}"
        m["Conv_3"] = f"Conv_{base + 3}"
    return m


def merge_stacked_variables(stem_vars, stage_vars_list,
                            num_residual: int = 1) -> dict:
    """(HourglassStem variables, [per-stage HourglassStack variables]) →
    monolithic :class:`StackedHourglass` variables.  The final stage's
    re-injection convs (structurally present in every HourglassStack but
    unused downstream) have no monolithic counterpart and are dropped.
    Used to export pipeline-trained checkpoints to the layout
    ``cli.infer`` and single-device serving load."""
    num_stack = len(stage_vars_list)
    cols = set(stem_vars) | {c for v in stage_vars_list for c in v}
    out = {}
    for col in cols:
        merged = dict(stem_vars.get(col, {}))
        for s, sv in enumerate(stage_vars_list):
            names = _stage_name_map(s, num_stack, num_residual)
            for src, dst in names.items():
                if src in sv.get(col, {}):
                    merged[dst] = sv[col][src]
        out[col] = merged
    return out


def split_stacked_variables(variables, template_stage_vars,
                            num_residual: int = 1) -> tuple[dict, list]:
    """Inverse of :func:`merge_stacked_variables`: monolithic
    :class:`StackedHourglass` variables → ``(stem_vars, [stage_vars])``.
    The final stage's re-injection convs don't exist in the monolithic
    net; they are taken from ``template_stage_vars`` (a per-stage list,
    e.g. a fresh pipelined init) — they receive no gradient, so any
    finite values preserve the trajectory."""
    num_stack = len(template_stage_vars)
    stem_names = {"Conv_0", "BatchNorm_0", "PreActBottleneck_0",
                  "PreActBottleneck_1", "PreActBottleneck_2"}
    stem_vars = {col: {k: v for k, v in tree.items() if k in stem_names}
                 for col, tree in variables.items()}
    stage_vars = []
    for s in range(num_stack):
        names = _stage_name_map(s, num_stack, num_residual)
        sv = {}
        for col, tree in variables.items():
            tmpl = template_stage_vars[s].get(col, {})
            sub = {src: tree[dst] for src, dst in names.items()
                   if dst in tree}
            for k in tmpl:  # final stage: Conv_2/Conv_3 from the template
                if k not in sub:
                    sub[k] = tmpl[k]
            sv[col] = sub
        stage_vars.append(sv)
    return stem_vars, stage_vars
