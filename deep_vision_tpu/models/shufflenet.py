"""ShuffleNet V1 — the reference's file is EMPTY
(ShuffleNet/pytorch/models/shufflenet_v1.py, 0 bytes, README says WIP —
SURVEY §2.2 #15).  Implemented properly here (Zhang et al. 2017): grouped
1×1 convs + channel shuffle + depthwise 3×3, three stages (4/8/4 units),
groups=3 channel plan 240/480/960.

TPU note: the channel shuffle is a reshape-transpose-reshape — pure layout,
free under XLA; grouped 1×1 convs map to batched MXU matmuls.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import ConvBN, global_avg_pool

_STAGE_CHANNELS = {1: (144, 288, 576), 2: (200, 400, 800), 3: (240, 480, 960),
                   4: (272, 544, 1088), 8: (384, 768, 1536)}
_STAGE_REPEATS = (4, 8, 4)


def channel_shuffle(x, groups: int):
    n, h, w, c = x.shape
    x = x.reshape(n, h, w, groups, c // groups)
    x = jnp.swapaxes(x, -2, -1)
    return x.reshape(n, h, w, c)


class ShuffleUnit(nn.Module):
    features: int
    groups: int = 3
    strides: int = 1
    first_group: bool = True  # stage2's first gconv is ungrouped (paper §3.2)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        bottleneck = self.features // 4
        out_features = self.features
        if self.strides > 1:
            # concat shortcut: unit emits features - in_channels new channels
            out_features = self.features - x.shape[-1]
        g = self.groups if self.first_group else 1
        y = ConvBN(bottleneck, (1, 1), groups=g, dtype=self.dtype)(x, train)
        y = channel_shuffle(y, self.groups)
        y = ConvBN(bottleneck, (3, 3), (self.strides, self.strides),
                   groups=bottleneck, act=None, dtype=self.dtype)(y, train)
        y = ConvBN(out_features, (1, 1), groups=self.groups, act=None,
                   dtype=self.dtype)(y, train)
        if self.strides > 1:
            shortcut = nn.avg_pool(x, (3, 3), (2, 2), padding="SAME")
            return nn.relu(jnp.concatenate([shortcut, y], axis=-1))
        return nn.relu(x + y)


class ShuffleNetV1(nn.Module):
    groups: int = 3
    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        channels = _STAGE_CHANNELS[self.groups]
        x = x.astype(self.dtype)
        x = ConvBN(24, (3, 3), (2, 2), dtype=self.dtype)(x, train)   # 224→112
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")           # →56
        for stage, (c, reps) in enumerate(zip(channels, _STAGE_REPEATS)):
            for i in range(reps):
                x = ShuffleUnit(
                    c, self.groups, strides=2 if i == 0 else 1,
                    first_group=not (stage == 0 and i == 0),
                    dtype=self.dtype)(x, train)
        x = global_avg_pool(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
