"""Shared building blocks for the model zoo.

Conventions (TPU-first):
- NHWC layout, channels-last (XLA's native conv layout on TPU).
- ``dtype`` = compute/activation dtype (bf16 for MXU throughput); params are
  always float32 and cast at use (flax's ``param_dtype=float32`` default).
- He/normal init matching the reference's explicit init where it has one
  (ResNet/pytorch/models/resnet50.py:84-93 kaiming_normal fan_out).
"""

from __future__ import annotations

from typing import Any, Callable, Sequence

import jax
import jax.numpy as jnp
from flax import linen as nn

# kaiming_normal(fan_out) for ReLU nets, as the reference's He init.
conv_kernel_init = nn.initializers.variance_scaling(2.0, "fan_out", "normal")


class ConvBN(nn.Module):
    """Conv → BatchNorm → (optional) activation.

    BatchNorm semantics under the data-sharded mesh: the batch axis is a
    single global axis under GSPMD jit, so batch statistics are *global*
    (sync-BN) — stronger than the reference's implicit per-replica BN under
    DataParallel (SURVEY §7 hard-part 3); documented here as a deliberate
    choice.
    """

    features: int
    kernel_size: Sequence[int] = (3, 3)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    use_bias: bool = False
    groups: int = 1
    act: Callable | None = nn.relu
    momentum: float = 0.9
    epsilon: float = 1e-5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel_size, self.strides,
                    padding=self.padding, use_bias=self.use_bias,
                    feature_group_count=self.groups,
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=self.momentum,
                         epsilon=self.epsilon, dtype=self.dtype)(x)
        if self.act is not None:
            x = self.act(x)
        return x


def local_response_norm(x, size: int, alpha: float = 1e-4,
                        beta: float = 0.75, k: float = 1.0):
    """Cross-channel LRN with torch ``nn.LocalResponseNorm`` semantics
    (the reference applies it in AlexNet — AlexNet/pytorch/models/alexnet_v1.py
    and the custom TF layer alexnet_v2.py:9-70):

        x / (k + alpha/size * Σ_{window} x²)^beta   over a channel window.

    Implemented as an NHWC channel-axis average pool over squares — one fused
    XLA reduce-window, no transposes (TPU-friendly; torch does NCHW)."""
    sq = jnp.square(x)
    half = size // 2
    # pad channels and sum a sliding window along the last axis
    window = jax.lax.reduce_window(
        sq, 0.0, jax.lax.add,
        window_dimensions=(1, 1, 1, size),
        window_strides=(1, 1, 1, 1),
        padding=((0, 0), (0, 0), (0, 0), (half, size - 1 - half)))
    return x / jnp.power(k + alpha / size * window, beta)


def count_params(params) -> int:
    return sum(x.size for x in jax.tree_util.tree_leaves(params))


def global_avg_pool(x):
    return jnp.mean(x, axis=(1, 2))
