"""Flax model zoo — one module per family, NHWC, dtype-polymorphic (bf16
compute on TPU, f32 params)."""

from deep_vision_tpu.models.alexnet import AlexNetV1, AlexNetV2
from deep_vision_tpu.models.inception import InceptionV1, InceptionV3
from deep_vision_tpu.models.lenet import LeNet5
from deep_vision_tpu.models.mobilenet import MobileNetV1
from deep_vision_tpu.models.resnet import (
    ResNet34,
    ResNet50,
    ResNet50V2,
    ResNet152,
)
from deep_vision_tpu.models.shufflenet import ShuffleNetV1
from deep_vision_tpu.models.vgg import VGG16, VGG19

__all__ = [
    "AlexNetV1", "AlexNetV2", "InceptionV1", "InceptionV3", "LeNet5",
    "MobileNetV1", "ResNet34", "ResNet50", "ResNet50V2", "ResNet152",
    "ShuffleNetV1", "VGG16", "VGG19",
]
