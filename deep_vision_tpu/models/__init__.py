"""Flax model zoo — one module per family, NHWC, dtype-polymorphic (bf16
compute on TPU, f32 params)."""

from deep_vision_tpu.models.lenet import LeNet5

__all__ = ["LeNet5"]
