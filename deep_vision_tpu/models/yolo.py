"""Darknet-53 + YOLOv3 3-scale detector — parity with
YOLO/tensorflow/yolov3.py: DarknetConv (conv-BN-LeakyReLU) :23-41,
DarknetResidual :44-51, 3-output backbone :54-92, FPN-style head :95-235,
COCO anchor table :18-20.

TPU-first notes:
- raw head outputs stay in "t-space" (tx,ty,tw,th,obj,classes); decoding
  (sigmoid + grid offsets + anchor scaling) lives in
  ``tasks.detection.decode_boxes`` so the train graph and the eval graph
  share one codec;
- upsample is nearest ×2 via ``jax.image.resize`` — folds into the
  following conv;
- all three scales come from ONE trace; no dynamic shapes anywhere.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax
import jax.numpy as jnp
import numpy as np
from flax import linen as nn

# (w, h) anchor priors normalized by 416 (yolov3.py:18-20), grouped
# small→large; scale 0 = 52×52 grid gets the small anchors.
YOLO_ANCHORS = np.array(
    [(10, 13), (16, 30), (33, 23),
     (30, 61), (62, 45), (59, 119),
     (116, 90), (156, 198), (373, 326)], np.float32) / 416.0
ANCHOR_MASKS = np.array([[0, 1, 2], [3, 4, 5], [6, 7, 8]])


class DarknetConv(nn.Module):
    features: int
    kernel_size: int = 3
    strides: int = 1
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        if self.strides == 2:
            # darknet pads top-left for stride-2 convs
            x = jnp.pad(x, ((0, 0), (1, 0), (1, 0), (0, 0)))
            padding = "VALID"
        else:
            padding = "SAME"
        x = nn.Conv(self.features, (self.kernel_size, self.kernel_size),
                    (self.strides, self.strides), padding=padding,
                    use_bias=False, dtype=self.dtype)(x)
        x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                         epsilon=1e-5, dtype=self.dtype)(x)
        return nn.leaky_relu(x, 0.1)


class DarknetResidual(nn.Module):
    features: int  # block input channels; bottleneck is features//2
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        y = DarknetConv(self.features // 2, 1, dtype=self.dtype)(x, train)
        y = DarknetConv(self.features, 3, dtype=self.dtype)(y, train)
        return x + y


class Darknet53(nn.Module):
    """Backbone emitting (52², 26², 13²) feature maps at 416² input.

    ``width``/``blocks`` scale channels and residual-block counts
    (1.0/(1,2,8,8,4) = the paper's Darknet-53); smaller settings give a
    yolov3-tiny-class backbone for fast tests and small datasets.
    """

    dtype: Any = jnp.float32
    width: float = 1.0
    blocks: tuple = (1, 2, 8, 8, 4)

    def _w(self, f: int) -> int:
        return max(8, int(f * self.width))

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(DarknetConv, dtype=self.dtype)
        x = conv(self._w(32), 3)(x, train)
        x = conv(self._w(64), 3, 2)(x, train)             # /2
        for _ in range(self.blocks[0]):
            x = DarknetResidual(self._w(64), self.dtype)(x, train)
        x = conv(self._w(128), 3, 2)(x, train)            # /4
        for _ in range(self.blocks[1]):
            x = DarknetResidual(self._w(128), self.dtype)(x, train)
        x = conv(self._w(256), 3, 2)(x, train)            # /8
        for _ in range(self.blocks[2]):
            x = DarknetResidual(self._w(256), self.dtype)(x, train)
        route_small = x                                   # 52²×256
        x = conv(self._w(512), 3, 2)(x, train)            # /16
        for _ in range(self.blocks[3]):
            x = DarknetResidual(self._w(512), self.dtype)(x, train)
        route_medium = x                                  # 26²×512
        x = conv(self._w(1024), 3, 2)(x, train)           # /32
        for _ in range(self.blocks[4]):
            x = DarknetResidual(self._w(1024), self.dtype)(x, train)
        return route_small, route_medium, x               # 13²×1024


def _upsample2(x):
    # nearest-neighbor ×2 (see models/hourglass.py _up2: resize compiles
    # with fewer layout copies than double jnp.repeat)
    n, h, w, c = x.shape
    return jax.image.resize(x, (n, 2 * h, 2 * w, c), "nearest")


class YoloConvBlock(nn.Module):
    """5-conv 1-3-1-3-1 neck block (yolov3.py head)."""

    features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(DarknetConv, dtype=self.dtype)
        x = conv(self.features, 1)(x, train)
        x = conv(self.features * 2, 3)(x, train)
        x = conv(self.features, 1)(x, train)
        x = conv(self.features * 2, 3)(x, train)
        x = conv(self.features, 1)(x, train)
        return x


class YoloHead(nn.Module):
    """3×3 conv + 1×1 projection to 3·(5+C) raw channels."""

    features: int
    num_classes: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = DarknetConv(self.features * 2, 3, dtype=self.dtype)(x, train)
        x = nn.Conv(3 * (5 + self.num_classes), (1, 1), dtype=self.dtype)(x)
        n, h, w, _ = x.shape
        x = x.reshape(n, h, w, 3, 5 + self.num_classes)
        return x.astype(jnp.float32)  # raw t-space, f32 for the loss


class YoloV3(nn.Module):
    """Returns raw outputs for the three scales, LARGE grid first
    (52²: small objects) to match the anchor-mask order."""

    num_classes: int = 80
    dtype: Any = jnp.float32
    width: float = 1.0
    blocks: tuple = (1, 2, 8, 8, 4)

    def _w(self, f: int) -> int:
        return max(8, int(f * self.width))

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        small, medium, large = Darknet53(self.dtype, self.width,
                                         self.blocks)(x, train)

        x13 = YoloConvBlock(self._w(512), self.dtype)(large, train)
        out13 = YoloHead(self._w(512), self.num_classes, self.dtype)(
            x13, train)

        x = DarknetConv(self._w(256), 1, dtype=self.dtype)(x13, train)
        x = jnp.concatenate([_upsample2(x), medium], axis=-1)
        x26 = YoloConvBlock(self._w(256), self.dtype)(x, train)
        out26 = YoloHead(self._w(256), self.num_classes, self.dtype)(
            x26, train)

        x = DarknetConv(self._w(128), 1, dtype=self.dtype)(x26, train)
        x = jnp.concatenate([_upsample2(x), small], axis=-1)
        x52 = YoloConvBlock(self._w(128), self.dtype)(x, train)
        out52 = YoloHead(self._w(128), self.num_classes, self.dtype)(
            x52, train)

        return out52, out26, out13  # scale order matches ANCHOR_MASKS rows
