"""GAN generators/discriminators — parity with
DCGAN/tensorflow/models.py (ConvTranspose generator from 100-d noise :30-65,
conv discriminator :8-27) and CycleGAN/tensorflow/models.py (ResNet-block
generator with reflection padding :8-78, PatchGAN discriminator :81-104).

TPU notes: ConvTranspose maps to MXU like a conv; reflection padding is
jnp.pad(mode="reflect") — a gather XLA fuses; tanh outputs stay f32.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


# ---------------------------------------------------------------------------
# DCGAN (MNIST 28×28×1)
# ---------------------------------------------------------------------------


class DCGANGenerator(nn.Module):
    """100-d noise → 28²×1 tanh image."""

    latent_dim: int = 100
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, z, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, dtype=self.dtype)

        z = z.astype(self.dtype)
        x = nn.Dense(7 * 7 * 256, use_bias=False, dtype=self.dtype)(z)
        x = nn.leaky_relu(bn()(x), 0.3)
        x = x.reshape((-1, 7, 7, 256))
        x = nn.ConvTranspose(128, (5, 5), (1, 1), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.leaky_relu(bn()(x), 0.3)
        x = nn.ConvTranspose(64, (5, 5), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)   # 14²
        x = nn.leaky_relu(bn()(x), 0.3)
        x = nn.ConvTranspose(1, (5, 5), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)   # 28²
        return jnp.tanh(x).astype(jnp.float32)


class DCGANDiscriminator(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(64, (5, 5), (2, 2), padding="SAME", dtype=self.dtype)(x)
        x = nn.leaky_relu(x, 0.3)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = nn.Conv(128, (5, 5), (2, 2), padding="SAME", dtype=self.dtype)(x)
        x = nn.leaky_relu(x, 0.3)
        x = nn.Dropout(0.3, deterministic=not train)(x)
        x = x.reshape((x.shape[0], -1))
        return nn.Dense(1, dtype=self.dtype)(x).astype(jnp.float32)


# ---------------------------------------------------------------------------
# CycleGAN (256×256×3)
# ---------------------------------------------------------------------------


def reflect_pad(x, p: int):
    return jnp.pad(x, ((0, 0), (p, p), (p, p), (0, 0)), mode="reflect")


class ResNetBlock(nn.Module):
    """reflection-pad 3×3 conv ×2 + identity (models.py:17-38)."""

    dim: int = 256
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, dtype=self.dtype)

        y = reflect_pad(x, 1)
        y = nn.Conv(self.dim, (3, 3), padding="VALID", use_bias=False,
                    dtype=self.dtype)(y)
        y = nn.relu(bn()(y))
        y = reflect_pad(y, 1)
        y = nn.Conv(self.dim, (3, 3), padding="VALID", use_bias=False,
                    dtype=self.dtype)(y)
        y = bn()(y)
        return x + y


class CycleGANGenerator(nn.Module):
    """c7s1-64, d128, d256, R256×n, u128, u64, c7s1-3 (models.py:41-78)."""

    n_blocks: int = 9
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, dtype=self.dtype)

        x = x.astype(self.dtype)
        x = reflect_pad(x, 3)
        x = nn.Conv(64, (7, 7), padding="VALID", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        x = nn.Conv(128, (3, 3), (2, 2), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        x = nn.Conv(256, (3, 3), (2, 2), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        for _ in range(self.n_blocks):
            x = ResNetBlock(256, self.dtype)(x, train)
        x = nn.ConvTranspose(128, (3, 3), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        x = nn.ConvTranspose(64, (3, 3), (2, 2), padding="SAME",
                             use_bias=False, dtype=self.dtype)(x)
        x = nn.relu(bn()(x))
        x = reflect_pad(x, 3)
        x = nn.Conv(3, (7, 7), padding="VALID", dtype=self.dtype)(x)
        return jnp.tanh(x).astype(jnp.float32)


class PatchGANDiscriminator(nn.Module):
    """C64-C128-C256-C512 → 1-channel patch map (models.py:81-104)."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, dtype=self.dtype)

        x = x.astype(self.dtype)
        x = nn.Conv(64, (4, 4), (2, 2), padding="SAME", dtype=self.dtype)(x)
        x = nn.leaky_relu(x, 0.2)
        for f in (128, 256):
            x = nn.Conv(f, (4, 4), (2, 2), padding="SAME", use_bias=False,
                        dtype=self.dtype)(x)
            x = nn.leaky_relu(bn()(x), 0.2)
        x = nn.Conv(512, (4, 4), (1, 1), padding="SAME", use_bias=False,
                    dtype=self.dtype)(x)
        x = nn.leaky_relu(bn()(x), 0.2)
        return nn.Conv(1, (4, 4), (1, 1), padding="SAME",
                       dtype=self.dtype)(x).astype(jnp.float32)
