"""VGG-16/19 — parity with VGG/pytorch/models/vgg16.py:8-127 and vgg19.py
(plain 3×3 stacks; the reference writes every layer out by hand, here the
stack is data-driven).  Classifier: dropout → 4096 → 4096 → num_classes.

TPU note: all convs are 3×3 SAME — uniform shapes XLA tiles perfectly; the
two 4096-wide FC layers are pure MXU matmuls.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

# channel plan per stage; M = maxpool
_VGG16 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M")
_VGG19 = (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
          512, 512, 512, 512, "M", 512, 512, 512, 512, "M")


class VGG(nn.Module):
    plan: Sequence = _VGG16
    num_classes: int = 1000
    dropout: float = 0.5
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for item in self.plan:
            if item == "M":
                x = nn.max_pool(x, (2, 2), (2, 2))
            else:
                x = nn.relu(nn.Conv(item, (3, 3), padding="SAME",
                                    dtype=self.dtype)(x))
        x = x.reshape((x.shape[0], -1))  # 7×7×512 at 224²
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.relu(nn.Dense(4096, dtype=self.dtype)(x))
        x = nn.Dropout(self.dropout, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


def VGG16(num_classes: int = 1000, dtype: Any = jnp.float32) -> VGG:
    return VGG(plan=_VGG16, num_classes=num_classes, dtype=dtype)


def VGG19(num_classes: int = 1000, dtype: Any = jnp.float32) -> VGG:
    return VGG(plan=_VGG19, num_classes=num_classes, dtype=dtype)
