"""Inception V1 (GoogLeNet) — parity with
Inception/pytorch/models/inception_v1.py:9-201: 4-branch ``InceptionModule``
(:127-158), two ``AuxiliaryClassifier`` heads (:161-190) emitted only in
training mode (:92-113), channel plan per the table at :43-71.

Inception V3 — the reference ships a 5-line stub (inception_v3.py:1-5,
SURVEY §2.2 #12); here it is implemented properly (Szegedy et al. 2015:
factorized 7×7 stem → 3×Inception-A → grid-reduction → 4×Inception-B with
n×1/1×n factorization → reduction → 2×Inception-C, BN everywhere, aux head
on the last 17×17 block).

TPU note: each module's four branches are independent convs XLA schedules
back-to-back on the MXU; concat is free (layout).  Aux heads only exist in
the training graph — eval traces a smaller program.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Sequence

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import (
    conv_kernel_init,
    global_avg_pool,
    local_response_norm,
)


class BasicConv(nn.Module):
    """Conv + ReLU (V1, reference BasicConv2d :193-201) or Conv+BN+ReLU (V3)."""

    features: int
    kernel_size: Sequence[int] = (1, 1)
    strides: Sequence[int] = (1, 1)
    padding: Any = "SAME"
    use_bn: bool = False
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.Conv(self.features, self.kernel_size, self.strides,
                    padding=self.padding, use_bias=not self.use_bn,
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)
        if self.use_bn:
            x = nn.BatchNorm(use_running_average=not train, momentum=0.9,
                             epsilon=1e-3, dtype=self.dtype)(x)
        return nn.relu(x)


class InceptionModule(nn.Module):
    """1×1 | 1×1→3×3 | 1×1→5×5 | maxpool→1×1, channel-concat."""

    c1: int
    c3r: int
    c3: int
    c5r: int
    c5: int
    cp: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype)
        b1 = conv(self.c1)(x, train)
        b2 = conv(self.c3, (3, 3))(conv(self.c3r)(x, train), train)
        b3 = conv(self.c5, (5, 5))(conv(self.c5r)(x, train), train)
        b4 = conv(self.cp)(nn.max_pool(x, (3, 3), (1, 1), padding="SAME"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class AuxClassifier(nn.Module):
    """5×5/3 avgpool → 1×1 conv128 → FC1024 → dropout(0.7) → FC1000
    (reference :161-190)."""

    num_classes: int = 1000
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = nn.avg_pool(x, (5, 5), (3, 3))
        x = BasicConv(128, dtype=self.dtype)(x, train)
        x = x.reshape((x.shape[0], -1))
        x = nn.relu(nn.Dense(1024, dtype=self.dtype)(x))
        x = nn.Dropout(0.7, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class InceptionV1(nn.Module):
    num_classes: int = 1000
    aux_heads: bool = True
    use_lrn: bool = True  # the reference stem LRNs (inception_v1.py lrn1/lrn2)
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, dtype=self.dtype)
        mod = partial(InceptionModule, dtype=self.dtype)
        x = x.astype(self.dtype)
        # explicit pad 3 = torch's stride-2 window placement (SAME would pad
        # low=2/high=3 and shift every window) — keeps reference-format
        # checkpoint imports numerically exact
        x = conv(64, (7, 7), (2, 2), padding=[(3, 3), (3, 3)])(x, train)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")          # →56
        # SAME maxpool == torch ceil_mode here (even sizes: both pad (0,1));
        # post-ReLU values are ≥0 so the -inf SAME fill never wins
        if self.use_lrn:
            x = local_response_norm(x, size=64)
        x = conv(64)(x, train)
        x = conv(192, (3, 3))(x, train)
        if self.use_lrn:
            x = local_response_norm(x, size=192)
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")          # →28
        x = mod(64, 96, 128, 16, 32, 32)(x, train)      # 3a → 256
        x = mod(128, 128, 192, 32, 96, 64)(x, train)    # 3b → 480
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")          # →14
        x = mod(192, 96, 208, 16, 48, 64)(x, train)     # 4a → 512
        # aux heads are built unconditionally so their params exist for any
        # init mode; in the eval graph their outputs are unused and XLA
        # dead-code-eliminates the whole branch.
        aux1 = AuxClassifier(self.num_classes, self.dtype)(x, train) \
            if self.aux_heads else None
        x = mod(160, 112, 224, 24, 64, 64)(x, train)    # 4b
        x = mod(128, 128, 256, 24, 64, 64)(x, train)    # 4c
        x = mod(112, 144, 288, 32, 64, 64)(x, train)    # 4d → 528
        aux2 = AuxClassifier(self.num_classes, self.dtype)(x, train) \
            if self.aux_heads else None
        x = mod(256, 160, 320, 32, 128, 128)(x, train)  # 4e → 832
        x = nn.max_pool(x, (3, 3), (2, 2), padding="SAME")          # →7
        x = mod(256, 160, 320, 32, 128, 128)(x, train)  # 5a
        x = mod(384, 192, 384, 48, 128, 128)(x, train)  # 5b → 1024
        x = global_avg_pool(x)
        x = nn.Dropout(0.4, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        x = x.astype(jnp.float32)
        if train and self.aux_heads:
            return (x, aux1, aux2)
        return x


# ---------------------------------------------------------------------------
# Inception V3 (proper implementation where the reference has a stub)
# ---------------------------------------------------------------------------


class InceptionA(nn.Module):
    pool_features: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, use_bn=True, dtype=self.dtype)
        b1 = conv(64)(x, train)
        b2 = conv(64, (5, 5))(conv(48)(x, train), train)
        b3 = conv(96, (3, 3))(conv(96, (3, 3))(conv(64)(x, train), train), train)
        b4 = conv(self.pool_features)(
            nn.avg_pool(x, (3, 3), (1, 1), padding="SAME"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionA(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, use_bn=True, dtype=self.dtype)
        b1 = conv(384, (3, 3), (2, 2), padding="VALID")(x, train)
        b2 = conv(96, (3, 3), (2, 2), padding="VALID")(
            conv(96, (3, 3))(conv(64)(x, train), train), train)
        b3 = nn.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionB(nn.Module):
    """17×17 blocks with n×1/1×n factorized 7-convs."""

    c7: int
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, use_bn=True, dtype=self.dtype)
        c = self.c7
        b1 = conv(192)(x, train)
        b2 = conv(192, (7, 1))(conv(c, (1, 7))(conv(c)(x, train), train), train)
        b3 = x
        for f, k in ((c, (1, 1)), (c, (7, 1)), (c, (1, 7)), (c, (7, 1)),
                     (192, (1, 7))):
            b3 = conv(f, k)(b3, train)
        b4 = conv(192)(nn.avg_pool(x, (3, 3), (1, 1), padding="SAME"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class ReductionB(nn.Module):
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, use_bn=True, dtype=self.dtype)
        b1 = conv(320, (3, 3), (2, 2), padding="VALID")(
            conv(192)(x, train), train)
        b2 = x
        for f, k, s, p in ((192, (1, 1), (1, 1), "SAME"),
                           (192, (1, 7), (1, 1), "SAME"),
                           (192, (7, 1), (1, 1), "SAME"),
                           (192, (3, 3), (2, 2), "VALID")):
            b2 = conv(f, k, s, padding=p)(b2, train)
        b3 = nn.max_pool(x, (3, 3), (2, 2))
        return jnp.concatenate([b1, b2, b3], axis=-1)


class InceptionC(nn.Module):
    """8×8 blocks with split 1×3/3×1 branches."""

    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, use_bn=True, dtype=self.dtype)
        b1 = conv(320)(x, train)
        b2 = conv(384)(x, train)
        b2 = jnp.concatenate([conv(384, (1, 3))(b2, train),
                              conv(384, (3, 1))(b2, train)], axis=-1)
        b3 = conv(384, (3, 3))(conv(448)(x, train), train)
        b3 = jnp.concatenate([conv(384, (1, 3))(b3, train),
                              conv(384, (3, 1))(b3, train)], axis=-1)
        b4 = conv(192)(nn.avg_pool(x, (3, 3), (1, 1), padding="SAME"), train)
        return jnp.concatenate([b1, b2, b3, b4], axis=-1)


class InceptionV3(nn.Module):
    num_classes: int = 1000
    aux_heads: bool = True
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        conv = partial(BasicConv, use_bn=True, dtype=self.dtype)
        x = x.astype(self.dtype)                                     # 299²
        x = conv(32, (3, 3), (2, 2), padding="VALID")(x, train)      # →149
        x = conv(32, (3, 3), padding="VALID")(x, train)              # →147
        x = conv(64, (3, 3))(x, train)
        x = nn.max_pool(x, (3, 3), (2, 2))                           # →73
        x = conv(80, (1, 1))(x, train)
        x = conv(192, (3, 3), padding="VALID")(x, train)             # →71
        x = nn.max_pool(x, (3, 3), (2, 2))                           # →35
        x = InceptionA(32, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = InceptionA(64, self.dtype)(x, train)
        x = ReductionA(self.dtype)(x, train)                         # →17
        x = InceptionB(128, self.dtype)(x, train)
        x = InceptionB(160, self.dtype)(x, train)
        x = InceptionB(160, self.dtype)(x, train)
        x = InceptionB(192, self.dtype)(x, train)
        aux = None
        if self.aux_heads:  # params always built; eval graph DCEs the branch
            a = nn.avg_pool(x, (5, 5), (3, 3))
            a = conv(128)(a, train)
            a = conv(768, (5, 5), padding="VALID")(a, train)
            a = global_avg_pool(a)
            aux = nn.Dense(self.num_classes, dtype=self.dtype)(a)
            aux = aux.astype(jnp.float32)
        x = ReductionB(self.dtype)(x, train)                         # →8
        x = InceptionC(self.dtype)(x, train)
        x = InceptionC(self.dtype)(x, train)
        x = global_avg_pool(x)
        x = nn.Dropout(0.5, deterministic=not train)(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        x = x.astype(jnp.float32)
        if train and self.aux_heads:
            return (x, aux)
        return x
