"""LeNet-5 — parity with LeNet/pytorch/models/lenet5.py:14-67 and
LeNet/tensorflow/models/lenet5.py:7-34.

C1 conv6@5×5 → tanh → S2 avgpool2 → tanh → C3 conv16@5×5 → tanh →
S4 avgpool2 → tanh → C5 conv120@5×5 → tanh → F6 dense84 → tanh → dense10.
Input: 32×32×1 NHWC (MNIST padded 28→32).  61,706 params.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn


class LeNet5Big(nn.Module):
    """A deliberately heavy MNIST-shape classifier — the cascade's BIG
    tier opposite LeNet-5 (serve/cascade.py, bench.py --serve-cascade).

    Same 32×32×1 input and class count as LeNet-5 so the two tiers are
    interchangeable on the wire, but VGG-style doubled-conv blocks with
    ``width``× the channels and a wide head: ~50× the FLOPs/params of
    LeNet-5 at width 32 — the compute ratio the reference zoo spans
    between its mobile and server models, reproduced at a size CPU
    hosts can still bench."""

    num_classes: int = 10
    width: int = 32
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        for mult in (1, 2, 4):  # 32→16→8→4 after the pools
            ch = self.width * mult
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.Conv(ch, (3, 3), padding="SAME", dtype=self.dtype)(x)
            x = nn.relu(x)
            x = nn.max_pool(x, (2, 2), (2, 2))
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(8 * self.width, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class LeNet5Nano(nn.Module):
    """A deliberately tiny MNIST-shape classifier — the N-tier
    cascade's tier-0 below LeNet-5 (serve/cascade.py,
    bench.py --serve-cascade --tiers 3).

    Same 32×32×1 input and class count as the other two so all three
    tiers are interchangeable on the wire: one strided conv8@5×5 →
    pool → dense, ~5K params (~12× fewer than LeNet-5) — the
    mobile-below-mobile end of the reference zoo's compute span."""

    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(8, (5, 5), strides=(2, 2), padding="VALID",
                    dtype=self.dtype)(x)                               # 32→14
        x = nn.relu(x)
        x = nn.avg_pool(x, (2, 2), (2, 2))                             # 14→7
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class LeNet5(nn.Module):
    num_classes: int = 10
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        x = x.astype(self.dtype)
        x = nn.Conv(6, (5, 5), padding="VALID", dtype=self.dtype)(x)   # 32→28
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), (2, 2))                             # 28→14
        x = nn.tanh(x)
        x = nn.Conv(16, (5, 5), padding="VALID", dtype=self.dtype)(x)  # 14→10
        x = nn.tanh(x)
        x = nn.avg_pool(x, (2, 2), (2, 2))                             # 10→5
        x = nn.tanh(x)
        x = nn.Conv(120, (5, 5), padding="VALID", dtype=self.dtype)(x)  # 5→1
        x = nn.tanh(x)
        x = x.reshape((x.shape[0], -1))
        x = nn.Dense(84, dtype=self.dtype)(x)
        x = nn.tanh(x)
        x = nn.Dense(self.num_classes, dtype=self.dtype)(x)
        return x.astype(jnp.float32)
