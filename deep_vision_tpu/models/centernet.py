"""CenterNet ("Objects as Points") hourglass detector — completes the
reference's UNFINISHED ObjectsAsPoints stack (SURVEY §2.2 #18: empty
``loss_objects`` ObjectsAsPoints/tensorflow/train.py:35, trainer never run
:248, label gen stubbed to zeros preprocess.py:129-131).

Parity with the model that DOES exist (ObjectsAsPoints/tensorflow/model.py):
per-order filter tables :17-32 (order-5: 256,256,384,384,384,512),
BN-free ``DetectionHead`` 3-head (class heatmap / wh / offset) :72-91,
2-stack with re-injection :130-179.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import conv_kernel_init
from deep_vision_tpu.models.hourglass import HourglassModule, PreActBottleneck

# depth-indexed filters for the order-5 module (model.py:17-23)
CENTERNET_FILTERS = (256, 256, 384, 384, 384, 512)


class DetectionHead(nn.Module):
    """3×3 conv256+ReLU → 3×3 conv out, NO BatchNorm (model.py:72-78)."""

    out_features: int
    bias_init_value: float = 0.0  # heatmap head: -2.19 focal prior
    dtype: Any = jnp.float32
    features: int = 256

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, (3, 3), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.out_features, (3, 3), padding="SAME",
                    bias_init=nn.initializers.constant(self.bias_init_value),
                    dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class CenterNetStem(nn.Module):
    """The pre-stack head (model.py:130-140): 7×7/2 conv → bottleneck →
    2×2 pool, H×W → H/4×W/4.  Submodule auto-names (Conv_0, BatchNorm_0,
    PreActBottleneck_0) match the stem portion of the monolithic
    :class:`CenterNet` so :func:`merge_centernet_variables` is a pure
    rename."""

    filters: tuple = CENTERNET_FILTERS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        base = self.filters[0]
        x = x.astype(self.dtype)
        x = nn.Conv(base // 2, (7, 7), (2, 2), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)(x))
        x = PreActBottleneck(base, self.dtype)(x, train)
        return nn.max_pool(x, (2, 2), (2, 2))


class CenterNetStack(nn.Module):
    """ONE CenterNet stack as a standalone same-shape map — the pipeline
    stage unit (:func:`deep_vision_tpu.parallel.pipelined.PipelinedModel.
    from_centernet`).  Maps a (B, H, W, base) carry to
    ``(new_carry, (heat, wh, offset))``; every stack is structurally
    identical (the last stack's re-injection conv goes unused
    downstream, like the hourglass stage unit)."""

    num_classes: int = 80
    order: int = 5
    filters: tuple = CENTERNET_FILTERS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        base = self.filters[0]
        y = HourglassModule(self.order, list(self.filters),
                            num_residual=1, dtype=self.dtype)(x, train)
        y = nn.Conv(base, (3, 3), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(y)
        y = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)(y))
        heat = DetectionHead(self.num_classes, -2.19, self.dtype,
                             features=base)(y)
        wh = DetectionHead(2, 0.0, self.dtype, features=base)(y)
        offset = DetectionHead(2, 0.0, self.dtype, features=base)(y)
        new_x = x + nn.Conv(base, (1, 1), dtype=self.dtype)(y)
        return new_x, (heat, wh, offset)


class CenterNet(nn.Module):
    """256²×3 → per-stack (heatmap_logits (64²,C), wh (64²,2), offset).

    ``order``/``filters`` default to the reference's order-5 table; smaller
    settings give a test-scale model (order must satisfy
    2**order ≤ input_size/4).
    """

    num_classes: int = 80
    num_stack: int = 2
    order: int = 5
    filters: tuple = CENTERNET_FILTERS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=self.dtype)

        base = self.filters[0]
        x = x.astype(self.dtype)
        x = nn.Conv(base // 2, (7, 7), (2, 2), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)  # /2
        x = nn.relu(bn()(x))
        x = PreActBottleneck(base, self.dtype)(x, train)
        x = nn.max_pool(x, (2, 2), (2, 2))                              # /4

        outputs = []
        for s in range(self.num_stack):
            y = HourglassModule(self.order, list(self.filters),
                                num_residual=1, dtype=self.dtype)(x, train)
            y = nn.Conv(base, (3, 3), padding="SAME",
                        kernel_init=conv_kernel_init, dtype=self.dtype)(y)
            y = nn.relu(bn()(y))
            # -2.19 bias prior: σ(-2.19)≈0.1 initial heatmap (CenterNet)
            heat = DetectionHead(self.num_classes, -2.19, self.dtype,
                                 features=base)(y)
            wh = DetectionHead(2, 0.0, self.dtype, features=base)(y)
            offset = DetectionHead(2, 0.0, self.dtype, features=base)(y)
            outputs.append((heat, wh, offset))
            if s < self.num_stack - 1:
                x = x + nn.Conv(base, (1, 1), dtype=self.dtype)(y)
        return tuple(outputs)


# --------------------------------------------------------------------------
# Variable-layout conversion: monolithic CenterNet <-> (CenterNetStem +
# per-stage CenterNetStack) — the pipeline-parallel layout.  Pure renames
# mirroring the two ``__call__`` bodies (same scheme as
# models/hourglass.merge_stacked_variables).

def _cn_stage_name_map(s: int, num_stack: int) -> dict:
    """CenterNetStack submodule name → its name inside CenterNet for
    stack ``s``.  Monolithic call order per stack: HourglassModule, 3×3
    Conv+BN, three DetectionHeads, and (all but the last stack) the
    re-injection Conv — so the Conv counter advances 2 per stack (1 stem
    Conv before it) and DetectionHead 3 per stack."""
    m = {"HourglassModule_0": f"HourglassModule_{s}",
         "Conv_0": f"Conv_{1 + 2 * s}",
         "BatchNorm_0": f"BatchNorm_{1 + s}"}
    for j in range(3):
        m[f"DetectionHead_{j}"] = f"DetectionHead_{3 * s + j}"
    if s < num_stack - 1:
        m["Conv_1"] = f"Conv_{2 + 2 * s}"
    return m


def merge_centernet_variables(stem_vars, stage_vars_list) -> dict:
    """(CenterNetStem variables, [per-stage CenterNetStack variables]) →
    monolithic :class:`CenterNet` variables (the final stage's unused
    re-injection conv is dropped)."""
    num_stack = len(stage_vars_list)
    cols = set(stem_vars) | {c for v in stage_vars_list for c in v}
    out = {}
    for col in cols:
        merged = dict(stem_vars.get(col, {}))
        for s, sv in enumerate(stage_vars_list):
            names = _cn_stage_name_map(s, num_stack)
            for src, dst in names.items():
                if src in sv.get(col, {}):
                    merged[dst] = sv[col][src]
        out[col] = merged
    return out


def split_centernet_variables(variables, template_stage_vars
                              ) -> tuple[dict, list]:
    """Inverse of :func:`merge_centernet_variables`; the final stage's
    re-injection conv comes from ``template_stage_vars`` (absent in the
    monolithic net, receives no gradient)."""
    num_stack = len(template_stage_vars)
    stem_names = {"Conv_0", "BatchNorm_0", "PreActBottleneck_0"}
    stem_vars = {col: {k: v for k, v in tree.items() if k in stem_names}
                 for col, tree in variables.items()}
    stage_vars = []
    for s in range(num_stack):
        names = _cn_stage_name_map(s, num_stack)
        sv = {}
        for col, tree in variables.items():
            tmpl = template_stage_vars[s].get(col, {})
            sub = {src: tree[dst] for src, dst in names.items()
                   if dst in tree}
            for k in tmpl:
                if k not in sub:
                    sub[k] = tmpl[k]
            sv[col] = sub
        stage_vars.append(sv)
    return stem_vars, stage_vars
