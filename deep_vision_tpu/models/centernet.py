"""CenterNet ("Objects as Points") hourglass detector — completes the
reference's UNFINISHED ObjectsAsPoints stack (SURVEY §2.2 #18: empty
``loss_objects`` ObjectsAsPoints/tensorflow/train.py:35, trainer never run
:248, label gen stubbed to zeros preprocess.py:129-131).

Parity with the model that DOES exist (ObjectsAsPoints/tensorflow/model.py):
per-order filter tables :17-32 (order-5: 256,256,384,384,384,512),
BN-free ``DetectionHead`` 3-head (class heatmap / wh / offset) :72-91,
2-stack with re-injection :130-179.
"""

from __future__ import annotations

from typing import Any

import jax.numpy as jnp
from flax import linen as nn

from deep_vision_tpu.models.common import conv_kernel_init
from deep_vision_tpu.models.hourglass import HourglassModule, PreActBottleneck

# depth-indexed filters for the order-5 module (model.py:17-23)
CENTERNET_FILTERS = (256, 256, 384, 384, 384, 512)


class DetectionHead(nn.Module):
    """3×3 conv256+ReLU → 3×3 conv out, NO BatchNorm (model.py:72-78)."""

    out_features: int
    bias_init_value: float = 0.0  # heatmap head: -2.19 focal prior
    dtype: Any = jnp.float32
    features: int = 256

    @nn.compact
    def __call__(self, x):
        x = nn.Conv(self.features, (3, 3), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)
        x = nn.relu(x)
        x = nn.Conv(self.out_features, (3, 3), padding="SAME",
                    bias_init=nn.initializers.constant(self.bias_init_value),
                    dtype=self.dtype)(x)
        return x.astype(jnp.float32)


class CenterNet(nn.Module):
    """256²×3 → per-stack (heatmap_logits (64²,C), wh (64²,2), offset).

    ``order``/``filters`` default to the reference's order-5 table; smaller
    settings give a test-scale model (order must satisfy
    2**order ≤ input_size/4).
    """

    num_classes: int = 80
    num_stack: int = 2
    order: int = 5
    filters: tuple = CENTERNET_FILTERS
    dtype: Any = jnp.float32

    @nn.compact
    def __call__(self, x, train: bool = False):
        def bn():
            return nn.BatchNorm(use_running_average=not train, momentum=0.9,
                                dtype=self.dtype)

        base = self.filters[0]
        x = x.astype(self.dtype)
        x = nn.Conv(base // 2, (7, 7), (2, 2), padding="SAME",
                    kernel_init=conv_kernel_init, dtype=self.dtype)(x)  # /2
        x = nn.relu(bn()(x))
        x = PreActBottleneck(base, self.dtype)(x, train)
        x = nn.max_pool(x, (2, 2), (2, 2))                              # /4

        outputs = []
        for s in range(self.num_stack):
            y = HourglassModule(self.order, list(self.filters),
                                num_residual=1, dtype=self.dtype)(x, train)
            y = nn.Conv(base, (3, 3), padding="SAME",
                        kernel_init=conv_kernel_init, dtype=self.dtype)(y)
            y = nn.relu(bn()(y))
            # -2.19 bias prior: σ(-2.19)≈0.1 initial heatmap (CenterNet)
            heat = DetectionHead(self.num_classes, -2.19, self.dtype,
                                 features=base)(y)
            wh = DetectionHead(2, 0.0, self.dtype, features=base)(y)
            offset = DetectionHead(2, 0.0, self.dtype, features=base)(y)
            outputs.append((heat, wh, offset))
            if s < self.num_stack - 1:
                x = x + nn.Conv(base, (1, 1), dtype=self.dtype)(y)
        return tuple(outputs)
