"""MNIST idx-ubyte parsing — semantics of LeNet/pytorch/data_load.py:1-56,
vectorized (np.frombuffer instead of the reference's per-byte Python loop).

Images: 28×28 uint8 → zero-pad to 32×32 → NHWC float32 → normalize(mean,std).
"""

from __future__ import annotations

import gzip
import os

import numpy as np

MEAN, STD = 0.1307, 0.3081  # standard MNIST stats (the reference passes these)


def _open(path: str):
    return gzip.open(path, "rb") if path.endswith(".gz") else open(path, "rb")


def load_idx_images(path: str) -> np.ndarray:
    with _open(path) as f:
        b = f.read()
    magic = int.from_bytes(b[0:4], "big")
    assert magic == 2051, f"bad image idx magic {magic}"
    count = int.from_bytes(b[4:8], "big")
    rows = int.from_bytes(b[8:12], "big")
    cols = int.from_bytes(b[12:16], "big")
    images = np.frombuffer(b, np.uint8, count * rows * cols, offset=16)
    return images.reshape(count, rows, cols)


def load_idx_labels(path: str) -> np.ndarray:
    with _open(path) as f:
        b = f.read()
    magic = int.from_bytes(b[0:4], "big")
    assert magic == 2049, f"bad label idx magic {magic}"
    count = int.from_bytes(b[4:8], "big")
    return np.frombuffer(b, np.uint8, count, offset=8).astype(np.int32)


def pad_uint8(images: np.ndarray) -> np.ndarray:
    """uint8 (N,28,28) → uint8 NHWC (N,32,32,1): the geometric half of
    ``preprocess`` only — the wire stays 1 byte/pixel and the float
    normalize runs as a traced device prologue
    (ops/preprocess.make_mnist_preprocess)."""
    return np.pad(images, ((0, 0), (2, 2), (2, 2)), "constant")[..., None]


def preprocess(images: np.ndarray, mean: float = MEAN, std: float = STD) -> np.ndarray:
    """uint8 (N,28,28) → normalized float32 NHWC (N,32,32,1)."""
    x = np.pad(images, ((0, 0), (2, 2), (2, 2)), "constant")
    x = x.astype(np.float32) / 255.0
    x = (x - mean) / std
    return x[..., None]


def load_mnist(root: str, split: str = "train",
               device_normalize: bool = False) -> dict[str, np.ndarray]:
    """``device_normalize=True`` keeps the uint8 wire: images stay raw
    0–255 bytes (zero-padded to 32×32 — padding is dtype-agnostic) and
    the /255 + standardize runs on device inside the jitted step, so
    host batches, the prefetch queue, and the H2D DMA carry 4× fewer
    bytes.  False is the legacy host-normalized float32 path."""
    prefix = "train" if split == "train" else "t10k"
    names = [f"{prefix}-images-idx3-ubyte", f"{prefix}-labels-idx1-ubyte"]
    paths = []
    for name in names:
        for cand in (name, name + ".gz", name.replace("-idx", ".idx")):
            p = os.path.join(root, cand)
            if os.path.exists(p):
                paths.append(p)
                break
        else:
            raise FileNotFoundError(f"{name}[.gz] not under {root}")
    raw = load_idx_images(paths[0])
    image = pad_uint8(raw) if device_normalize else preprocess(raw)
    return {"image": image, "label": load_idx_labels(paths[1])}


def synthetic_mnist(n: int = 512, seed: int = 0, num_classes: int = 10
                    ) -> dict[str, np.ndarray]:
    """Learnable synthetic digits for smoke tests (MNIST-shaped wrapper)."""
    from deep_vision_tpu.data.synthetic import synthetic_classification

    return synthetic_classification(n, 32, 1, num_classes, seed)
