__all__ = ["ArrayLoader", "prefetch_to_device", "DevicePrefetcher",
           "HostStagingPool"]

_PIPELINE = {"DevicePrefetcher", "HostStagingPool"}


def __getattr__(name):
    # lazy re-export (PEP 562): loader/pipeline import jax, and data-pipeline
    # worker processes (spawn/forkserver) import submodules of this package —
    # they must not pay a full JAX import + RSS each just to reach the
    # numpy-only decode/transform code
    if name in _PIPELINE:
        from deep_vision_tpu.data import pipeline

        return getattr(pipeline, name)
    if name in __all__:
        from deep_vision_tpu.data import loader

        return getattr(loader, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
