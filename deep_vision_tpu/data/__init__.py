from deep_vision_tpu.data.loader import ArrayLoader, prefetch_to_device

__all__ = ["ArrayLoader", "prefetch_to_device"]
