// Native half of the raw-dvrec read path (SURVEY §7 hard-part 1: feed the
// chip from one host core).  The role the reference's data loaders get from
// torch/TF's C++ internals (ResNet/pytorch/train.py:229-234 DataLoader
// workers), done dvrec-native: one call assembles a whole training batch —
// positioned reads straight from the shard files, crop + horizontal flip
// fused into the copy into the caller's preallocated (B, S, S, 3) buffer.
// No decode (payloads are raw uint8 from `prepare_data --store raw`), no
// per-image Python, no intermediate copies.  One entry point, one job.
//
// Built by data/native/__init__.py with the system C++ toolchain (g++ via
// cc) into a shared object loaded with ctypes; the Python path remains the
// fallback wherever a toolchain is missing.

#include <cstdint>
#include <cstdio>
#include <cstring>

#include <fcntl.h>
#include <unistd.h>

extern "C" {

// Assemble one batch of crops from raw-uint8 dvrec payloads.
//   fds:        per-item open file descriptors (shard files)
//   offsets:    per-item payload byte offsets
//   heights/widths: per-item stored image dims (channels fixed at 3)
//   tops/lefts: per-item crop origin (in the flipped image when flip=1)
//   flips:      per-item horizontal-flip flag
//   crop:       output square side S
//   out:        (n, S, S, 3) uint8, C-contiguous
//   scratch:    caller-provided buffer of at least max_payload bytes
// Returns 0 on success, -(i+1) if the read for item i failed.
int dvrec_assemble_batch(const int32_t* fds, const int64_t* offsets,
                         const int32_t* heights, const int32_t* widths,
                         const int32_t* tops, const int32_t* lefts,
                         const uint8_t* flips, int32_t n, int32_t crop,
                         uint8_t* out, uint8_t* scratch) {
  const int64_t row_out = static_cast<int64_t>(crop) * 3;
  for (int32_t i = 0; i < n; ++i) {
    const int64_t h = heights[i], w = widths[i];
    const int64_t payload = h * w * 3;
    int64_t done = 0;
    while (done < payload) {
      ssize_t got = pread(fds[i], scratch + done, payload - done,
                          offsets[i] + done);
      if (got <= 0) return -(i + 1);
      done += got;
    }
    uint8_t* dst = out + static_cast<int64_t>(i) * crop * row_out;
    const int64_t top = tops[i], left = lefts[i];
    if (!flips[i]) {
      for (int64_t r = 0; r < crop; ++r) {
        const uint8_t* src = scratch + ((top + r) * w + left) * 3;
        memcpy(dst + r * row_out, src, row_out);
      }
    } else {
      // crop coordinates address the FLIPPED image (matching
      // transforms.train_transform_u8: flip THEN crop): flipped column
      // left+c maps to stored column w-1-(left+c)
      for (int64_t r = 0; r < crop; ++r) {
        const uint8_t* src_row = scratch + (top + r) * w * 3;
        uint8_t* dst_row = dst + r * row_out;
        for (int64_t c = 0; c < crop; ++c) {
          const uint8_t* px = src_row + (w - 1 - left - c) * 3;
          dst_row[c * 3 + 0] = px[0];
          dst_row[c * 3 + 1] = px[1];
          dst_row[c * 3 + 2] = px[2];
        }
      }
    }
  }
  return 0;
}

}  // extern "C"
