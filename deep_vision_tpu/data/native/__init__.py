"""ctypes loader for the native dvrec reader (dvrec_reader.cc).

Compiles the shared object on first use with the system C++ toolchain
(g++/cc) into ``~/.cache/deep_vision_tpu`` (keyed by source hash, so
edits rebuild automatically) and exposes the entry point.  Every caller
must treat ``load() is None`` as "no toolchain" and keep the numpy
fallback — the native path is an accelerator, not a dependency.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import shutil
import subprocess

_SRC = os.path.join(os.path.dirname(__file__), "dvrec_reader.cc")
_LIB = None
_TRIED = False


def _build() -> str | None:
    with open(_SRC, "rb") as f:
        tag = hashlib.sha256(f.read()).hexdigest()[:16]
    cache = os.environ.get(
        "DEEP_VISION_TPU_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "deep_vision_tpu"))
    out = os.path.join(cache, f"dvrec_reader_{tag}.so")
    if os.path.exists(out):
        return out
    cxx = os.environ.get("CXX") or shutil.which("g++") or shutil.which("c++")
    if cxx is None:
        return None
    os.makedirs(cache, exist_ok=True)
    tmp = out + f".tmp{os.getpid()}"
    try:
        subprocess.run(
            [cxx, "-O3", "-shared", "-fPIC", "-std=c++17", _SRC, "-o", tmp],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, out)  # atomic: concurrent builders race safely
        return out
    except Exception:  # noqa: BLE001 — no toolchain / failed compile: fall back to pure NumPy
        if os.path.exists(tmp):
            os.remove(tmp)
        return None


def load() -> ctypes.CDLL | None:
    """The compiled library, or None when no toolchain is available."""
    global _LIB, _TRIED
    if _TRIED:
        return _LIB
    _TRIED = True
    if os.environ.get("DEEP_VISION_TPU_NO_NATIVE"):
        return None
    path = _build()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
        lib.dvrec_assemble_batch.restype = ctypes.c_int32
        lib.dvrec_assemble_batch.argtypes = [
            ctypes.POINTER(ctypes.c_int32),   # fds
            ctypes.POINTER(ctypes.c_int64),   # offsets
            ctypes.POINTER(ctypes.c_int32),   # heights
            ctypes.POINTER(ctypes.c_int32),   # widths
            ctypes.POINTER(ctypes.c_int32),   # tops
            ctypes.POINTER(ctypes.c_int32),   # lefts
            ctypes.POINTER(ctypes.c_uint8),   # flips
            ctypes.c_int32,                   # n
            ctypes.c_int32,                   # crop
            ctypes.POINTER(ctypes.c_uint8),   # out
            ctypes.POINTER(ctypes.c_uint8),   # scratch
        ]
        _LIB = lib
    except OSError:
        _LIB = None
    return _LIB
