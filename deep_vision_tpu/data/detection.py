"""Detection input pipeline — parity with YOLO/tensorflow/preprocess.py:
bbox-preserving random flip (:37-50) and random crop (:52-119), resize to the
model input size, then 3-scale grid label encoding
(``tasks.detection.encode_labels``, the vectorized port of :137-224).

Samples are dicts {"image": HWC uint8, "boxes": (N,4) normalized corner
boxes, "classes": (N,) int}.  The loader emits static-shape batches:
{"image": (B,S,S,3) f32, "y_true_0..2", "boxes", "boxes_mask"}.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from deep_vision_tpu.data.transforms import rescale
from deep_vision_tpu.tasks.detection import encode_labels


def flip_boxes_lr(boxes: np.ndarray) -> np.ndarray:
    """(N,4) normalized corners (x1,y1,x2,y2) under horizontal flip."""
    out = boxes.copy()
    out[:, 0] = 1.0 - boxes[:, 2]
    out[:, 2] = 1.0 - boxes[:, 0]
    return out


def random_crop_with_boxes(img: np.ndarray, boxes: np.ndarray,
                           rng: np.random.Generator):
    """Box-preserving random crop — exact semantics of the reference's
    ``get_random_crop_delta`` + ``random_crop_image_and_label``
    (YOLO/tensorflow/preprocess.py:52-119): sample one margin per side
    uniformly between the union hull of ALL boxes and the image edge, so
    the crop always contains every box in full; boxes are renormalized by
    the delta formula (new = (old - lo_delta) / (1 - lo_delta - hi_delta)).

    Returns (crop, new_boxes, keep) — keep is all-True (kept for caller
    symmetry with flip/other augmentations that can drop boxes).
    """
    h, w = img.shape[:2]
    if len(boxes) == 0:
        return img, boxes, np.zeros((0,), bool)
    # normalized slack between the hull of all boxes and each image edge
    dx1 = rng.uniform(0, max(0.0, boxes[:, 0].min()))
    dy1 = rng.uniform(0, max(0.0, boxes[:, 1].min()))
    dx2 = rng.uniform(0, max(0.0, 1.0 - boxes[:, 2].max()))
    dy2 = rng.uniform(0, max(0.0, 1.0 - boxes[:, 3].max()))
    new_w = 1.0 - dx1 - dx2
    new_h = 1.0 - dy1 - dy2
    out = boxes.copy()
    out[:, [0, 2]] = (boxes[:, [0, 2]] - dx1) / max(new_w, 1e-9)
    out[:, [1, 3]] = (boxes[:, [1, 3]] - dy1) / max(new_h, 1e-9)
    oy, ox = int(dy1 * h), int(dx1 * w)
    th = max(1, int(np.ceil(new_h * h)))
    tw = max(1, int(np.ceil(new_w * w)))
    crop = img[oy:oy + th, ox:ox + tw]
    out = np.clip(out, 0.0, 1.0).astype(np.float32)
    return crop, out, np.ones(len(boxes), bool)


def resize_square(img: np.ndarray, size: int) -> np.ndarray:
    """Plain square resize (the reference resizes to 416² after crop)."""
    from deep_vision_tpu.data.transforms import resize_bilinear

    return resize_bilinear(img, size, size)


def corners_to_xywh(boxes: np.ndarray) -> np.ndarray:
    xy = (boxes[:, :2] + boxes[:, 2:4]) / 2
    wh = boxes[:, 2:4] - boxes[:, :2]
    return np.concatenate([xy, wh], axis=1)


def _augment_resize(sample: dict, rng: np.random.Generator,
                    image_size: int, augment: bool, crop: bool,
                    device_normalize: bool):
    """Shared prep front half: flip[/crop] → resize → (uint8 | f32/255).
    With ``device_normalize`` the image stays uint8 (4× smaller H2D
    payload; the /255 scale runs inside the jitted step,
    ops/preprocess.py)."""
    img = sample["image"]
    boxes = np.asarray(sample["boxes"], np.float32).reshape(-1, 4)
    classes = np.asarray(sample["classes"], np.int64).reshape(-1)
    if augment and len(boxes):
        if rng.random() < 0.5:
            img = img[:, ::-1]
            boxes = flip_boxes_lr(boxes)
        if crop and rng.random() < 0.5:
            img, boxes, keep = random_crop_with_boxes(img, boxes, rng)
            classes = classes[keep]
    img = resize_square(img, image_size)
    x = img if device_normalize else img.astype(np.float32) / 255.0
    return x, boxes, classes


def prepare_yolo_sample(sample: dict, rng: np.random.Generator, *,
                        num_classes: int, image_size: int, grids,
                        augment: bool, device_normalize: bool = False
                        ) -> dict:
    x, boxes, classes = _augment_resize(sample, rng, image_size, augment,
                                        crop=True,
                                        device_normalize=device_normalize)
    enc = encode_labels(corners_to_xywh(boxes), classes, num_classes,
                        grids=grids)
    return {"image": x, **enc}


def prepare_centernet_sample(sample: dict, rng: np.random.Generator, *,
                             num_classes: int, image_size: int, grids,
                             augment: bool, device_normalize: bool = False
                             ) -> dict:
    from deep_vision_tpu.tasks.centernet import encode_centernet_labels

    x, boxes, classes = _augment_resize(sample, rng, image_size, augment,
                                        crop=False,
                                        device_normalize=device_normalize)
    enc = encode_centernet_labels(
        corners_to_xywh(boxes), classes, num_classes,
        grid=image_size // 4)
    return {"image": x, **enc}


# worker-side state: initialized once per worker process (the 0-worker
# path calls the prepare function inline with the same per-item rng, so
# pooled and sequential iteration yield IDENTICAL batches)
_DET_WORKER: dict = {}


def _det_worker_init(cfg: dict):
    _DET_WORKER.update(cfg)


def _det_prepare(args: tuple) -> dict:
    i, epoch = args
    w = _DET_WORKER
    rng = np.random.default_rng((w["seed"], epoch, int(i)))
    return w["prepare"](w["samples"][i], rng, **w["kwargs"])


class DetectionLoader:
    """Batch iterator over an in-memory/detection-record dataset.

    ``samples``: sequence of dicts (see module docstring) or a callable
    ``index -> sample`` plus ``length``.

    Per-item augmentation rng derives from ``(seed, epoch, sample_index)``
    — deterministic and independent of iteration order or worker count.
    ``num_workers`` > 0 preps samples in a process pool (forkserver;
    samples ship to workers once at pool creation); lazy record samples
    decode in the workers, parallelizing the JPEG decode that dominates
    the cold-epoch cost.
    """

    PREPARE = staticmethod(prepare_yolo_sample)

    def __init__(self, samples: Sequence[dict], batch_size: int,
                 num_classes: int, image_size: int = 416,
                 grids: Sequence[int] | None = None,
                 train: bool = True, seed: int = 0, augment: bool = True,
                 device_normalize: bool = False, num_workers: int = 0,
                 prefetch_batches: int = 2):
        self.samples = samples
        self.batch_size = batch_size
        self.num_classes = num_classes
        self.image_size = image_size
        self.grids = tuple(grids) if grids else (
            image_size // 8, image_size // 16, image_size // 32)
        self.train = train
        self.seed = seed
        self.augment = augment and train
        self.device_normalize = device_normalize
        self.num_workers = num_workers
        self.prefetch_batches = max(1, prefetch_batches)
        self.epoch = 0
        self._pool = None
        if num_workers > 0:
            import multiprocessing as mp

            # forkserver, NOT fork: the JAX runtime has live threads by
            # loader-construction time (same rationale as ImageNetLoader)
            try:
                ctx = mp.get_context("forkserver")
            except ValueError:
                ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(
                num_workers, initializer=_det_worker_init,
                initargs=(dict(samples=samples, seed=seed,
                               prepare=type(self).PREPARE,
                               kwargs=self._prep_kwargs()),))

    def _prep_kwargs(self) -> dict:
        return dict(num_classes=self.num_classes,
                    image_size=self.image_size, grids=self.grids,
                    augment=self.augment,
                    device_normalize=self.device_normalize)

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self) -> int:
        full = len(self.samples) // self.batch_size
        if not self.train and len(self.samples) % self.batch_size:
            return full + 1  # eval covers the FULL set (padded last batch)
        return full

    def _prepare_indexed(self, i: int, epoch: int) -> dict:
        rng = np.random.default_rng((self.seed, epoch, int(i)))
        return type(self).PREPARE(self.samples[i], rng,
                                  **self._prep_kwargs())

    def close(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool.join()
            self._pool = None

    def __iter__(self) -> Iterator[dict]:
        from collections import deque

        from deep_vision_tpu.data.loader import pad_eval_indices

        order = np.random.default_rng((self.seed, self.epoch))
        idx = np.arange(len(self.samples))
        if self.train:
            order.shuffle(idx)
        # weight-0 fillers keep the batch shape static; loss metrics
        # and the mAP accumulator both honor the weight mask
        plan = [pad_eval_indices(idx, b * self.batch_size, self.batch_size)
                for b in range(len(self))]
        if self._pool is not None:
            # keep prefetch_batches async batches in flight so worker
            # decode overlaps the consumer's device step
            chunk = max(1, self.batch_size // (2 * self.num_workers))
            pending: deque = deque()
            submit = 0
            for b in range(len(plan)):
                while submit < len(plan) and len(pending) < \
                        self.prefetch_batches:
                    args = [(int(i), self.epoch) for i in plan[submit][0]]
                    pending.append(self._pool.map_async(
                        _det_prepare, args, chunksize=chunk))
                    submit += 1
                items = pending.popleft().get()
                yield self._assemble(items, plan[b][1])
        else:
            for sel, weight, _ in plan:
                items = [self._prepare_indexed(int(i), self.epoch)
                         for i in sel]
                yield self._assemble(items, weight)

    def _assemble(self, items: list, weight) -> dict:
        batch = {k: np.stack([it[k] for it in items]) for k in items[0]}
        if not self.train:
            batch["weight"] = weight
        return batch


class CenterNetLoader(DetectionLoader):
    """Same sample format/augmentation, CenterNet target encoding
    (tasks.centernet.encode_centernet_labels) at stride-4 resolution."""

    PREPARE = staticmethod(prepare_centernet_sample)


def synthetic_detection_dataset(n: int, image_size: int = 416,
                                num_classes: int = 3, seed: int = 0
                                ) -> list[dict]:
    """Learnable synthetic scenes: colored rectangles on noise, class =
    color; the detection analog of ``synthetic_classification``."""
    rng = np.random.default_rng(seed)
    palette = rng.integers(64, 255, size=(num_classes, 3))
    samples = []
    for _ in range(n):
        img = rng.integers(0, 64, size=(image_size, image_size, 3),
                           dtype=np.uint8)
        k = int(rng.integers(1, 4))
        boxes, classes = [], []
        for _ in range(k):
            w = rng.uniform(0.15, 0.5)
            h = rng.uniform(0.15, 0.5)
            x1 = rng.uniform(0, 1 - w)
            y1 = rng.uniform(0, 1 - h)
            c = int(rng.integers(0, num_classes))
            px = [int(x1 * image_size), int(y1 * image_size),
                  int((x1 + w) * image_size), int((y1 + h) * image_size)]
            img[px[1]:px[3], px[0]:px[2]] = palette[c]
            boxes.append([x1, y1, x1 + w, y1 + h])
            classes.append(c)
        samples.append({"image": img,
                        "boxes": np.asarray(boxes, np.float32),
                        "classes": np.asarray(classes, np.int64)})
    return samples
