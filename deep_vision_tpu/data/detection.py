"""Detection input pipeline — parity with YOLO/tensorflow/preprocess.py:
bbox-preserving random flip (:37-50) and random crop (:52-119), resize to the
model input size, then 3-scale grid label encoding
(``tasks.detection.encode_labels``, the vectorized port of :137-224).

Samples are dicts {"image": HWC uint8, "boxes": (N,4) normalized corner
boxes, "classes": (N,) int}.  The loader emits static-shape batches:
{"image": (B,S,S,3) f32, "y_true_0..2", "boxes", "boxes_mask"}.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from deep_vision_tpu.data.loader import PreppedSampleLoader
from deep_vision_tpu.data.transforms import rescale
from deep_vision_tpu.tasks.detection import encode_labels


def flip_boxes_lr(boxes: np.ndarray) -> np.ndarray:
    """(N,4) normalized corners (x1,y1,x2,y2) under horizontal flip."""
    out = boxes.copy()
    out[:, 0] = 1.0 - boxes[:, 2]
    out[:, 2] = 1.0 - boxes[:, 0]
    return out


def random_crop_with_boxes(img: np.ndarray, boxes: np.ndarray,
                           rng: np.random.Generator):
    """Box-preserving random crop — exact semantics of the reference's
    ``get_random_crop_delta`` + ``random_crop_image_and_label``
    (YOLO/tensorflow/preprocess.py:52-119): sample one margin per side
    uniformly between the union hull of ALL boxes and the image edge, so
    the crop always contains every box in full; boxes are renormalized by
    the delta formula (new = (old - lo_delta) / (1 - lo_delta - hi_delta)).

    Returns (crop, new_boxes, keep) — keep is all-True (kept for caller
    symmetry with flip/other augmentations that can drop boxes).
    """
    h, w = img.shape[:2]
    if len(boxes) == 0:
        return img, boxes, np.zeros((0,), bool)
    # normalized slack between the hull of all boxes and each image edge
    dx1 = rng.uniform(0, max(0.0, boxes[:, 0].min()))
    dy1 = rng.uniform(0, max(0.0, boxes[:, 1].min()))
    dx2 = rng.uniform(0, max(0.0, 1.0 - boxes[:, 2].max()))
    dy2 = rng.uniform(0, max(0.0, 1.0 - boxes[:, 3].max()))
    new_w = 1.0 - dx1 - dx2
    new_h = 1.0 - dy1 - dy2
    out = boxes.copy()
    out[:, [0, 2]] = (boxes[:, [0, 2]] - dx1) / max(new_w, 1e-9)
    out[:, [1, 3]] = (boxes[:, [1, 3]] - dy1) / max(new_h, 1e-9)
    oy, ox = int(dy1 * h), int(dx1 * w)
    th = max(1, int(np.ceil(new_h * h)))
    tw = max(1, int(np.ceil(new_w * w)))
    crop = img[oy:oy + th, ox:ox + tw]
    out = np.clip(out, 0.0, 1.0).astype(np.float32)
    return crop, out, np.ones(len(boxes), bool)


def resize_square(img: np.ndarray, size: int) -> np.ndarray:
    """Plain square resize (the reference resizes to 416² after crop)."""
    from deep_vision_tpu.data.transforms import resize_bilinear

    return resize_bilinear(img, size, size)


def corners_to_xywh(boxes: np.ndarray) -> np.ndarray:
    xy = (boxes[:, :2] + boxes[:, 2:4]) / 2
    wh = boxes[:, 2:4] - boxes[:, :2]
    return np.concatenate([xy, wh], axis=1)


def _augment_resize(sample: dict, rng: np.random.Generator,
                    image_size: int, augment: bool, crop: bool,
                    device_normalize: bool):
    """Shared prep front half: flip[/crop] → resize → (uint8 | f32/255).
    With ``device_normalize`` the image stays uint8 (4× smaller H2D
    payload; the /255 scale runs inside the jitted step,
    ops/preprocess.py)."""
    img = sample["image"]
    boxes = np.asarray(sample["boxes"], np.float32).reshape(-1, 4)
    classes = np.asarray(sample["classes"], np.int64).reshape(-1)
    if augment and len(boxes):
        if rng.random() < 0.5:
            img = img[:, ::-1]
            boxes = flip_boxes_lr(boxes)
        if crop and rng.random() < 0.5:
            img, boxes, keep = random_crop_with_boxes(img, boxes, rng)
            classes = classes[keep]
    img = resize_square(img, image_size)
    x = img if device_normalize else img.astype(np.float32) / 255.0
    return x, boxes, classes


def prepare_yolo_sample(sample: dict, rng: np.random.Generator, *,
                        num_classes: int, image_size: int, grids,
                        augment: bool, device_normalize: bool = False
                        ) -> dict:
    x, boxes, classes = _augment_resize(sample, rng, image_size, augment,
                                        crop=True,
                                        device_normalize=device_normalize)
    enc = encode_labels(corners_to_xywh(boxes), classes, num_classes,
                        grids=grids)
    return {"image": x, **enc}


def prepare_centernet_sample(sample: dict, rng: np.random.Generator, *,
                             num_classes: int, image_size: int, grids,
                             augment: bool, device_normalize: bool = False
                             ) -> dict:
    from deep_vision_tpu.tasks.centernet import encode_centernet_labels

    x, boxes, classes = _augment_resize(sample, rng, image_size, augment,
                                        crop=False,
                                        device_normalize=device_normalize)
    enc = encode_centernet_labels(
        corners_to_xywh(boxes), classes, num_classes,
        grid=image_size // 4)
    return {"image": x, **enc}


class DetectionLoader(PreppedSampleLoader):
    """Batch iterator over an in-memory/detection-record dataset.

    ``samples``: sequence of dicts (see module docstring) or a callable
    ``index -> sample`` plus ``length``.  Pool/prefetch/rng semantics:
    :class:`~deep_vision_tpu.data.loader.PreppedSampleLoader`.
    """

    PREPARE = staticmethod(prepare_yolo_sample)

    def __init__(self, samples: Sequence[dict], batch_size: int,
                 num_classes: int, image_size: int = 416,
                 grids: Sequence[int] | None = None,
                 train: bool = True, seed: int = 0, augment: bool = True,
                 device_normalize: bool = False, num_workers: int = 0,
                 prefetch_batches: int = 2):
        self.num_classes = num_classes
        self.image_size = image_size
        self.grids = tuple(grids) if grids else (
            image_size // 8, image_size // 16, image_size // 32)
        self.augment = augment and train
        self.device_normalize = device_normalize
        super().__init__(samples, batch_size, train, seed, num_workers,
                         prefetch_batches)

    def _prep_kwargs(self) -> dict:
        return dict(num_classes=self.num_classes,
                    image_size=self.image_size, grids=self.grids,
                    augment=self.augment,
                    device_normalize=self.device_normalize)


class CenterNetLoader(DetectionLoader):
    """Same sample format/augmentation, CenterNet target encoding
    (tasks.centernet.encode_centernet_labels) at stride-4 resolution."""

    PREPARE = staticmethod(prepare_centernet_sample)


def synthetic_detection_dataset(n: int, image_size: int = 416,
                                num_classes: int = 3, seed: int = 0
                                ) -> list[dict]:
    """Learnable synthetic scenes: colored rectangles on noise, class =
    color; the detection analog of ``synthetic_classification``."""
    rng = np.random.default_rng(seed)
    palette = rng.integers(64, 255, size=(num_classes, 3))
    samples = []
    for _ in range(n):
        img = rng.integers(0, 64, size=(image_size, image_size, 3),
                           dtype=np.uint8)
        k = int(rng.integers(1, 4))
        boxes, classes = [], []
        for _ in range(k):
            w = rng.uniform(0.15, 0.5)
            h = rng.uniform(0.15, 0.5)
            x1 = rng.uniform(0, 1 - w)
            y1 = rng.uniform(0, 1 - h)
            c = int(rng.integers(0, num_classes))
            px = [int(x1 * image_size), int(y1 * image_size),
                  int((x1 + w) * image_size), int((y1 + h) * image_size)]
            img[px[1]:px[3], px[0]:px[2]] = palette[c]
            boxes.append([x1, y1, x1 + w, y1 + h])
            classes.append(c)
        samples.append({"image": img,
                        "boxes": np.asarray(boxes, np.float32),
                        "classes": np.asarray(classes, np.int64)})
    return samples
