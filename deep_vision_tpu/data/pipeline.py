"""Staged train-input pipeline: host staging pool + ``DevicePrefetcher``.

This is the serving wire stack (PR 2's ``StagingPool``, PR 5's uint8 wire,
the engine's pipelined H2D) ported to the *training* side, replacing the
single background thread in :func:`deep_vision_tpu.data.loader.prefetch_to_device`.
Per batch the producer thread runs four stages:

    prep_wait → assemble → h2d → enqueue

``prep_wait`` is time blocked on the upstream loader (worker pool /
augmentation), ``assemble`` copies the host batch into a reused staging
buffer (the DMA-source the runtime reads from — steady state holds at
most ``depth + 1`` buffers per distinct leaf shape when the backend
copies on H2D, one more when the CPU runtime zero-copies and release is
deferred to the device array's GC; reused forever either way),
``h2d`` issues the sharded ``device_put`` and waits for the transfer, and
``enqueue`` hands the *device* batch to the bounded queue.  The consumer
side records two stages — ``stall`` (time the train loop waited on the
queue: input-bound) and ``step`` (time between dequeues: compute-bound) —
in the :class:`deep_vision_tpu.obs.trace.Span` style, so each side's
stages sum exactly to its wall time by construction and

    input_stall_frac = stall / (stall + step)

is the honest "how much of the epoch was spent waiting on input" number
(docs/PERF.md "Input pipeline").  H2D traffic is accounted per batch key
(``h2d_bytes_by_key``) so the uint8-vs-float32 wire ratio is measured on
the image tensor alone, not diluted by labels.

Unlike the legacy generator, an epoch here is abandonable: ``close()``
(called from ``Trainer.fit``'s finally path, and from the legacy shim's
``finally``) sets the stop event, drains the queue so a blocked producer
``put`` unblocks, and joins the thread — a preempted or diverged epoch
leaves no daemon thread behind and no device batches pinned in the queue.
"""

from __future__ import annotations

import queue
import threading
import weakref
from typing import Any, Callable, Iterable

import jax
import numpy as np

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.trace import Span
from deep_vision_tpu.parallel.mesh import shard_batch

__all__ = ["HostStagingPool", "DevicePrefetcher"]

_END = object()


def _leaf_name(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", getattr(p, "idx", getattr(p, "name", None)))
        parts.append(str(key) if key is not None else str(p))
    return "/".join(parts) if parts else "batch"


class HostStagingPool:
    """Per-(shape, dtype) free-list of host staging buffers.

    The serving ``StagingPool`` contract generalized to arbitrary batch
    pytrees: a buffer is checked out at assemble, pinned until its H2D
    completes (the runtime may read it asynchronously — or, CPU
    zero-copy, for the device array's whole life), then returned.
    ``allocated``/``reused`` make the reuse testable — an epoch of N
    batches must allocate at most ``depth + 2`` buffers per distinct
    leaf shape, not N.
    """

    def __init__(self):
        self._free: dict[tuple, list[np.ndarray]] = {}  # guarded-by: _lock
        self._lock = new_lock("data.pipeline.HostStagingPool._lock")
        self.allocated = 0  # guarded-by: _lock
        self.reused = 0  # guarded-by: _lock

    def acquire(self, shape: tuple, dtype) -> np.ndarray:
        key = (tuple(shape), np.dtype(dtype).str)
        with self._lock:
            free = self._free.setdefault(key, [])
            if free:
                self.reused += 1
                return free.pop()
            self.allocated += 1
        return np.empty(shape, dtype)

    def release(self, buf: np.ndarray):
        key = (buf.shape, buf.dtype.str)
        with self._lock:
            self._free.setdefault(key, []).append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {
                "allocated": self.allocated,
                "reused": self.reused,
                "pooled": {str(k): len(v) for k, v in self._free.items()},
            }


class _EpochStream:
    """One epoch's staged batch stream (created by ``DevicePrefetcher.iterate``).

    Producer thread owns ``_pspan`` (prep_wait/assemble/h2d/enqueue marks),
    the consumer owns ``_cspan`` (stall/step) — the Span ownership rule, so
    neither side's marks race the other's.
    """

    def __init__(self, mesh, iterable: Iterable, depth: int,
                 pool: HostStagingPool,
                 host_transform: Callable[[Any], Any] | None = None):
        self.mesh = mesh
        self.depth = depth
        self._pool = pool
        self._iterable = iterable
        self._host_transform = host_transform
        self._q: queue.Queue = queue.Queue(maxsize=depth)
        self._stop = threading.Event()
        self._error: BaseException | None = None
        self._pspan = Span(request_id="producer", origin="start")
        self._cspan = Span(request_id="consumer", origin="start")
        self._first_get = True
        self._done = False
        self.batches = 0            # consumer-side: batches yielded
        self.h2d_bytes = 0          # producer-side until join; then stable
        self.h2d_bytes_by_key: dict[str, int] = {}
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="dvt-prefetch")
        self._thread.start()

    # -- producer ------------------------------------------------------------

    def _offer(self, item) -> bool:
        """Bounded put that gives up when the epoch is closed — the fix for
        the legacy producer blocking forever on ``q.put`` after the consumer
        abandoned the iterator."""
        while not self._stop.is_set():
            try:
                self._q.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _stage(self, item):
        """Copy host leaves into pooled staging buffers (the DMA source).

        Returns the staged pytree plus the checked-out buffers; 0-d leaves
        and already-placed ``jax.Array`` leaves pass through un-pooled.
        """
        leaves, treedef = jax.tree_util.tree_flatten_with_path(item)
        staged, bufs = [], []
        for path, leaf in leaves:
            if isinstance(leaf, jax.Array):  # already on device: no H2D
                staged.append(leaf)
                continue
            arr = np.asarray(leaf)
            name = _leaf_name(path)
            self.h2d_bytes += arr.nbytes
            self.h2d_bytes_by_key[name] = \
                self.h2d_bytes_by_key.get(name, 0) + arr.nbytes
            if arr.ndim == 0:
                staged.append(arr)
                continue
            buf = self._pool.acquire(arr.shape, arr.dtype)
            np.copyto(buf, arr)
            bufs.append(buf)
            staged.append(buf)
        return jax.tree_util.tree_unflatten(treedef, staged), bufs

    @staticmethod
    def _zero_copied(dev_leaf, buf: np.ndarray) -> bool:
        """Did the backend alias ``buf`` instead of copying it?

        The CPU runtime zero-copies suitably-aligned host arrays into
        ``device_put`` results — the jax.Array then READS the host buffer
        for its whole lifetime, so the H2D fence proves nothing about
        reusability.  Compare device buffer pointers against the staging
        buffer's range; anything unprovable counts as aliased (release is
        deferred, never unsafe).  Real accelerator transfers are DMA
        copies and never hit this."""
        try:
            ptrs = [s.data.unsafe_buffer_pointer()
                    for s in dev_leaf.addressable_shards]
        except Exception:  # noqa: BLE001 — can't prove a copy happened
            return True
        lo = buf.ctypes.data
        return any(lo <= p < lo + buf.nbytes for p in ptrs)

    def _release(self, staged, dev, bufs: list):
        """Return staging buffers to the pool: immediately when the
        runtime copied them, else (CPU zero-copy) deferred to the device
        array's GC — releasing early lets the next batch overwrite bytes
        a queued batch still reads (batch N shows batch N+2's pixels)."""
        if not bufs:
            return
        by_id = {id(b): b for b in bufs}
        for s, d in zip(jax.tree_util.tree_leaves(staged),
                        jax.tree_util.tree_leaves(dev)):
            buf = by_id.pop(id(s), None)
            if buf is None:
                continue
            if self._zero_copied(d, buf):
                weakref.finalize(d, self._pool.release, buf)
            else:
                self._pool.release(buf)

    def _loop(self):  # dvtlint: hot
        try:
            it = iter(self._iterable)
            while not self._stop.is_set():
                try:
                    item = next(it)
                except StopIteration:
                    break
                self._pspan.mark("prep_wait")
                if self._host_transform is not None:
                    item = self._host_transform(item)
                staged, bufs = self._stage(item)
                self._pspan.mark("assemble")
                dev = shard_batch(staged, self.mesh)
                # wait for the transfer so the staging buffers are reusable
                # (this thread overlaps the consumer's compute, so the wait
                # costs pipeline depth, not step time)
                jax.block_until_ready(dev)  # dvtlint: disable=DVT003 — H2D fence off the compute thread, releases staging buffers
                self._release(staged, dev, bufs)
                self._pspan.mark("h2d")
                if not self._offer(dev):
                    return
                self._pspan.mark("enqueue")
        except BaseException as e:  # noqa: BLE001 — re-raised consumer-side
            self._error = e
        finally:
            self._offer(_END)

    # -- consumer ------------------------------------------------------------

    def __iter__(self):
        return self

    def __next__(self):
        if self._done:
            raise StopIteration
        if not self._first_get:
            self._cspan.mark("step")
        self._first_get = False
        while True:
            try:
                item = self._q.get(timeout=0.1)
                break
            except queue.Empty:
                if self._stop.is_set():
                    self._done = True
                    raise StopIteration from None
        self._cspan.mark("stall")
        if item is _END:
            self._done = True
            self._thread.join(timeout=5.0)
            if self._error is not None:
                raise self._error
            raise StopIteration
        self.batches += 1
        return item

    def close(self):
        """Stop the producer, drain pinned device batches, join the thread.

        Idempotent; safe mid-epoch (abandoned iteration) and after normal
        exhaustion."""
        self._stop.set()
        self._done = True
        try:
            while True:
                self._q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)

    @property
    def alive(self) -> bool:
        return self._thread.is_alive()

    def stats(self) -> dict:
        """Per-epoch input-goodput block (the trainer logs this verbatim)."""
        prod = self._pspan.to_dict()["stages"]
        cons = self._cspan.to_dict()["stages"]
        stall_ms = cons.get("stall", 0.0)
        step_ms = cons.get("step", 0.0)
        wall_ms = stall_ms + step_ms
        n = max(1, self.batches)
        return {
            "batches": self.batches,
            "input_stall_frac": stall_ms / wall_ms if wall_ms > 0 else 0.0,
            "stall_ms": round(stall_ms, 3),
            "step_ms": round(step_ms, 3),
            "h2d_bytes": self.h2d_bytes,
            "h2d_bytes_per_step": self.h2d_bytes / n,
            "h2d_bytes_by_key": dict(self.h2d_bytes_by_key),
            "producer_ms": {k: round(v, 3) for k, v in prod.items()},
            "pool": self._pool.stats(),
        }


class DevicePrefetcher:
    """Staged, abandonable host→device prefetcher for the train loop.

    One instance persists across epochs (the staging pool keeps its
    buffers, so epoch 2 allocates nothing); each ``iterate()`` call runs
    one epoch through a fresh producer thread and bounded queue of
    *device* batches.  ``depth`` bounds batches resident on device ahead
    of the consumer — depth 1 is classic double-buffering (one in
    compute, one staged), deeper absorbs burstier augmentation.

    ``host_transform`` runs producer-side just before staging (the GAN
    trainer threads ``task.host_prepare`` through it for prefetch-safe
    tasks).
    """

    def __init__(self, mesh, depth: int = 2):
        self.mesh = mesh
        self.depth = max(1, int(depth))
        self.pool = HostStagingPool()
        self._epoch: _EpochStream | None = None

    def iterate(self, iterable: Iterable,
                host_transform: Callable[[Any], Any] | None = None
                ) -> _EpochStream:
        """Start (and return) one epoch's staged stream.  At most one epoch
        is live per prefetcher — starting a new one closes the previous."""
        self.close()
        self._epoch = _EpochStream(self.mesh, iterable, self.depth,
                                   self.pool, host_transform)
        return self._epoch

    def close(self):
        """Tear down the live epoch (if any): unblock + join its producer,
        drop queued device batches.  Called from ``Trainer.fit``'s finally
        path so preemption/divergence aborts leak nothing."""
        if self._epoch is not None:
            self._epoch.close()
            self._epoch = None

    def stats(self) -> dict:
        return self._epoch.stats() if self._epoch is not None else {}

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
