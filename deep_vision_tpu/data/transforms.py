"""Image transforms — numpy ports of the reference's cv2/torch pipeline
(ResNet/pytorch/data_load.py:72-296: Rescale :72-101, RandomHorizontalFlip
:104-113, RandomCrop :116-143, CenterCrop :146-173, ToTensor :176-194,
Normalize :197-210, ColorJitter :213-296) — the pipeline that produced the
published accuracy numbers (SURVEY §7 hard-part 4 picks this over the TF one).

All functions take/return HWC uint8 or float32 numpy arrays on the HOST —
augmentation is host-side work feeding ``device_put``, never traced by XLA.
Randomness comes from an explicit ``np.random.Generator`` (seedable per
epoch/worker, unlike the reference's global ``random``).
"""

from __future__ import annotations

import numpy as np

try:  # PIL ships with the baked-in torch/torchvision stack
    from PIL import Image
except ImportError:  # pragma: no cover
    Image = None

try:  # cv2 resize is ~2× PIL's — and cv2 is the reference's own backend
    # (ResNet/pytorch/data_load.py uses cv2 throughout); gated: PIL fallback
    import cv2 as _cv2

    _cv2.setNumThreads(0)  # workers are already process-parallel
except ImportError:  # pragma: no cover
    import warnings

    _cv2 = None
    warnings.warn(
        "cv2 unavailable — PIL resize fallback (slower, and NOT "
        "bit-identical: PIL antialiases on downscale, cv2 does not)",
        stacklevel=1)


def resize_bilinear(img: np.ndarray, w: int, h: int) -> np.ndarray:
    """Bilinear resize to (w, h): cv2 when present, else PIL.

    The two backends are NOT numerically identical (PIL antialiases on
    downscale); the active backend is announced once at import so accuracy
    comparisons across machines are attributable.  Accepts uint8 or float
    HWC arrays; dtype is preserved on both paths."""
    if img.shape[0] == h and img.shape[1] == w:
        # already at target (e.g. raw-store reads): zero-copy, and the
        # result may ALIAS the input — possibly a read-only frombuffer
        # view of the record cache (records._LazySample).  Contract:
        # callers must not write the result in place (audited round 5:
        # every consumer flows into astype/np.stack copies; a violation
        # raises ValueError loudly on the read-only view, it cannot
        # corrupt silently)
        return img
    if _cv2 is not None:
        return _cv2.resize(img, (w, h), interpolation=_cv2.INTER_LINEAR)
    if img.dtype == np.uint8:
        return np.asarray(Image.fromarray(img).resize((w, h),
                                                      Image.BILINEAR))
    # float inputs: PIL mode-F per channel keeps full precision
    chans = [np.asarray(Image.fromarray(img[..., c], mode="F")
                        .resize((w, h), Image.BILINEAR))
             for c in range(img.shape[-1])]
    return np.stack(chans, axis=-1).astype(img.dtype)


_resize = resize_bilinear  # module-internal alias

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)


def imagenet_resize_for(image_size: int) -> int:
    """Shorter-side resize target paired with a crop size (the 256-for-224
    ratio, clamped above the crop) — single source for train/eval/infer."""
    return max(image_size * 256 // 224, image_size + 8)


def rescale(img: np.ndarray, size: int) -> np.ndarray:
    """Resize so the SHORTER side == size, preserving aspect ratio
    (reference Rescale :72-101)."""
    h, w = img.shape[:2]
    if h < w:
        nh, nw = size, max(1, int(round(w * size / h)))
    else:
        nh, nw = max(1, int(round(h * size / w))), size
    if (nh, nw) == (h, w):
        return img
    return _resize(img, nw, nh)


def random_horizontal_flip(img: np.ndarray, rng: np.random.Generator,
                           p: float = 0.5) -> np.ndarray:
    if rng.random() < p:
        return img[:, ::-1]
    return img


def random_crop(img: np.ndarray, size: int, rng: np.random.Generator) -> np.ndarray:
    h, w = img.shape[:2]
    top = int(rng.integers(0, h - size + 1))
    left = int(rng.integers(0, w - size + 1))
    return img[top:top + size, left:left + size]


def center_crop(img: np.ndarray, size: int) -> np.ndarray:
    h, w = img.shape[:2]
    top, left = (h - size) // 2, (w - size) // 2
    return img[top:top + size, left:left + size]


def color_jitter(img: np.ndarray, rng: np.random.Generator,
                 brightness: float = 0.2, contrast: float = 0.2,
                 saturation: float = 0.2) -> np.ndarray:
    """Brightness/contrast/saturation jitter in random order, factors
    uniform in [1-x, 1+x] (reference ColorJitter :213-296; hue=0 there,
    so hue is omitted).  Operates on float32 [0,1]."""
    x = img.astype(np.float32) / 255.0 if img.dtype == np.uint8 else img
    ops = []
    if brightness > 0:
        f = rng.uniform(max(0, 1 - brightness), 1 + brightness)
        ops.append(lambda a, f=f: a * f)
    if contrast > 0:
        f = rng.uniform(max(0, 1 - contrast), 1 + contrast)
        ops.append(lambda a, f=f: (a - a.mean()) * f + a.mean())
    if saturation > 0:
        f = rng.uniform(max(0, 1 - saturation), 1 + saturation)

        def sat(a, f=f):
            gray = a @ np.array([0.299, 0.587, 0.114], np.float32)
            return gray[..., None] + (a - gray[..., None]) * f

        ops.append(sat)
    rng.shuffle(ops)
    for op in ops:
        x = op(x)
    return np.clip(x, 0.0, 1.0)


def normalize(img: np.ndarray, mean=IMAGENET_MEAN, std=IMAGENET_STD) -> np.ndarray:
    """[0,1] float32 HWC → standardized (reference Normalize :197-210)."""
    x = img.astype(np.float32)
    if x.max() > 1.5:  # still uint8-range
        x = x / 255.0
    return (x - mean) / std


def train_transform(img: np.ndarray, rng: np.random.Generator,
                    size: int = 224, resize: int = 256,
                    jitter: bool = True) -> np.ndarray:
    """The reference's imagenet_train_transform (ResNet/pytorch/train.py:315-324):
    Rescale(256) → flip → RandomCrop(224) → ColorJitter(.2,.2,.2) → Normalize."""
    img = rescale(img, resize)
    img = random_horizontal_flip(img, rng)
    img = random_crop(img, size, rng)
    if jitter:
        img = color_jitter(img, rng)
    return normalize(img)


def eval_transform(img: np.ndarray, size: int = 224, resize: int = 256
                   ) -> np.ndarray:
    """imagenet_val_transform (train.py:326-331): Rescale → CenterCrop → Normalize."""
    img = rescale(img, resize)
    img = center_crop(img, size)
    return normalize(img)


# -- the TF "ResNet preprocessing" variant (ResNet/tensorflow/data_load.py) --
# channel means in RAW 0-255 space (:35-38); this pipeline subtracts means
# but does NOT divide by 255 or std — models trained with it expect
# mean-centered 0-255-range inputs

TF_CHANNEL_MEANS = np.array([123.68, 116.78, 103.94], np.float32)


def tf_train_transform(img: np.ndarray, rng: np.random.Generator,
                       size: int = 224, resize: int = 256) -> np.ndarray:
    """TF train path (:158-193): aspect-preserving resize(256) → random
    crop(224) → random flip → mean subtraction.  (Crop comes BEFORE flip
    here, unlike the cv2/torch pipeline; no color jitter.)"""
    img = rescale(img, resize)
    img = random_crop(img, size, rng)
    img = random_horizontal_flip(img, rng)
    return img.astype(np.float32) - TF_CHANNEL_MEANS


def tf_eval_transform(img: np.ndarray, size: int = 224, resize: int = 256
                      ) -> np.ndarray:
    """TF eval path: aspect-preserving resize → central crop (:46-63) →
    mean subtraction (:66-92)."""
    img = rescale(img, resize)
    img = center_crop(img, size)
    return img.astype(np.float32) - TF_CHANNEL_MEANS


def train_transform_u8(img: np.ndarray, rng: np.random.Generator,
                       size: int = 224, resize: int = 256) -> np.ndarray:
    """Host half of the device-preprocess split: Rescale → flip → RandomCrop,
    all uint8 (jitter+normalize run on device — ops/preprocess.py).

    Returns a VIEW when no resize was needed (raw records): the one copy
    happens at batch assembly (np.stack) or pickling — materializing here
    too would double the pipeline's memory traffic (the 1-core host budget,
    SURVEY §7 hard-part 1)."""
    img = rescale(img, resize)
    img = random_horizontal_flip(img, rng)
    return random_crop(img, size, rng)


def eval_transform_u8(img: np.ndarray, size: int = 224, resize: int = 256
                      ) -> np.ndarray:
    """Host half for eval: Rescale → CenterCrop, uint8 (view — see
    train_transform_u8)."""
    return center_crop(rescale(img, resize), size)
