"""Dataset preparation: raw downloads → dvrec shards.

Ports of the reference's prep layer (SURVEY §2.4), TF/ray-free:

- VOC:  XML annotation parse (Datasets/VOC2007/tfrecords.py:124-155),
  normalized corner boxes with the same bounds asserts (:61-64); the 2012
  builder differs only in paths (SURVEY #33).
- COCO: JSON → per-image grouped annotations (Datasets/MSCOCO/tfrecords.py:
  115-133), category re-index from 1-based (:135-158), xywh→corners.
- MPII: pose JSON → normalized keypoints + visibility remap 0/2
  (Datasets/MPII/tfrecords_mpii.py:54-84).
- ImageNet: flat synset-prefixed dir → classification shards (the
  build_imagenet_tfrecord.py role; PNG/CMYK handling is PIL ``convert("RGB")``
  at read time instead of a TF session, :236-270).
- CycleGAN: pair-less two-dir builder (CycleGAN/tensorflow/tfrecords.py:9-73)
  and CelebA attribute split (celeba.py:1-24).

Shard fan-out uses ``records.write_sharded`` (process pool — the reference's
ray.remote / threading.Coordinator role).
"""

from __future__ import annotations

import json
import os
import xml.etree.ElementTree as ET

import numpy as np

from deep_vision_tpu.data import records as R

# encoders must be MODULE-LEVEL: they are pickled into the shard-writer
# process pool (local closures are not picklable)
def _encode_labeled_file(item):
    path, label = item
    with open(path, "rb") as f:
        return {"label": int(label), "filename": os.path.basename(path)}, \
            f.read()


def sanitize_image(payload: bytes) -> tuple[bytes, str]:
    """Build-time image hardening → (clean JPEG bytes, status).

    The reference handled ImageNet's dirty files with hard-coded filename
    blacklists (PNG-as-.JPEG ``_is_png`` build_imagenet_tfrecord.py:272-283,
    CMYK JPEGs ``_is_cmyk`` :286-309) re-encoded through a TF session
    (``ImageCoder`` :236-270).  We detect by CONTENT instead of filename, so
    any dirty file is caught, not just the 23 known ones:

    - clean RGB JPEG → bytes pass through untouched (status ``ok``);
    - PNG/CMYK/grayscale/palette/alpha → decoded + re-encoded as RGB JPEG
      quality 100, matching the ImageCoder settings (status ``reencoded``);
    - truncated-but-salvageable → partial decode re-encoded (``reencoded``);
    - undecodable → status ``bad`` (caller drops the file so shards are
      100% readable instead of throwing mid-epoch).
    """
    import io

    from PIL import Image, ImageFile

    try:
        with Image.open(io.BytesIO(payload)) as im:
            if im.format == "JPEG" and im.mode == "RGB":
                im.load()  # full decode — catches truncation up front
                return payload, "ok"
    except Exception:  # noqa: BLE001 — any decode error falls through to the salvage path
        pass
    old = ImageFile.LOAD_TRUNCATED_IMAGES
    ImageFile.LOAD_TRUNCATED_IMAGES = True
    try:
        with Image.open(io.BytesIO(payload)) as im:
            rgb = im.convert("RGB")
        buf = io.BytesIO()
        rgb.save(buf, format="JPEG", quality=100)
        return buf.getvalue(), "reencoded"
    except Exception:  # noqa: BLE001 — undecodable even with truncation allowed: drop the sample
        return b"", "bad"
    finally:
        ImageFile.LOAD_TRUNCATED_IMAGES = old


def decode_image_robust(payload: bytes) -> np.ndarray | None:
    """One decode with sanitize_image's salvage semantics: any format →
    RGB uint8 HWC; truncated files partially decode; undecodable → None."""
    import io

    from PIL import Image, ImageFile

    old = ImageFile.LOAD_TRUNCATED_IMAGES
    ImageFile.LOAD_TRUNCATED_IMAGES = True
    try:
        with Image.open(io.BytesIO(payload)) as im:
            return np.asarray(im.convert("RGB"))
    except Exception:  # noqa: BLE001 — undecodable payload maps to None by contract
        return None
    finally:
        ImageFile.LOAD_TRUNCATED_IMAGES = old


def _encode_imagenet_item(item, store: str = "jpeg", resize: int = 256):
    """(path, label, synset, human, bboxes) → (header, payload) or None
    to drop an undecodable file (records._write_shard skips None).

    ``store`` picks the payload encoding:

    - ``jpeg``: the sanitized original JPEG — archival fidelity, decode at
      read time (the reference TFRecord semantics,
      build_imagenet_tfrecord.py:472-689);
    - ``raw``: decode ONCE at build time, aspect-preserving rescale of the
      shorter side to ``resize``, store raw uint8 HWC — the read path is
      then decode-free (frombuffer + crop), which is what lets a 1-core
      TPU-VM host feed the chip (SURVEY §7 hard-part 1).  Train-time
      augmentation (random crop + flip) is unchanged: it operates on the
      rescaled image in both paths.
    """
    path, label, synset, human, bboxes = item
    with open(path, "rb") as f:
        payload = f.read()
    header = {"label": int(label), "filename": os.path.basename(path),
              "synset": synset, "human": human}
    if bboxes:
        header["bboxes"] = bboxes
    if store == "raw":
        # raw stores decoded pixels, so sanitize's JPEG re-encode step is
        # moot — decode ONCE (salvaging truncated files like
        # sanitize_image does), rescale, store
        img = decode_image_robust(payload)
        if img is None:
            print(f"[prep] dropping undecodable image {path}", flush=True)
            return None
        from deep_vision_tpu.data.transforms import rescale

        img = np.ascontiguousarray(rescale(img, resize))
        header["shape"] = list(img.shape)
        header["enc"] = "raw"
        return header, img.tobytes()
    clean, status = sanitize_image(payload)
    if status == "bad":
        print(f"[prep] dropping undecodable image {path}", flush=True)
        return None
    if status == "reencoded":
        header["reencoded"] = True
    return header, clean


def _encode_file(path):
    with open(path, "rb") as f:
        return {"filename": os.path.basename(path)}, f.read()


VOC_CLASSES = (
    "aeroplane", "bicycle", "bird", "boat", "bottle", "bus", "car", "cat",
    "chair", "cow", "diningtable", "dog", "horse", "motorbike", "person",
    "pottedplant", "sheep", "sofa", "train", "tvmonitor")


def load_class_names(path: str | None, default=VOC_CLASSES) -> dict[str, int]:
    """names file (one class per line — voc_2007_names.txt style) → map."""
    if path is None:
        return {n: i for i, n in enumerate(default)}
    with open(path) as f:
        return {line.strip(): i for i, line in enumerate(f) if line.strip()}


def parse_voc_xml(xml_path: str, names_map: dict[str, int]) -> dict:
    """One VOC annotation → sample dict with NORMALIZED corner boxes
    (reference parse_one_xml + normalization asserts tfrecords.py:61-64)."""
    root = ET.parse(xml_path).getroot()
    filename = root.find(".//filename").text
    size = root.find("size")
    w = float(size.find("width").text)
    h = float(size.find("height").text)
    boxes, classes = [], []
    for obj in root.findall(".//object"):
        name = obj.find("name").text
        bb = obj.find("bndbox")
        x1 = float(bb.find("xmin").text) / w
        y1 = float(bb.find("ymin").text) / h
        x2 = float(bb.find("xmax").text) / w
        y2 = float(bb.find("ymax").text) / h
        assert 0 <= x1 <= 1 and 0 <= y1 <= 1, f"bad bbox in {xml_path}"
        assert x1 <= x2 <= 1.001 and y1 <= y2 <= 1.001
        boxes.append([x1, y1, min(x2, 1.0), min(y2, 1.0)])
        classes.append(names_map[name])
    return {"filename": filename,
            "boxes": np.asarray(boxes, np.float32).reshape(-1, 4),
            "classes": np.asarray(classes, np.int64)}


def prepare_voc(voc_root: str, out_dir: str, split: str = "train",
                names_file: str | None = None, num_shards: int = 8,
                num_workers: int = 8, year: str = "2007",
                store: str = "jpeg", resize: int = 416) -> int:
    """VOCdevkit/VOC{year}/{Annotations,JPEGImages} → dvrec shards."""
    base = os.path.join(voc_root, f"VOC{year}")
    anno_dir = os.path.join(base, "Annotations")
    img_dir = os.path.join(base, "JPEGImages")
    names_map = load_class_names(names_file)
    # honor VOC's split lists (ImageSets/Main/<split>.txt) so train and val
    # shards hold disjoint images; fall back to everything if absent
    split_file = os.path.join(base, "ImageSets", "Main", f"{split}.txt")
    wanted = None
    if os.path.exists(split_file):
        with open(split_file) as f:
            wanted = {line.split()[0] for line in f if line.strip()}
    samples = []
    for xml_file in sorted(os.listdir(anno_dir)):
        if not xml_file.endswith(".xml"):
            continue
        if wanted is not None and xml_file[:-4] not in wanted:
            continue
        s = parse_voc_xml(os.path.join(anno_dir, xml_file), names_map)
        img_path = os.path.join(img_dir, s["filename"])
        with open(img_path, "rb") as f:
            s["image_bytes"] = f.read()
        samples.append(s)
    _, n = R.write_detection_records(samples, out_dir, split, num_shards,
                                     num_workers, store=store, resize=resize)
    return n


def prepare_coco(annotation_json: str, image_dir: str, out_dir: str,
                 split: str = "train", num_shards: int = 16,
                 num_workers: int = 8, store: str = "jpeg",
                 resize: int = 416) -> int:
    """COCO instances JSON → dvrec (per-image grouping + 0-based classes)."""
    with open(annotation_json) as f:
        coco = json.load(f)
    # re-index 1-based, possibly sparse, category ids → dense 0-based
    cat_ids = sorted(c["id"] for c in coco["categories"])
    cat_map = {cid: i for i, cid in enumerate(cat_ids)}
    images = {im["id"]: im for im in coco["images"]}
    by_image: dict[int, list] = {}
    for anno in coco.get("annotations", []):
        by_image.setdefault(anno["image_id"], []).append(anno)
    samples = []
    for image_id, annos in sorted(by_image.items()):
        im = images[image_id]
        w, h = float(im["width"]), float(im["height"])
        boxes, classes = [], []
        for a in annos:
            x, y, bw, bh = a["bbox"]  # xywh corner-origin (COCO format)
            boxes.append([x / w, y / h, (x + bw) / w, (y + bh) / h])
            classes.append(cat_map[int(a["category_id"])])
        path = os.path.join(image_dir, im["file_name"])
        with open(path, "rb") as f:
            payload = f.read()
        samples.append({"image_bytes": payload,
                        "boxes": np.clip(np.asarray(boxes, np.float32)
                                         .reshape(-1, 4), 0, 1),
                        "classes": np.asarray(classes, np.int64)})
    _, n = R.write_detection_records(samples, out_dir, split, num_shards,
                                     num_workers, store=store, resize=resize)
    return n


def prepare_mpii(annotation_json: str, image_dir: str, out_dir: str,
                 split: str = "train", num_shards: int = 8,
                 num_workers: int = 8, store: str = "jpeg",
                 resize: int = 384) -> int:
    """MPII pose JSON (list of {image, joints, joints_visibility, center,
    scale}) → pose dvrec.  Visibility remap 0→0, else→2 (reference :63)."""
    with open(annotation_json) as f:
        annos = json.load(f)
    samples = []
    for a in annos:
        path = os.path.join(image_dir, a["image"])
        if not os.path.exists(path):
            continue
        with open(path, "rb") as f:
            payload = f.read()
        joints = np.asarray(a["joints"], np.float32)
        vis = np.asarray([0 if v == 0 else 2
                          for v in a["joints_visibility"]], np.float32)
        kp = np.concatenate([joints, vis[:, None]], axis=1)
        samples.append({"image_bytes": payload, "keypoints": kp,
                        "center": np.asarray(a.get("center", (0, 0)),
                                             np.float32),
                        "scale": float(a.get("scale", 1.0))})
    _, n = R.write_pose_records(samples, out_dir, split, num_shards,
                                num_workers, store=store, resize=resize)
    return n


def load_synset_humans(metadata_file: str) -> dict[str, str]:
    """synset → human-readable label ("n01440764 → tench, Tinca tinca") —
    the ``synset_to_human`` lookup of build_imagenet_tfrecord.py:472-689.
    Accepts both tab- and space-separated metadata lines."""
    out: dict[str, str] = {}
    with open(metadata_file) as f:
        for line in f:
            parts = line.strip().split(None, 1)
            if parts:
                out[parts[0]] = parts[1] if len(parts) > 1 else ""
    return out


def load_bbox_csv(csv_path: str) -> dict[str, list[list[float]]]:
    """bbox CSV (``process_imagenet_bboxes`` output / the reference's
    process_bounding_boxes.py format) → filename → [[x1,y1,x2,y2], ...]."""
    out: dict[str, list[list[float]]] = {}
    with open(csv_path) as f:
        for line in f:
            parts = line.strip().split(",")
            if len(parts) != 5:
                continue
            out.setdefault(parts[0], []).append(
                [float(v) for v in parts[1:]])
    return out


def process_imagenet_bboxes(xml_dir: str, out_csv: str,
                            synsets_file: str | None = None) -> dict:
    """ImageNet bbox XML tree (``<xml_dir>/nXXXX/nXXXX_YYYY.xml``) → CSV of
    ``<file>.JPEG,xmin,ymin,xmax,ymax`` relative coords — the
    process_bounding_boxes.py:16-264 role.

    Same data-noise rules as the reference: coords are normalized by the
    annotator-displayed width/height stored in the XML, min/max swapped if
    inverted, clamped to [0,1]; degenerate boxes (zero extent after
    clamping) are skipped; with a synsets file, off-challenge XML dirs are
    skipped, and a box label that differs from the directory synset is only
    rejected when it IS a challenge synset (many dog boxes carry human
    labels like 'Scottish_deerhound' instead of a synset id).
    Returns counters {files, boxes, skipped_files, skipped_boxes}.
    """
    import glob as _glob

    wanted = None
    if synsets_file is not None:
        with open(synsets_file) as f:
            wanted = {line.strip() for line in f if line.strip()}
    stats = {"files": 0, "boxes": 0, "skipped_files": 0, "skipped_boxes": 0}
    with open(out_csv, "w") as out:
        for xml_path in sorted(
                _glob.glob(os.path.join(xml_dir, "*", "*.xml"))):
            synset = os.path.basename(os.path.dirname(xml_path))
            if wanted is not None and synset not in wanted:
                stats["skipped_files"] += 1
                continue
            try:
                root = ET.parse(xml_path).getroot()
            except ET.ParseError:
                stats["skipped_files"] += 1
                continue
            # the XML's <filename> is noisy (sometimes '%s'); the XML
            # basename is authoritative, as in the reference
            image_name = os.path.splitext(os.path.basename(xml_path))[0]
            wrote = 0
            for obj in root.iter("object"):
                name = obj.findtext("name", "")
                if (wanted is not None and name != synset
                        and name in wanted):
                    stats["skipped_boxes"] += 1
                    continue
                try:
                    w = float(root.findtext(".//width"))
                    h = float(root.findtext(".//height"))
                    bb = obj.find("bndbox")
                    xs = sorted((float(bb.findtext("xmin")) / w,
                                 float(bb.findtext("xmax")) / w))
                    ys = sorted((float(bb.findtext("ymin")) / h,
                                 float(bb.findtext("ymax")) / h))
                except (TypeError, ValueError, ZeroDivisionError):
                    stats["skipped_boxes"] += 1
                    continue
                x1, x2 = (min(max(v, 0.0), 1.0) for v in xs)
                y1, y2 = (min(max(v, 0.0), 1.0) for v in ys)
                if x1 >= x2 or y1 >= y2:
                    stats["skipped_boxes"] += 1
                    continue
                out.write(f"{image_name}.JPEG,{x1:.4f},{y1:.4f},"
                          f"{x2:.4f},{y2:.4f}\n")
                wrote += 1
            if wrote:
                stats["files"] += 1
                stats["boxes"] += wrote
            else:
                stats["skipped_files"] += 1
    return stats


def prepare_imagenet(src_dir: str, labels_file: str, out_dir: str,
                     split: str = "train", num_shards: int = 64,
                     num_workers: int = 8, bbox_csv: str | None = None,
                     store: str = "jpeg", resize: int = 256) -> int:
    """Flattened synset-prefixed JPEG dir → classification dvrec shards
    (the 1024/128-shard layout of build_imagenet_tfrecord.py, scaled by
    ``num_shards``).

    Every image is content-sanitized at build time (``sanitize_image`` —
    the blacklist+ImageCoder role, :236-309): PNG-as-JPEG / CMYK /
    truncated files are re-encoded, undecodable ones dropped, so shards
    are 100% readable.  Headers carry synset + human label (:472-689) and,
    with ``bbox_csv``, the image's bounding boxes."""
    # one pass over the metadata file yields both lookups (synset→index by
    # line order, synset→human by the rest of the line)
    label_map: dict[str, int] = {}
    humans = load_synset_humans(labels_file)
    for idx, synset in enumerate(humans):
        label_map[synset] = idx
    boxes = load_bbox_csv(bbox_csv) if bbox_csv else {}
    files = sorted(f for f in os.listdir(src_dir)
                   if os.path.isfile(os.path.join(src_dir, f)))
    items = []
    for f in files:
        synset = f.split("_")[0]
        items.append((os.path.join(src_dir, f), label_map[synset], synset,
                      humans.get(synset, ""), boxes.get(f, None)))
    import functools

    encode = _encode_imagenet_item if store == "jpeg" else functools.partial(
        _encode_imagenet_item, store=store, resize=resize)
    _, written = R.write_sharded(items, out_dir, split, num_shards,
                                 encode, num_workers)
    if written < len(items):
        print(f"[prep] dropped {len(items) - written} undecodable file(s) "
              f"of {len(items)}", flush=True)
    return written


def flatten_imagenet_train(train_dir: str, out_dir: str,
                           link: bool = True) -> int:
    """Raw ILSVRC2012 train layout → the flat ``synset_imagename.JPEG``
    dir the loaders expect — the untar-script.sh + flatten-script.sh role.

    Handles both raw layouts: per-synset tars (``nXXXX.tar`` as extracted
    from ILSVRC2012_img_train.tar) and per-synset subdirectories.  Files
    inside train tars are already named ``nXXXX_YYYY.JPEG`` so flattening
    is a move/link, not a rename.  ``link=True`` hardlinks (falls back to
    copy across filesystems) instead of the reference's 150 GB ``cp``."""
    import shutil
    import tarfile

    os.makedirs(out_dir, exist_ok=True)
    n = 0
    for entry in sorted(os.listdir(train_dir)):
        full = os.path.join(train_dir, entry)
        if entry.endswith(".tar"):
            with tarfile.open(full) as tf:
                for member in tf:
                    if not member.isfile():
                        continue
                    if os.path.exists(os.path.join(
                            out_dir, os.path.basename(member.name))):
                        continue  # idempotent re-runs, like the dir branch
                    tf.extract(member, out_dir, filter="data")
                    n += 1
        elif os.path.isdir(full):
            for f in sorted(os.listdir(full)):
                dst = os.path.join(out_dir, f)
                if os.path.exists(dst):
                    continue
                if link:
                    try:
                        os.link(os.path.join(full, f), dst)
                    except OSError:
                        shutil.copy2(os.path.join(full, f), dst)
                else:
                    shutil.copy2(os.path.join(full, f), dst)
                n += 1
    return n


def flatten_imagenet_val(val_dir: str, out_dir: str,
                         ground_truth: str | None = None,
                         synsets_file: str | None = None,
                         link: bool = True) -> int:
    """Raw val layout → flat ``synset_ILSVRC2012_val_XXXX.JPEG`` dir —
    the flatten-val-script.sh role.

    Two raw layouts:
    - per-synset subdirectories (the reference script's input): flatten
      with ``<dirname>_<filename>`` naming;
    - the flat official tar output (``ILSVRC2012_val_00000001.JPEG`` ...)
      plus the 50k-line ground-truth file (1-based ILSVRC2012 label ids)
      and the synsets list mapping id→synset: prefix each file with its
      synset."""
    import shutil

    os.makedirs(out_dir, exist_ok=True)

    def place(src, name):
        dst = os.path.join(out_dir, name)
        if os.path.exists(dst):
            return
        if link:
            try:
                os.link(src, dst)
                return
            except OSError:
                pass
        shutil.copy2(src, dst)

    entries = sorted(os.listdir(val_dir))
    subdirs = [e for e in entries
               if os.path.isdir(os.path.join(val_dir, e))]
    n = 0
    if subdirs:
        for d in subdirs:
            for f in sorted(os.listdir(os.path.join(val_dir, d))):
                place(os.path.join(val_dir, d, f), f"{d}_{f}")
                n += 1
        return n
    if not (ground_truth and synsets_file):
        raise ValueError(
            "flat val dir needs --ground-truth (ILSVRC2012 validation "
            "ground truth) and --synsets (id→synset order) to label files")
    with open(synsets_file) as f:
        synsets = [line.strip() for line in f if line.strip()]
    with open(ground_truth) as f:
        labels = [int(line) for line in f if line.strip()]
    files = [e for e in entries if e.upper().endswith((".JPEG", ".JPG"))]
    if len(files) != len(labels):
        raise ValueError(f"{len(files)} val images vs {len(labels)} "
                         f"ground-truth lines")
    bad = [l for l in labels if not 1 <= l <= len(synsets)]
    if bad:
        raise ValueError(
            f"ground-truth labels must be 1..{len(synsets)} (ILSVRC ids "
            f"are 1-based); got e.g. {bad[0]} — is the file 0-based?")
    for f, lab in zip(files, labels):
        place(os.path.join(val_dir, f), f"{synsets[lab - 1]}_{f}")
        n += 1
    return n


def prepare_unpaired(dir_a: str, dir_b: str, out_dir: str,
                     split: str = "train", num_shards: int = 4,
                     num_workers: int = 4) -> tuple[int, int]:
    """CycleGAN pair-less builder: domain dirs → '<split>_a' / '<split>_b'
    shards (CycleGAN/tensorflow/tfrecords.py:9-73)."""
    counts = []
    for tag, d in (("a", dir_a), ("b", dir_b)):
        files = sorted(f for f in os.listdir(d)
                       if f.lower().endswith((".jpg", ".jpeg", ".png")))
        items = [os.path.join(d, f) for f in files]
        R.write_sharded(items, out_dir, f"{split}_{tag}", num_shards,
                        _encode_file, num_workers)
        counts.append(len(items))
    return tuple(counts)


def split_celeba_by_attribute(attr_file: str, image_dir: str, out_a: str,
                              out_b: str, attribute: str = "Male") -> tuple[int, int]:
    """CelebA list_attr_celeba.txt split (celeba.py:1-24): symlink images
    into two domain dirs by one binary attribute."""
    os.makedirs(out_a, exist_ok=True)
    os.makedirs(out_b, exist_ok=True)
    with open(attr_file) as f:
        lines = f.read().splitlines()
    header = lines[1].split()
    col = header.index(attribute)
    na = nb = 0
    for line in lines[2:]:
        parts = line.split()
        fname, val = parts[0], int(parts[1 + col])
        src = os.path.join(image_dir, fname)
        if not os.path.exists(src):
            continue
        dst = os.path.join(out_a if val > 0 else out_b, fname)
        if not os.path.exists(dst):
            os.symlink(os.path.abspath(src), dst)
        if val > 0:
            na += 1
        else:
            nb += 1
    return na, nb
