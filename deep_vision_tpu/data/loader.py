"""Host-side batching + device prefetch.

Replaces torch ``DataLoader(num_workers=16)`` (ResNet/pytorch/train.py:229-234)
and ``tf.data`` prefetch/AUTOTUNE (YOLO/tensorflow/train.py:265-272) with
numpy batching plus a background thread that ``device_put``s ahead of the
compute stream (double buffering): while step N runs on the TPU, batch N+1 is
already being transferred H2D, so HBM never waits on the host.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator

import numpy as np


def pad_eval_indices(idx: np.ndarray, start: int, batch_size: int
                     ) -> tuple[np.ndarray, np.ndarray, int]:
    """Static-shape eval padding, shared by every loader: slice
    ``idx[start:start+batch_size]``, pad a short tail by repeating the
    first index, and return ``(sel, weight, n_real)`` where ``weight`` is
    the 0/1 mask tasks use to ignore the filler rows."""
    sel = idx[start:start + batch_size]
    n_real = len(sel)
    if 0 < n_real < batch_size:
        sel = np.concatenate([sel, np.repeat(idx[:1], batch_size - n_real)])
    weight = np.zeros(batch_size, np.float32)
    weight[:n_real] = 1.0
    return sel, weight, n_real


# -- worker-side state for PreppedSampleLoader pools (one dict per worker
# process; the 0-worker path calls PREPARE inline with the same per-item
# rng, so pooled and sequential iteration yield IDENTICAL batches) -------
_PREP_WORKER: dict = {}


def _prep_worker_init(cfg: dict):
    _PREP_WORKER.update(cfg)


def _prep_one(args: tuple) -> dict:
    i, epoch = args
    w = _PREP_WORKER
    rng = np.random.default_rng((w["seed"], epoch, int(i)))
    return w["prepare"](w["samples"][i], rng, **w["kwargs"])


class PreppedSampleLoader:
    """Shared machinery for per-sample-prep loaders (detection, pose):
    epoch shuffling, static eval padding, per-item augmentation rng
    derived from ``(seed, epoch, sample_index)`` — deterministic and
    independent of iteration order or worker count — and an optional
    forkserver worker pool with ``prefetch_batches`` async batches in
    flight so worker decode overlaps the consumer's device step.

    Subclasses set ``PREPARE`` to a module-level (picklable) function
    ``prepare(sample, rng, **kwargs)`` and implement ``_prep_kwargs``;
    their own fields must be assigned BEFORE calling ``super().__init__``
    (pool creation snapshots ``_prep_kwargs()``).
    """

    PREPARE: Callable

    def __init__(self, samples, batch_size: int, train: bool, seed: int,
                 num_workers: int = 0, prefetch_batches: int = 2):
        self.samples = samples
        self.batch_size = batch_size
        self.train = train
        self.seed = seed
        self.num_workers = num_workers
        self.prefetch_batches = max(1, prefetch_batches)
        self.epoch = 0
        self._pool = None
        if num_workers > 0:
            import multiprocessing as mp

            # forkserver, NOT fork: the JAX runtime has live threads by
            # loader-construction time (same rationale as ImageNetLoader)
            try:
                ctx = mp.get_context("forkserver")
            except ValueError:
                ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(
                num_workers, initializer=_prep_worker_init,
                initargs=(dict(samples=samples, seed=seed,
                               prepare=type(self).PREPARE,
                               kwargs=self._prep_kwargs()),))

    def _prep_kwargs(self) -> dict:
        raise NotImplementedError

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self) -> int:
        full = len(self.samples) // self.batch_size
        if not self.train and len(self.samples) % self.batch_size:
            return full + 1  # eval covers the FULL set (padded last batch)
        return full

    def _prepare_indexed(self, i: int, epoch: int) -> dict:
        rng = np.random.default_rng((self.seed, epoch, int(i)))
        return type(self).PREPARE(self.samples[i], rng,
                                  **self._prep_kwargs())

    def close(self):
        if self._pool is not None:
            self._pool.terminate()
            # Pool.join has no timeout parameter; terminate() already
            # killed the workers so this only reaps them
            self._pool.join()  # dvtlint: disable=DVT007
            self._pool = None

    def _assemble(self, items: list, weight) -> dict:
        batch = {k: np.stack([it[k] for it in items]) for k in items[0]}
        if not self.train:
            # weight-0 fillers keep the batch shape static; loss metrics
            # and host evaluators honor the mask (shared loader contract)
            batch["weight"] = weight
        return batch

    def __iter__(self) -> Iterator[dict]:
        from collections import deque

        order = np.random.default_rng((self.seed, self.epoch))
        idx = np.arange(len(self.samples))
        if self.train:
            order.shuffle(idx)
        plan = [pad_eval_indices(idx, b * self.batch_size, self.batch_size)
                for b in range(len(self))]
        if self._pool is not None:
            chunk = max(1, self.batch_size // (2 * self.num_workers))
            pending: deque = deque()
            submit = 0
            for b in range(len(plan)):
                while submit < len(plan) and len(pending) < \
                        self.prefetch_batches:
                    args = [(int(i), self.epoch) for i in plan[submit][0]]
                    pending.append(self._pool.map_async(
                        _prep_one, args, chunksize=chunk))
                    submit += 1
                # a hung worker should fail the epoch loudly, not pin
                # the training loop forever
                yield self._assemble(pending.popleft().get(timeout=600.0),
                                     plan[b][1])
        else:
            for sel, weight, _ in plan:
                items = [self._prepare_indexed(int(i), self.epoch)
                         for i in sel]
                yield self._assemble(items, weight)


class ArrayLoader:
    """In-memory dict-of-arrays dataset → shuffled fixed-size batches.

    The epoch-seeded reshuffle mirrors ``DataLoader(shuffle=True)``;
    ``drop_last=True`` keeps shapes static for XLA (no recompiles).
    """

    def __init__(self, data: dict[str, np.ndarray], batch_size: int,
                 shuffle: bool = True, drop_last: bool = True, seed: int = 0,
                 pad_last: bool = False,
                 transform: Callable[[dict, np.random.Generator], dict] | None = None):
        self.data = data
        n = len(next(iter(data.values())))
        for k, v in data.items():
            assert len(v) == n, f"length mismatch on '{k}'"
        self.n = n
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        self.pad_last = pad_last
        self.seed = seed
        self.epoch = 0
        self.transform = transform

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self) -> int:
        if self.drop_last:
            return self.n // self.batch_size
        return (self.n + self.batch_size - 1) // self.batch_size

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng(self.seed + self.epoch)
        idx = rng.permutation(self.n) if self.shuffle else np.arange(self.n)
        end = (self.n // self.batch_size) * self.batch_size if self.drop_last else self.n
        for start in range(0, end, self.batch_size):
            if self.pad_last:
                # static batch size (no XLA recompile, shard-safe) with
                # weight=0 fillers so metrics ignore them
                sel, weight, _ = pad_eval_indices(idx[:end], start,
                                                  self.batch_size)
            else:
                sel = idx[start:start + self.batch_size]
            batch = {k: v[sel] for k, v in self.data.items()}
            if self.pad_last:
                batch["weight"] = weight
            if self.transform is not None:
                batch = self.transform(batch, rng)
            yield batch


def prefetch_to_device(iterable: Iterable, mesh, depth: int = 2) -> Iterator:
    """Background device_put pipeline (the double-buffer) — legacy shim.

    Now a thin generator over :class:`deep_vision_tpu.data.pipeline.DevicePrefetcher`
    so the old call sites keep their contract (producer exceptions re-raise
    in the consumer — a dead producer must abort the epoch, not truncate it)
    while gaining the staged path's fix for the producer-thread leak: when
    the consumer abandons iteration early (preemption, divergence abort,
    mid-epoch exception) the generator's ``finally`` closes the epoch, which
    unblocks the producer's bounded put and joins the thread instead of
    leaving it parked on ``q.put`` forever with batches pinned in the queue.
    """
    from deep_vision_tpu.data.pipeline import DevicePrefetcher

    pf = DevicePrefetcher(mesh, depth=depth)
    try:
        yield from pf.iterate(iterable)
    finally:
        pf.close()
