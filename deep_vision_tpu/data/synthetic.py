"""Synthetic datasets for smoke runs and tests.

The reference's equivalent is CycleGAN's commented-out random-tensor dry-run
path (CycleGAN/tensorflow/train.py:338-342); here it is a first-class surface
(`--synthetic`) that works for every registered config: class-conditional
Gaussian blobs that a real network can overfit, so smoke runs exercise the
full train/eval/checkpoint path AND show a falling loss.
"""

from __future__ import annotations

import numpy as np


def synthetic_classification(n: int, image_size: int = 32, channels: int = 1,
                             num_classes: int = 10, seed: int = 0
                             ) -> dict[str, np.ndarray]:
    """Learnable synthetic images: one blob location per class + noise."""
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, num_classes, size=n).astype(np.int32)
    images = rng.normal(0, 0.3, size=(n, image_size, image_size, channels))
    images = images.astype(np.float32)
    ys, xs = np.mgrid[0:image_size, 0:image_size]
    grid = max(2, int(np.ceil(np.sqrt(num_classes))))
    step = image_size / (grid + 1)
    sigma = max(image_size / 10.0, 1.5)
    for c in range(np.minimum(num_classes, grid * grid)):
        cy = step * (1 + c // grid)
        cx = step * (1 + c % grid)
        blob = np.exp(-(((ys - cy) ** 2 + (xs - cx) ** 2) / (2 * sigma**2)))
        images[labels == c] += 2.0 * blob[..., None].astype(np.float32)
    return {"image": images, "label": labels}


def synthetic_images(n: int, image_size: int, channels: int = 3, seed: int = 0
                     ) -> np.ndarray:
    """Plain random images in [-1, 1] (GAN smoke data)."""
    rng = np.random.default_rng(seed)
    return rng.uniform(-1, 1, size=(n, image_size, image_size, channels)
                       ).astype(np.float32)
