"""dvrec: the framework's packed record format + sharded builders.

Replaces the reference's TFRecord layer (SURVEY §2.4: ImageNet builder
Datasets/ILSVRC2012/build_imagenet_tfrecord.py, VOC builders
Datasets/VOC2007/tfrecords.py, COCO Datasets/MSCOCO/tfrecords.py, MPII
Datasets/MPII/tfrecords_mpii.py) with a TF-free container:

    shard file = repeat[ u32 header_len | header JSON | u32 payload_len | payload ]

- header: arbitrary JSON metadata (labels, boxes, keypoints, shapes)
- payload: raw bytes (typically the encoded JPEG)
- shards are named ``{split}-{i:05d}-of-{n:05d}.dvrec``; writers fan out
  over a process pool (the reference used ``ray.remote``/thread pools —
  VOC2007/tfrecords.py:98-121, build_imagenet_tfrecord.py:420-469).
"""

from __future__ import annotations

import functools
import glob
import io
import json
import os
import struct
from typing import Callable, Iterable, Iterator, Sequence

import numpy as np

_U32 = struct.Struct("<I")


class RecordWriter:
    def __init__(self, path: str):
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._f = open(path, "wb")

    def write(self, header: dict, payload: bytes = b""):
        hb = json.dumps(header).encode()
        self._f.write(_U32.pack(len(hb)))
        self._f.write(hb)
        self._f.write(_U32.pack(len(payload)))
        self._f.write(payload)

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def read_records(path: str) -> Iterator[tuple[dict, bytes]]:
    with open(path, "rb") as f:
        for header, off, plen in scan_records(path):
            f.seek(off)
            yield header, f.read(plen)


def shard_name(out_dir: str, split: str, i: int, n: int) -> str:
    return os.path.join(out_dir, f"{split}-{i:05d}-of-{n:05d}.dvrec")


def list_shards(root: str, split: str) -> list[str]:
    return sorted(glob.glob(os.path.join(root, f"{split}-*.dvrec")))


def _write_shard(args):
    path, items, encode = args
    n = 0
    with RecordWriter(path) as w:
        for item in items:
            enc = encode(item)
            if enc is None:  # encoder dropped the item (e.g. corrupt image)
                continue
            header, payload = enc
            w.write(header, payload)
            n += 1
    return path, n


def write_sharded(items: Sequence, out_dir: str, split: str, num_shards: int,
                  encode: Callable, num_workers: int = 8) -> tuple[list[str], int]:
    """Fan items out to ``num_shards`` files, ``num_workers`` processes —
    the ray.remote/Coordinator role from the reference prep scripts.
    Returns (shard paths, records actually written) — the count can be
    below ``len(items)`` when the encoder drops items."""
    os.makedirs(out_dir, exist_ok=True)
    chunks = [list(items[i::num_shards]) for i in range(num_shards)]
    jobs = [(shard_name(out_dir, split, i, num_shards), chunk, encode)
            for i, chunk in enumerate(chunks)]
    if num_workers <= 1:
        results = [_write_shard(j) for j in jobs]
    else:
        import multiprocessing as mp

        with mp.get_context("fork").Pool(min(num_workers, num_shards)) as pool:
            results = pool.map(_write_shard, jobs)
    return [p for p, _ in results], sum(n for _, n in results)


# ---------------------------------------------------------------------------
# Detection records (VOC/COCO layout)
# ---------------------------------------------------------------------------


def _decode_for_raw(sample: dict) -> np.ndarray | None:
    """Sample's pixels as HWC uint8 (decoding image_bytes robustly);
    None drops an undecodable item (matches _encode_imagenet_item)."""
    if "image_bytes" not in sample:
        return np.asarray(sample["image"], np.uint8)
    from deep_vision_tpu.data.prep import decode_image_robust

    return decode_image_robust(sample["image_bytes"])


def encode_detection_sample(sample: dict, store: str = "jpeg",
                            resize: int = 416) -> tuple[dict, bytes] | None:
    """sample: {"image": HWC uint8 | "image_bytes": jpeg, "boxes": (N,4)
    normalized corners, "classes": (N,)} → (header, payload).

    ``store="raw"``: decode ONCE at build time, SQUARE-resize to
    ``resize``² (the detection geometry is an aspect-distorting square
    resize anyway, and boxes are normalized — so pre-squaring changes no
    label and only re-orders the resampling), store raw uint8 HWC.  The
    read path is then decode-free, and at ``resize`` == the training
    resolution (416 default) the un-cropped half of augmented reads skip
    the resize entirely — measured 145 → 591 img/s/core augmented (1192
    un-augmented) over the JPEG store at 480×640 inputs, above the 541
    img/s one-chip b128 YOLO ceiling (VERDICT r3 weak #7).
    """
    header = {
        "boxes": np.asarray(sample["boxes"], np.float32).reshape(-1, 4).tolist(),
        "classes": np.asarray(sample["classes"], np.int64).reshape(-1).tolist(),
    }
    if store == "raw":
        from deep_vision_tpu.data.transforms import resize_bilinear

        img = _decode_for_raw(sample)
        if img is None:
            return None
        img = np.ascontiguousarray(resize_bilinear(img, resize, resize))
        header["shape"] = list(img.shape)
        header["enc"] = "raw"
        return header, img.tobytes()
    if "image_bytes" in sample:
        payload = sample["image_bytes"]
    else:
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(sample["image"]).save(buf, format="JPEG", quality=95)
        payload = buf.getvalue()
    return header, payload


def scan_records(path: str) -> Iterator[tuple[dict, int, int]]:
    """Headers + (payload_offset, payload_len), WITHOUT reading payloads —
    shard scan is header-sized, not dataset-sized."""
    with open(path, "rb") as f:
        while True:
            raw = f.read(4)
            if len(raw) < 4:
                return
            (hlen,) = _U32.unpack(raw)
            header = json.loads(f.read(hlen))
            (plen,) = _U32.unpack(f.read(4))
            off = f.tell()
            f.seek(plen, 1)
            yield header, off, plen


class _LazySample(dict):
    """Dict-like sample holding (shard path, offset, length) — "image"
    access does a positioned read + JPEG decode.  The sample itself is a
    few hundred bytes, so a COCO-scale dataset costs ~MBs in the parent
    process and pickles cheaply to loader workers (the payload bytes
    never live in Python memory).

    ``cache_decoded=True`` keeps the decoded array on the sample after
    first access — an explicit opt-in for small datasets on big-RAM
    hosts; the default re-decodes per access so worker/parent memory
    stays bounded regardless of epochs (torch-DataLoader semantics).
    Subclasses parse their eager header fields in ``_parse``."""

    def __init__(self, header: dict, src: tuple, cache_decoded: bool):
        super().__init__()
        self._src = src
        self._cache = cache_decoded
        # raw-store payloads (enc="raw") read back with frombuffer —
        # no JPEG decode on the access path
        self._raw_shape = (tuple(header["shape"])
                           if header.get("enc") == "raw" else None)
        self._parse(header)

    def _parse(self, header: dict):
        raise NotImplementedError

    def __getitem__(self, key):
        if key == "image" and not dict.__contains__(self, "image"):
            path, off, plen = self._src
            fd = os.open(path, os.O_RDONLY)
            try:
                payload = os.pread(fd, plen, off)
            finally:
                os.close(fd)
            if self._raw_shape is not None:
                img = np.frombuffer(payload, np.uint8).reshape(
                    self._raw_shape)
            else:
                from PIL import Image

                img = np.asarray(Image.open(io.BytesIO(payload))
                                 .convert("RGB"))
            if self._cache:
                dict.__setitem__(self, "image", img)
            return img
        return dict.__getitem__(self, key)

    def __contains__(self, key):
        return key == "image" or dict.__contains__(self, key)


def _load_lazy_records(root: str, split: str, sample_cls,
                       cache_decoded: bool = False) -> list[dict]:
    shards = list_shards(root, split)
    if not shards:
        raise FileNotFoundError(f"no {split}-*.dvrec under {root}")
    return [sample_cls(header, (s, off, plen), cache_decoded)
            for s in shards for header, off, plen in scan_records(s)]


class _LazyDetectionSample(_LazySample):
    def _parse(self, header: dict):
        self["boxes"] = np.asarray(header["boxes"], np.float32).reshape(-1, 4)
        self["classes"] = np.asarray(header["classes"], np.int64)


def write_detection_records(samples: Sequence[dict], out_dir: str, split: str,
                            num_shards: int = 8, num_workers: int = 8,
                            store: str = "jpeg", resize: int = 416):
    encode = functools.partial(encode_detection_sample, store=store,
                               resize=resize)
    return write_sharded(samples, out_dir, split, num_shards,
                         encode, num_workers)


# ---------------------------------------------------------------------------
# Pose records (MPII layout: keypoints + center + scale —
# Datasets/MPII/tfrecords_mpii.py:54-84 feature semantics)
# ---------------------------------------------------------------------------


def encode_pose_sample(sample: dict, store: str = "jpeg",
                       resize: int = 384) -> tuple[dict, bytes] | None:
    """Pose labels are in PIXEL coordinates (keypoint x/y, center, and
    the MPII person scale whose ·200 is a pixel body height), so the raw
    store's build-time rescale multiplies all three by the same factor —
    ``crop_roi``/heatmap semantics are then identical on the read path."""
    kp = np.asarray(sample["keypoints"], np.float32).reshape(-1, 3)
    center = np.asarray(sample.get("center", (0, 0)), np.float32)
    scale = float(sample.get("scale", 1.0))
    if store == "raw":
        from deep_vision_tpu.data.transforms import rescale

        img = _decode_for_raw(sample)
        if img is None:
            return None
        h, w = img.shape[:2]
        img = np.ascontiguousarray(rescale(img, resize))
        fy, fx = img.shape[0] / h, img.shape[1] / w  # per-axis: the longer
        # side rounds, so one shared factor would drift keypoints <1 px
        kp = np.concatenate([kp[:, 0:1] * fx, kp[:, 1:2] * fy, kp[:, 2:3]],
                            axis=1)
        header = {
            "keypoints": kp.tolist(),
            "center": [float(center[0]) * fx, float(center[1]) * fy],
            "scale": scale * fy,  # scale·200 = body HEIGHT in pixels
            "shape": list(img.shape),
            "enc": "raw",
        }
        return header, img.tobytes()
    if "image_bytes" in sample:
        payload = sample["image_bytes"]
    else:
        from PIL import Image

        buf = io.BytesIO()
        Image.fromarray(sample["image"]).save(buf, format="JPEG", quality=95)
        payload = buf.getvalue()
    header = {
        "keypoints": kp.tolist(),
        "center": center.tolist(),
        "scale": scale,
    }
    return header, payload


class _LazyPoseSample(_LazySample):
    def _parse(self, header: dict):
        self["keypoints"] = np.asarray(header["keypoints"], np.float32)
        self["center"] = np.asarray(header["center"], np.float32)
        self["scale"] = header["scale"]


def write_pose_records(samples: Sequence[dict], out_dir: str, split: str,
                       num_shards: int = 8, num_workers: int = 8,
                       store: str = "jpeg", resize: int = 384):
    encode = functools.partial(encode_pose_sample, store=store,
                               resize=resize)
    return write_sharded(samples, out_dir, split, num_shards,
                         encode, num_workers)


def load_pose_records(root: str, split: str,
                      cache_decoded: bool = False) -> list[dict]:
    return _load_lazy_records(root, split, _LazyPoseSample, cache_decoded)


def load_detection_records(root: str, split: str,
                           cache_decoded: bool = False) -> list[dict]:
    """All shards → list of offset-based lazy samples (positioned read +
    JPEG decode on "image" access; see ``_LazySample`` for the memory
    contract)."""
    return _load_lazy_records(root, split, _LazyDetectionSample,
                              cache_decoded)
