"""GAN input pipelines.

- DCGAN: MNIST in-memory, scaled to [-1, 1] (DCGAN/tensorflow/main.py:21-26
  loads Keras MNIST and normalizes (x-127.5)/127.5).
- CycleGAN: unpaired A/B iterator — the zip-of-two-shuffled-datasets from
  CycleGAN/tensorflow/train.py:74-118; pairing is random per epoch.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np


def to_uint8_wire(x: np.ndarray) -> np.ndarray:
    """float [-1, 1] images → uint8 0–255 (the GAN wire inverse of
    ``(x - 127.5)/127.5``): what the loaders ship when
    ``device_normalize`` keeps the reverse scaling as a traced device
    prologue (ops/preprocess.make_gan_preprocess)."""
    return np.clip(np.round((x + 1.0) * 127.5), 0, 255).astype(np.uint8)


def mnist_gan_data(root: str | None = None, n_synthetic: int = 2048,
                   seed: int = 0,
                   device_normalize: bool = False) -> np.ndarray:
    """(N, 28, 28, 1) float32 in [-1, 1]; falls back to synthetic digits
    when no MNIST directory is given.  ``device_normalize=True`` keeps
    the uint8 wire instead — raw 0–255 bytes, with the (x-127.5)/127.5
    scaling deferred to the traced prologue — so the DCGAN loop's host
    batches and H2D DMA carry 1 byte/pixel like detection/pose."""
    if root:
        from deep_vision_tpu.data.mnist import load_idx_images

        import os

        for cand in ("train-images-idx3-ubyte", "train-images-idx3-ubyte.gz"):
            p = os.path.join(root, cand)
            if os.path.exists(p):
                images = load_idx_images(p)
                break
        else:
            raise FileNotFoundError(f"no MNIST idx images under {root}")
    else:
        from deep_vision_tpu.data.synthetic import synthetic_classification

        images = synthetic_classification(n_synthetic, 28, 1, 10, seed)["image"]
        images = (images - images.min()) / (np.ptp(images) + 1e-9) * 255.0
        images = images[..., 0]
    x = images.astype(np.float32)[..., None] if images.ndim == 3 else images
    if device_normalize:
        return np.clip(np.round(x), 0, 255).astype(np.uint8)
    return (x - 127.5) / 127.5


class GANLoader:
    """Single-domain loader: {"image": (B,H,W,C) in [-1,1]}."""

    def __init__(self, images: np.ndarray, batch_size: int, seed: int = 0):
        self.images = images
        self.batch_size = batch_size
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return len(self.images) // self.batch_size

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng((self.seed, self.epoch))
        idx = rng.permutation(len(self.images))
        for b in range(len(self)):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            yield {"image": self.images[sel]}


class UnpairedLoader:
    """Two-domain loader: {"image_a", "image_b"}, independently shuffled
    (the tf.data zip of shuffled A and B, train.py:74-118)."""

    def __init__(self, images_a: np.ndarray, images_b: np.ndarray,
                 batch_size: int, seed: int = 0):
        self.a, self.b = images_a, images_b
        self.batch_size = batch_size
        self.seed = seed
        self.epoch = 0

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self):
        return min(len(self.a), len(self.b)) // self.batch_size

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng((self.seed, self.epoch))
        ia = rng.permutation(len(self.a))
        ib = rng.permutation(len(self.b))
        for k in range(len(self)):
            s = slice(k * self.batch_size, (k + 1) * self.batch_size)
            yield {"image_a": self.a[ia[s]], "image_b": self.b[ib[s]]}


def synthetic_unpaired(n: int, image_size: int = 64, seed: int = 0,
                       device_normalize: bool = False
                       ) -> tuple[np.ndarray, np.ndarray]:
    """Two translatable domains: same shapes, opposite color casts.
    ``device_normalize=True`` ships both domains as uint8 wire batches
    (reverse scaling runs as the traced GAN prologue)."""
    rng = np.random.default_rng(seed)
    base = rng.uniform(-0.2, 0.2, size=(2 * n, image_size, image_size, 3))
    ys, xs = np.mgrid[0:image_size, 0:image_size] / image_size
    pattern = np.sin(6.28 * ys)[..., None] * np.array([1.0, -1.0, 0.5])
    a = np.clip(base[:n] + pattern * 0.6 + [0.3, -0.3, 0.0], -1, 1)
    b = np.clip(base[n:] - pattern * 0.6 + [-0.3, 0.3, 0.0], -1, 1)
    a, b = a.astype(np.float32), b.astype(np.float32)
    if device_normalize:
        return to_uint8_wire(a), to_uint8_wire(b)
    return a, b
