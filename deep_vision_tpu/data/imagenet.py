"""ImageNet (ILSVRC2012) input pipeline.

Dataset semantics mirror ``ImageNet2012Dataset``
(ResNet/pytorch/data_load.py:14-69): a FLAT directory of JPEGs whose label is
the synset prefix of the filename ("n02708093_7537.JPEG"), mapped to an index
via the metadata file (one "synset name..." line per class —
Datasets/ILSVRC2012/imagenet_2012_metadata.txt).

TPU-first loader design (SURVEY §7 hard-part 1 — keep the chips fed from
host Python):
- files are sharded per HOST (``jax.process_index``) so a multi-host pod
  never reads the same image twice per epoch;
- a multiprocess worker pool decodes+augments (the torch
  ``DataLoader(num_workers=16)`` role, ResNet/pytorch/train.py:229-234);
- batches flow through ``prefetch_to_device`` for double-buffered H2D.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from deep_vision_tpu.data import transforms as T


def load_synset_index(labels_file: str) -> dict[str, int]:
    """synset → class index, line order = index (reference :33-44)."""
    mapping: dict[str, int] = {}
    with open(labels_file) as f:
        for idx, line in enumerate(f):
            line = line.strip()
            if not line:
                continue
            mapping[line.split(" ")[0]] = idx
    return mapping


def _decode(path: str) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        return np.asarray(im.convert("RGB"))  # drops alpha, CMYK→RGB


class ImageNetFolder:
    """Flat-folder dataset: index → (decoded RGB uint8 HWC, label)."""

    def __init__(self, root_dir: str, labels_file: str):
        self.root_dir = root_dir
        self.files = sorted(
            f for f in os.listdir(root_dir)
            if os.path.isfile(os.path.join(root_dir, f)))
        label_to_idx = load_synset_index(labels_file)
        # filename prefix before the first '_' is the synset (reference :60-63)
        self.labels = np.array(
            [label_to_idx[f.split("_")[0]] for f in self.files], np.int32)

    def __len__(self) -> int:
        return len(self.files)

    def read(self, i: int) -> tuple[np.ndarray, int]:
        return _decode(os.path.join(self.root_dir, self.files[i])), int(self.labels[i])


# -- worker-side state (initialized once per worker PROCESS; never shared
# between loaders in-process — the 0-worker path passes cfg explicitly) -----
_WORKER: dict = {}


def _worker_init(cfg: dict):
    _WORKER.update(cfg)


def _load_one(cfg: dict, i: int, seed: int) -> tuple[np.ndarray, np.int32]:
    img = _decode(os.path.join(cfg["root_dir"], cfg["files"][i]))
    if cfg["train"]:
        rng = np.random.default_rng(seed)
        x = T.train_transform(img, rng, cfg["image_size"], cfg["resize"])
    else:
        x = T.eval_transform(img, cfg["image_size"], cfg["resize"])
    return x.astype(np.float32), cfg["labels"][i]


def _worker_load(args) -> tuple[np.ndarray, np.int32]:
    i, seed = args
    return _load_one(_WORKER, i, seed)


class ImageNetLoader:
    """Sharded, multiprocess, epoch-reshuffled batch iterator.

    Yields {"image": (B,H,W,3) f32, "label": (B,) i32} host batches; compose
    with ``prefetch_to_device`` for the H2D double buffer.
    """

    def __init__(self, root_dir: str, labels_file: str, batch_size: int,
                 train: bool = True, image_size: int = 224, resize: int = 256,
                 num_workers: int = 16, seed: int = 0,
                 process_index: int | None = None,
                 process_count: int | None = None):
        import jax

        self.ds = ImageNetFolder(root_dir, labels_file)
        pi = jax.process_index() if process_index is None else process_index
        pc = jax.process_count() if process_count is None else process_count
        # per-host shard: every host sees a disjoint 1/pc slice per epoch
        self.host_indices = np.arange(pi, len(self.ds), pc)
        self.batch_size = batch_size
        self.train = train
        self.image_size, self.resize = image_size, resize
        self.num_workers = num_workers
        self.seed = seed
        self.epoch = 0
        self._cfg = dict(root_dir=self.ds.root_dir, files=self.ds.files,
                         labels=self.ds.labels, train=train,
                         image_size=image_size, resize=resize)
        self._pool = None
        # create the pool EAGERLY on the main thread: forking lazily from the
        # prefetch producer thread can inherit held locks and deadlock
        if self.num_workers > 0:
            import multiprocessing as mp

            ctx = mp.get_context("fork")
            self._pool = ctx.Pool(self.num_workers, initializer=_worker_init,
                                  initargs=(self._cfg,))

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self) -> int:
        return len(self.host_indices) // self.batch_size

    def __iter__(self) -> Iterator[dict]:
        rng = np.random.default_rng((self.seed, self.epoch))
        idx = self.host_indices.copy()
        if self.train:
            rng.shuffle(idx)
        full = len(idx) // self.batch_size
        # eval covers the FULL set: the last partial batch is padded to the
        # static batch size with weight-0 fillers (pad_last semantics)
        partial = (not self.train) and (len(idx) % self.batch_size != 0)
        seeds = rng.integers(0, 2**63 - 1, size=len(idx) + self.batch_size)
        for b in range(full + int(partial)):
            sel = idx[b * self.batch_size:(b + 1) * self.batch_size]
            n_real = len(sel)
            if n_real < self.batch_size:
                sel = np.concatenate(
                    [sel, np.repeat(idx[:1], self.batch_size - n_real)])
            args = [(int(i), int(s)) for i, s in
                    zip(sel, seeds[b * self.batch_size:
                                   b * self.batch_size + self.batch_size])]
            if self._pool is not None:
                out = self._pool.map(_worker_load, args, chunksize=8)
            else:
                out = [_load_one(self._cfg, *a) for a in args]
            batch = {"image": np.stack([o[0] for o in out]),
                     "label": np.asarray([o[1] for o in out], np.int32)}
            if not self.train:
                weight = np.zeros(self.batch_size, np.float32)
                weight[:n_real] = 1.0
                batch["weight"] = weight
            yield batch

    def close(self):
        if self._pool is not None:
            self._pool.terminate()
            self._pool = None
