"""ImageNet (ILSVRC2012) input pipeline.

Dataset semantics mirror ``ImageNet2012Dataset``
(ResNet/pytorch/data_load.py:14-69): a FLAT directory of JPEGs whose label is
the synset prefix of the filename ("n02708093_7537.JPEG"), mapped to an index
via the metadata file (one "synset name..." line per class —
Datasets/ILSVRC2012/imagenet_2012_metadata.txt).

TPU-first loader design (SURVEY §7 hard-part 1 — keep the chips fed from
host Python):
- files are sharded per HOST (``jax.process_index``) so a multi-host pod
  never reads the same image twice per epoch;
- a multiprocess worker pool decodes+augments (the torch
  ``DataLoader(num_workers=16)`` role, ResNet/pytorch/train.py:229-234);
- batches flow through ``prefetch_to_device`` for double-buffered H2D.
"""

from __future__ import annotations

import os
from typing import Iterator

import numpy as np

from deep_vision_tpu.data import transforms as T


def load_synset_index(labels_file: str) -> dict[str, int]:
    """synset → class index, line order = index (reference :33-44)."""
    mapping: dict[str, int] = {}
    with open(labels_file) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue  # blank lines don't consume an index
            # split on ANY whitespace: the reference metadata file is
            # tab-separated ("n01440764\ttench, Tinca tinca")
            mapping[line.split()[0]] = len(mapping)
    return mapping


def _decode(path: str, draft_size: int | None = None) -> np.ndarray:
    from PIL import Image

    with Image.open(path) as im:
        if draft_size is not None:
            # JPEG DCT-domain downscale during decode (1/2, 1/4, 1/8):
            # large photos decode several× faster; PIL guarantees the
            # result stays ≥ the requested size, so rescale() still works
            im.draft("RGB", (draft_size, draft_size))
        return np.asarray(im.convert("RGB"))  # drops alpha, CMYK→RGB


def _decode_bytes(data: bytes, draft_size: int | None = None,
                  fast: bool = False) -> np.ndarray:
    import io

    from PIL import Image

    if fast:
        # cv2 JPEG decode is ~20% faster end-to-end and bit-identical to
        # PIL's (both libjpeg-turbo).  Only safe for SANITIZED sources
        # (prepare_imagenet re-encodes everything to clean RGB JPEG at
        # build time) — cv2 silently mis-decodes CMYK, so the folder path
        # stays on PIL.  A cheap PIL header peek picks the DCT half-size
        # decode when it still covers the resize target (draft semantics).
        from deep_vision_tpu.data.transforms import _cv2

        if _cv2 is not None:
            flag = _cv2.IMREAD_COLOR
            if draft_size is not None:
                with Image.open(io.BytesIO(data)) as im:  # header only
                    w, h = im.size
                # deepest DCT reduction that still covers the resize
                # target — the full 1/2–1/8 ladder PIL's draft offers
                for shift, reduced in ((3, _cv2.IMREAD_REDUCED_COLOR_8),
                                       (2, _cv2.IMREAD_REDUCED_COLOR_4),
                                       (1, _cv2.IMREAD_REDUCED_COLOR_2)):
                    if min(w, h) >> shift >= draft_size:
                        flag = reduced
                        break
            img = _cv2.imdecode(np.frombuffer(data, np.uint8), flag)
            if img is not None and img.ndim == 3 and img.shape[2] == 3:
                return _cv2.cvtColor(img, _cv2.COLOR_BGR2RGB)
            # undecodable by cv2: fall through to the robust PIL path
    with Image.open(io.BytesIO(data)) as im:
        if draft_size is not None:
            im.draft("RGB", (draft_size, draft_size))
        return np.asarray(im.convert("RGB"))


class ImageNetRecords:
    """Random-access view over classification dvrec shards (the consuming
    side of ``prepare_data imagenet`` — the reference's TFRecord trainer
    path, ResNet/tensorflow/train.py:178-214).

    Construction scans shard HEADERS once (seeking over payloads) to build
    an (path, offset, length, label) index; reads are then positioned
    single-payload I/O, so the same multiprocess decode pool as the folder
    loader parallelizes cleanly."""

    def __init__(self, root: str, split: str):
        import json
        import struct

        from deep_vision_tpu.data.records import list_shards

        u32 = struct.Struct("<I")
        # entry = (path, offset, length, shape|None): shape set for
        # train-ready raw-uint8 payloads (prepare_data --store raw), None
        # for JPEG payloads that decode at read time
        self.entries: list[tuple[str, int, int, tuple | None]] = []
        labels: list[int] = []
        shards = list_shards(root, split)
        if not shards:
            raise FileNotFoundError(f"no {split}-*.dvrec under {root}")
        for path in shards:
            with open(path, "rb") as f:
                while True:
                    raw = f.read(4)
                    if len(raw) < 4:
                        break
                    (hlen,) = u32.unpack(raw)
                    header = json.loads(f.read(hlen))
                    (plen,) = u32.unpack(f.read(4))
                    off = f.tell()
                    f.seek(plen, 1)  # skip payload
                    shape = tuple(header["shape"]) \
                        if header.get("enc") == "raw" else None
                    self.entries.append((path, off, plen, shape))
                    labels.append(int(header["label"]))
        self.labels = np.asarray(labels, np.int32)

    def __len__(self) -> int:
        return len(self.entries)


# worker-local fd cache: positioned reads reuse one open fd per shard.
# Capped (LRU-ish) so 1024-shard datasets never approach the per-process
# open-file limit; evicted fds are closed, reopening is cheap
_FDS: dict = {}
_FDS_MAX = 64


def _get_fd(path: str):
    f = _FDS.get(path)
    if f is None:
        while len(_FDS) >= _FDS_MAX:
            # evict the least-recently-used (dicts iterate in insertion
            # order; hits below re-insert, so the front is the coldest)
            old = _FDS.pop(next(iter(_FDS)))
            old.close()
        f = _FDS[path] = open(path, "rb")
    else:  # move-to-end on hit → LRU order holds under round-robin reads
        _FDS[path] = _FDS.pop(path)
    return f


def _pread(path: str, off: int, length: int) -> bytes:
    f = _get_fd(path)
    f.seek(off)
    return f.read(length)


def _close_fds():
    while _FDS:
        _, f = _FDS.popitem()
        f.close()


class ImageNetFolder:
    """Flat-folder dataset: index → (decoded RGB uint8 HWC, label)."""

    def __init__(self, root_dir: str, labels_file: str):
        self.root_dir = root_dir
        self.files = sorted(
            f for f in os.listdir(root_dir)
            if os.path.isfile(os.path.join(root_dir, f)))
        label_to_idx = load_synset_index(labels_file)
        # filename prefix before the first '_' is the synset (reference :60-63)
        self.labels = np.array(
            [label_to_idx[f.split("_")[0]] for f in self.files], np.int32)

    def __len__(self) -> int:
        return len(self.files)

    def read(self, i: int) -> tuple[np.ndarray, int]:
        return _decode(os.path.join(self.root_dir, self.files[i])), int(self.labels[i])


# -- worker-side state (initialized once per worker PROCESS; never shared
# between loaders in-process — the 0-worker path passes cfg explicitly) -----
_WORKER: dict = {}


def _worker_init(cfg: dict):
    _WORKER.update(cfg)


def _load_one(cfg: dict, i: int, seed: int) -> tuple[np.ndarray, np.int32]:
    # draft (DCT-domain downscale) only on the fast uint8 path — the
    # --host-normalize path promises reference-exact decode semantics
    draft = cfg["resize"] if cfg.get("device_normalize") else None
    if "entries" in cfg:  # dvrec shards: positioned read (+ decode)
        path, off, plen, shape = cfg["entries"][i]
        if shape is not None:
            # train-ready raw payload: no decode at all — frombuffer and
            # go straight to crop/flip (the rescale below is a no-op when
            # the build-time short side matches cfg["resize"])
            img = np.frombuffer(_pread(path, off, plen),
                                np.uint8).reshape(shape)
        else:
            # cv2 fast decode: records are sanitized RGB JPEG at build
            # time, and it's gated (like draft) to the device-normalize
            # path — the host-normalize/tf paths keep their
            # reference-exact PIL decode
            img = _decode_bytes(_pread(path, off, plen), draft_size=draft,
                                fast=bool(cfg.get("device_normalize")))
    else:
        img = _decode(os.path.join(cfg["root_dir"], cfg["files"][i]),
                      draft_size=draft)
    if cfg.get("preprocessing") == "tf":
        # TF "ResNet preprocessing" variant (mean-centered 0-255 floats) —
        # host-only, incompatible with the device-normalize split
        if cfg["train"]:
            rng = np.random.default_rng(seed)
            x = T.tf_train_transform(img, rng, cfg["image_size"],
                                     cfg["resize"])
        else:
            x = T.tf_eval_transform(img, cfg["image_size"], cfg["resize"])
        return x, cfg["labels"][i]
    if cfg.get("device_normalize"):
        # uint8 host path: decode+rescale+crop only; jitter+normalize run
        # inside the jitted step (ops/preprocess.py) — 4× smaller H2D
        if cfg["train"]:
            rng = np.random.default_rng(seed)
            return T.train_transform_u8(img, rng, cfg["image_size"],
                                        cfg["resize"]), cfg["labels"][i]
        return T.eval_transform_u8(img, cfg["image_size"],
                                   cfg["resize"]), cfg["labels"][i]
    if cfg["train"]:
        rng = np.random.default_rng(seed)
        x = T.train_transform(img, rng, cfg["image_size"], cfg["resize"])
    else:
        x = T.eval_transform(img, cfg["image_size"], cfg["resize"])
    return x.astype(np.float32), cfg["labels"][i]


def _worker_load(args) -> tuple[np.ndarray, np.int32]:
    i, seed = args
    return _load_one(_WORKER, i, seed)


class ImageNetLoader:
    """Sharded, multiprocess, epoch-reshuffled batch iterator.

    Yields {"image": (B,H,W,3), "label": (B,) i32} host batches — uint8
    images with ``device_normalize`` (the 1-byte/pixel train wire; the
    jitter/normalize runs as the jitted step's traced prologue), float32
    otherwise.  Compose with ``data.pipeline.DevicePrefetcher`` (or the
    legacy ``prefetch_to_device`` shim) for staged H2D.
    """

    def __init__(self, root_dir: str | None, labels_file: str | None,
                 batch_size: int,
                 train: bool = True, image_size: int = 224, resize: int = 256,
                 num_workers: int = 16, seed: int = 0,
                 process_index: int | None = None,
                 process_count: int | None = None,
                 prefetch_batches: int = 2,
                 device_normalize: bool = False,
                 preprocessing: str = "torch",
                 dataset: ImageNetRecords | None = None):
        import jax

        if preprocessing not in ("torch", "tf"):
            raise ValueError(f"preprocessing must be torch|tf, "
                             f"got {preprocessing!r}")
        if preprocessing == "tf" and device_normalize:
            raise ValueError("tf preprocessing is host-side only "
                             "(mean-centered 0-255 floats); disable "
                             "device_normalize")

        # source: flat folder (default) or dvrec shards (``dataset`` /
        # :meth:`from_records`) — downstream identical, only the worker
        # read path differs
        self.ds = dataset if dataset is not None \
            else ImageNetFolder(root_dir, labels_file)
        pi = jax.process_index() if process_index is None else process_index
        pc = jax.process_count() if process_count is None else process_count
        # per-host shard: every host sees a disjoint 1/pc slice per epoch
        self.host_indices = np.arange(pi, len(self.ds), pc)
        self.batch_size = batch_size
        self.train = train
        self.image_size, self.resize = image_size, resize
        self.num_workers = num_workers
        self.seed = seed
        self.epoch = 0
        self.prefetch_batches = max(1, prefetch_batches)
        self._cfg = dict(labels=self.ds.labels, train=train,
                         image_size=image_size, resize=resize,
                         device_normalize=device_normalize,
                         preprocessing=preprocessing)
        #: what this loader ships per pixel — the input-goodput logs and
        #: bench.py --input report H2D traffic against this
        self.wire_dtype = np.uint8 if device_normalize else np.float32
        if isinstance(self.ds, ImageNetRecords):
            self._cfg["entries"] = self.ds.entries
        else:
            self._cfg["root_dir"] = self.ds.root_dir
            self._cfg["files"] = self.ds.files
        self._pool = None
        # create the pool EAGERLY on the main thread. forkserver (spawn as
        # fallback) — NOT fork: by loader-construction time the JAX runtime
        # has live threads, and fork-with-threads can inherit held locks and
        # deadlock nondeterministically on long runs
        if self.num_workers > 0:
            import multiprocessing as mp

            try:
                ctx = mp.get_context("forkserver")
            except ValueError:
                ctx = mp.get_context("spawn")
            self._pool = ctx.Pool(self.num_workers, initializer=_worker_init,
                                  initargs=(self._cfg,))

    @classmethod
    def from_records(cls, root: str, split: str, batch_size: int,
                     **kwargs) -> "ImageNetLoader":
        """Train from ``prepare_data imagenet`` dvrec shards — the
        reference's TFRecord consumption path
        (ResNet/tensorflow/train.py:178-214)."""
        return cls(None, None, batch_size,
                   dataset=ImageNetRecords(root, split), **kwargs)

    def set_epoch(self, epoch: int):
        self.epoch = epoch

    def __len__(self) -> int:
        full = len(self.host_indices) // self.batch_size
        # eval iteration yields one extra weight-padded partial batch so
        # every example is scored exactly once — len() must agree
        if not self.train and len(self.host_indices) % self.batch_size:
            return full + 1
        return full

    def _batch_args(self, idx, seeds, b):
        """(args, n_real) for batch b — padded to the static batch size."""
        from deep_vision_tpu.data.loader import pad_eval_indices

        sel, _, n_real = pad_eval_indices(idx, b * self.batch_size,
                                          self.batch_size)
        args = [(int(i), int(s)) for i, s in
                zip(sel, seeds[b * self.batch_size:
                               b * self.batch_size + self.batch_size])]
        return args, n_real

    def _assemble(self, out, n_real) -> dict:
        batch = {"image": np.stack([o[0] for o in out]),
                 "label": np.asarray([o[1] for o in out], np.int32)}
        if not self.train:
            weight = np.zeros(self.batch_size, np.float32)
            weight[:n_real] = 1.0
            batch["weight"] = weight
        return batch

    def _native_batch(self, args, n_real) -> dict | None:
        """Whole-batch assembly through the C++ reader (data/native):
        positioned reads + crop + flip fused into one call, RNG-exact with
        the Python path (same per-item Generator draw order).  Returns
        None — caller falls back — unless every item is a raw payload at
        the loader's resize on the device-normalize path and the native
        library is available."""
        if not self._cfg.get("device_normalize") or "entries" not in self._cfg:
            return None
        from deep_vision_tpu.data.native import load as load_native

        lib = load_native()
        if lib is None:
            return None
        import ctypes

        entries = self._cfg["entries"]
        size, resize = self.image_size, self.resize
        n = len(args)
        fds = np.empty(n, np.int32)
        offs = np.empty(n, np.int64)
        hs = np.empty(n, np.int32)
        ws = np.empty(n, np.int32)
        tops = np.empty(n, np.int32)
        lefts = np.empty(n, np.int32)
        flips = np.zeros(n, np.uint8)
        labels = np.empty(n, np.int32)
        max_payload = 0
        for j, (i, seed) in enumerate(args):
            path, off, plen, shape = entries[i]
            if shape is None:
                return None  # JPEG payload: decode path handles it
            h, w = int(shape[0]), int(shape[1])
            if min(h, w) != resize or h < size or w < size:
                return None  # stored at a different resize: rescale needed
            if self.train:
                # EXACT draw order of train_transform_u8: flip, then
                # crop top, then crop left, from default_rng(seed)
                r = np.random.default_rng(seed)
                flips[j] = r.random() < 0.5
                tops[j] = r.integers(0, h - size + 1)
                lefts[j] = r.integers(0, w - size + 1)
            else:
                tops[j] = (h - size) // 2
                lefts[j] = (w - size) // 2
            fds[j] = _get_fd(path).fileno()
            offs[j] = off
            hs[j], ws[j] = h, w
            labels[j] = self._cfg["labels"][i]
            max_payload = max(max_payload, plen)
        out = np.empty((n, size, size, 3), np.uint8)
        if getattr(self, "_scratch", None) is None or \
                len(self._scratch) < max_payload:
            self._scratch = np.empty(max_payload, np.uint8)

        def p(a, t):
            return a.ctypes.data_as(ctypes.POINTER(t))

        rc = lib.dvrec_assemble_batch(
            p(fds, ctypes.c_int32), p(offs, ctypes.c_int64),
            p(hs, ctypes.c_int32), p(ws, ctypes.c_int32),
            p(tops, ctypes.c_int32), p(lefts, ctypes.c_int32),
            p(flips, ctypes.c_uint8), n, size,
            p(out, ctypes.c_uint8), p(self._scratch, ctypes.c_uint8))
        if rc != 0:
            return None  # short read etc. — let the Python path report
        batch = {"image": out, "label": labels}
        if not self.train:
            weight = np.zeros(self.batch_size, np.float32)
            weight[:n_real] = 1.0
            batch["weight"] = weight
        return batch

    def __iter__(self) -> Iterator[dict]:
        from collections import deque

        rng = np.random.default_rng((self.seed, self.epoch))
        idx = self.host_indices.copy()
        if self.train:
            rng.shuffle(idx)
        full = len(idx) // self.batch_size
        # eval covers the FULL set: the last partial batch is padded to the
        # static batch size with weight-0 fillers (pad_last semantics)
        partial = (not self.train) and (len(idx) % self.batch_size != 0)
        seeds = rng.integers(0, 2**63 - 1, size=len(idx) + self.batch_size)
        n_batches = full + int(partial)
        if self._pool is None:
            for b in range(n_batches):
                args, n_real = self._batch_args(idx, seeds, b)
                batch = self._native_batch(args, n_real)
                if batch is None:
                    batch = self._assemble(
                        [_load_one(self._cfg, *a) for a in args], n_real)
                yield batch
            return
        # overlapped decode: keep `prefetch_batches` async batches in flight
        # so workers decode batch N+1..N+k while the chip trains on batch N
        # (the DataLoader(num_workers) prefetch role,
        # ResNet/pytorch/train.py:229-234)
        chunk = max(1, self.batch_size // (2 * self.num_workers))
        pending: deque = deque()
        for b in range(n_batches):
            args, n_real = self._batch_args(idx, seeds, b)
            pending.append(
                (self._pool.map_async(_worker_load, args, chunksize=chunk),
                 n_real))
            if len(pending) > self.prefetch_batches:
                res, nr = pending.popleft()
                # a hung decode worker fails the epoch loudly instead of
                # pinning the input pipeline forever
                yield self._assemble(res.get(timeout=600.0), nr)
        while pending:
            res, nr = pending.popleft()
            yield self._assemble(res.get(timeout=600.0), nr)

    def close(self):
        if self._pool is not None:
            self._pool.terminate()  # worker fds die with the processes
            self._pool = None
        _close_fds()  # 0-worker path reads in-process
