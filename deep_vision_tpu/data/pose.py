"""Pose input pipeline — parity with Hourglass/tensorflow/preprocess.py:
keypoint-driven ``crop_roi`` with body-scale margin (:43-88), resize to 256²,
16-channel 64² heatmap targets (:158-173 via ``tasks.pose.make_heatmaps``).

Samples: {"image": HWC uint8, "keypoints": (K,3) [x_px, y_px, visibility],
"center": (2,), "scale": float (MPII person scale, body height = scale·200)}.
"""

from __future__ import annotations

from typing import Iterator, Sequence

import numpy as np

from deep_vision_tpu.data.detection import resize_square
from deep_vision_tpu.data.loader import PreppedSampleLoader
from deep_vision_tpu.tasks.pose import make_heatmaps

MPII_NUM_KEYPOINTS = 16
# symmetric joints swapped under horizontal flip (MPII order:
# 0-5 r/l ankle-knee-hip, 10-15 r/l wrist-elbow-shoulder)
MPII_FLIP_PAIRS = ((0, 5), (1, 4), (2, 3), (10, 15), (11, 14), (12, 13))


def crop_roi(img: np.ndarray, keypoints: np.ndarray, scale: float,
             margin: float = 0.2) -> tuple[np.ndarray, np.ndarray]:
    """Crop around visible keypoints with body-height margin; returns the
    crop + keypoints in normalized crop coords (preprocess.py:43-88)."""
    h, w = img.shape[:2]
    kp = np.asarray(keypoints, np.float32)
    # visible = visibility channel set AND coords valid (MPII marks occluded
    # joints vis=0 while keeping coordinates; negative coords mean absent)
    vis = (kp[:, 2] > 0) & (kp[:, 0] >= 0)
    if not vis.any():
        norm = np.concatenate([kp[:, :2] / [w, h], kp[:, 2:3]], 1)
        return img, norm
    body = scale * 200.0
    x1 = int(max(0, kp[vis, 0].min() - body * margin))
    x2 = int(min(w, kp[vis, 0].max() + body * margin))
    y1 = int(max(0, kp[vis, 1].min() - body * margin))
    y2 = int(min(h, kp[vis, 1].max() + body * margin))
    crop = img[y1:y2, x1:x2]
    ch, cw = max(crop.shape[0], 1), max(crop.shape[1], 1)
    out = kp.copy()
    out[:, 0] = (kp[:, 0] - x1) / cw
    out[:, 1] = (kp[:, 1] - y1) / ch
    return crop, out


def prepare_pose_sample(sample: dict, rng: np.random.Generator, *,
                        image_size: int, heatmap_size: int,
                        flip_perm: np.ndarray, augment: bool,
                        device_normalize: bool = False) -> dict:
    img = sample["image"]
    kp = np.asarray(sample["keypoints"], np.float32)
    crop, norm_kp = crop_roi(img, kp, float(sample.get("scale", 1.0)))
    if augment and rng.random() < 0.5:
        crop = crop[:, ::-1]
        # mirror x AND swap symmetric joints (left wrist ↔ right wrist)
        norm_kp = norm_kp[flip_perm].copy()
        norm_kp[:, 0] = 1.0 - norm_kp[:, 0]
    img = resize_square(crop, image_size)
    x = img if device_normalize else img.astype(np.float32) / 255.0
    hm_kp = np.concatenate(
        [norm_kp[:, :2] * heatmap_size, norm_kp[:, 2:3]], 1)
    heat = make_heatmaps(hm_kp, heatmap_size, heatmap_size)
    return {"image": x, "heatmaps": heat,
            "keypoints": hm_kp.astype(np.float32)}


class PoseLoader(PreppedSampleLoader):
    """Batch iterator: crop → resize 256² → [0,1] floats (or uint8 with
    ``device_normalize``) + 64² heatmaps.  Pool/prefetch/rng semantics:
    :class:`~deep_vision_tpu.data.loader.PreppedSampleLoader`."""

    PREPARE = staticmethod(prepare_pose_sample)

    def __init__(self, samples: Sequence[dict], batch_size: int,
                 image_size: int = 256, heatmap_size: int = 64,
                 num_keypoints: int = MPII_NUM_KEYPOINTS,
                 train: bool = True, seed: int = 0,
                 flip_pairs: Sequence[tuple[int, int]] | None = MPII_FLIP_PAIRS,
                 device_normalize: bool = False, num_workers: int = 0,
                 prefetch_batches: int = 2):
        # channel permutation applied on horizontal flip (left/right swap)
        perm = np.arange(num_keypoints)
        if flip_pairs:
            for a, b in flip_pairs:
                if a < num_keypoints and b < num_keypoints:
                    perm[a], perm[b] = perm[b], perm[a]
        self.flip_perm = perm
        self.image_size = image_size
        self.heatmap_size = heatmap_size
        self.num_keypoints = num_keypoints
        self.device_normalize = device_normalize
        super().__init__(samples, batch_size, train, seed, num_workers,
                         prefetch_batches)

    def _prep_kwargs(self) -> dict:
        return dict(image_size=self.image_size,
                    heatmap_size=self.heatmap_size,
                    flip_perm=self.flip_perm, augment=self.train,
                    device_normalize=self.device_normalize)


def synthetic_pose_dataset(n: int, image_size: int = 256,
                           num_keypoints: int = MPII_NUM_KEYPOINTS,
                           seed: int = 0) -> list[dict]:
    """Learnable synthetic poses: bright dots at keypoint locations."""
    rng = np.random.default_rng(seed)
    samples = []
    for _ in range(n):
        img = rng.integers(0, 48, size=(image_size, image_size, 3),
                           dtype=np.uint8)
        kp = np.zeros((num_keypoints, 3), np.float32)
        for k in range(num_keypoints):
            x = rng.uniform(0.15, 0.85) * image_size
            y = rng.uniform(0.15, 0.85) * image_size
            vis = 1.0 if rng.random() > 0.1 else 0.0
            kp[k] = (x, y, vis)
            if vis:
                xi, yi = int(x), int(y)
                img[max(0, yi - 3):yi + 3, max(0, xi - 3):xi + 3] = \
                    [255, 40 + 12 * k, 220 - 12 * k]
        samples.append({"image": img, "keypoints": kp,
                        "center": np.array([image_size / 2] * 2, np.float32),
                        "scale": image_size / 250.0})
    return samples
