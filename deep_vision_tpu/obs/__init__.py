"""Serving observability: per-request tracing, structured logging, MFU.

Three small, dependency-free pieces that the serving stack
(``deep_vision_tpu/serve``) threads through every layer — batcher,
drainer, router, watchdog, prober — without perturbing the clean hot
path (the same discipline as ``faults.py``: one ``enabled``/``is None``
read guards every touch point):

    trace.py  ``Span`` (per-request stage timestamps + hop notes) and
              ``Tracer`` (bounded in-memory ring of recent traces, a
              slow-request JSONL sampler, per-stage aggregate sums).
              Request ids arrive at the edge (``X-DVT-Request-Id``,
              generated at gateway or backend, propagated via header);
              ``?debug=1`` echoes a request's own breakdown.
    log.py    ``logging``-based structured one-line-JSON events under
              the ``dvt.serve.*`` namespaces (watchdog restarts,
              breaker transitions, quarantines, evacuations each emit
              exactly one line with the request/batch context).
    mfu.py    serving MFU: per-bucket analytic FLOPs (XLA cost
              analysis, with a documented params-based fallback) over
              measured compute-stage seconds against the device peak —
              a ``serving_mfu`` gauge in ``/metrics``, ``/v1/stats``
              and ``bench.py --serve``.

The Prometheus text renderer the ``/metrics`` endpoints use lives in
``core/metrics.py`` (``PromText``) next to ``LatencyHistogram``, whose
fixed shared bin edges are what make cumulative-bucket export and
cross-process merging exact.  Docs: docs/OBSERVABILITY.md.
"""

from deep_vision_tpu.obs.log import configure_logging, event, get_logger
from deep_vision_tpu.obs.mfu import MfuMeter, peak_flops_per_s
from deep_vision_tpu.obs.trace import Span, Tracer, new_request_id

__all__ = ["MfuMeter", "Span", "Tracer", "configure_logging", "event",
           "get_logger", "new_request_id", "peak_flops_per_s"]
