"""Structured logging for the serving stack: one JSON line per event.

Stdlib ``logging`` under the ``dvt.serve.*`` namespaces — no handler or
format is installed at import time, so library use stays silent (the
default root WARNING level makes every INFO ``event`` a cheap
``isEnabledFor`` no-op) and tests capture events with ``caplog``
untouched.  The CLIs (``cli.serve`` / ``cli.gateway``) opt in via
``--log-level`` → ``configure_logging``, which attaches one stderr
handler to the ``dvt`` root.

``event(logger, name, **fields)`` renders ``{"ts": ..., "event": name,
"logger": ..., **fields}`` as a single JSON line — the same shape the
slow-request trace sampler emits, so one ``jq`` pipeline reads both.
"""

from __future__ import annotations

import json
import logging
import time

_ROOT = "dvt"


def get_logger(name: str) -> logging.Logger:
    """A namespaced serving logger, e.g. ``get_logger("dvt.serve.engine")``."""
    return logging.getLogger(name)


def configure_logging(level: str = "info") -> logging.Logger:
    """Attach one stderr handler to the ``dvt`` root at ``level``.

    Idempotent: a second call only adjusts the level.  The root stops
    propagating so configured CLIs don't double-print through the
    global root logger.
    """
    root = logging.getLogger(_ROOT)
    root.setLevel(getattr(logging, level.upper(), logging.INFO))
    if not root.handlers:
        handler = logging.StreamHandler()
        handler.setFormatter(logging.Formatter("%(message)s"))
        root.addHandler(handler)
        root.propagate = False
    return root


def event(logger: logging.Logger, name: str, level: int = logging.INFO,
          **fields):
    """Emit one structured JSON line (skipped entirely when the level is
    off — the guard is the only cost on the unconfigured path)."""
    if not logger.isEnabledFor(level):
        return
    rec = {"ts": round(time.time(), 6), "event": name,
           "logger": logger.name}
    rec.update(fields)
    logger.log(level, json.dumps(rec, default=str))
