"""Per-request spans + the process-wide trace ring and slow sampler.

A ``Span`` is an append-only list of ``(stage, monotonic_ts)`` marks
plus ``(event, detail, ts)`` notes.  The first mark is the origin; each
later mark NAMES THE SEGMENT THAT ENDS AT IT, so the breakdown is the
successive deltas and sums exactly to the span total by construction —
that is what lets a ``?debug=1`` response account for its whole
measured in-server latency instead of an approximation.

Stage names through the serving stack (docs/OBSERVABILITY.md):

    recv → decode → admit → queue_wait → batch_form → staging →
    h2d_dispatch → compute_d2h → retry_exec* → respond

(``retry_exec`` only appears on bisect-retried requests; a stage that
repeats — e.g. a retried request staging twice — accumulates.)  Hops
that don't advance the pipeline are ``notes``: shed, batch_failure,
bisect_retry, quarantined, exec_timeout, rescued, evacuated at the
engine/replica layer; attempt, retry, failover, hedge, hedge_win at
the gateway.

Ownership rule across thread boundaries: whoever CREATES a span
finishes it.  The engine auto-finishes spans it created (via a future
done-callback, so every terminal path — served, shed, quarantined,
timed out — seals the span); the HTTP front-end and gateway create
their own spans, pass them down, and finish after the response is
built.  The engine marks a borrowed span only BEFORE resolving its
future, so the creator's later marks never race the engine's.

The hot-path discipline mirrors ``faults.py``: when tracing is off
(``DVT_SERVE_TRACE=0`` / ``Tracer(enabled=False)``) every touch point
is a single ``span is None`` read.
"""

from __future__ import annotations

import collections
import os
import threading

from deep_vision_tpu.analysis.sanitizer import new_lock
import time
import uuid

from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.serve.trace")

#: response/request header carrying the request id edge-to-edge
REQUEST_ID_HEADER = "X-DVT-Request-Id"


def new_request_id() -> str:
    return uuid.uuid4().hex[:16]


class Span:
    """One request's stage timeline.  Cheap: two lists, no locking —
    marks happen from one thread at a time by the ownership rule."""

    __slots__ = ("request_id", "marks", "notes", "finished")

    def __init__(self, request_id: str | None = None,
                 origin: str = "submit"):
        self.request_id = request_id or new_request_id()
        self.marks: list[tuple[str, float]] = [(origin, time.monotonic())]
        self.notes: list[tuple[str, str, float]] = []
        self.finished = False

    def mark(self, stage: str):
        self.marks.append((stage, time.monotonic()))

    def note(self, name: str, detail: str = ""):
        self.notes.append((name, str(detail)[:200], time.monotonic()))

    @property
    def total_s(self) -> float:
        return self.marks[-1][1] - self.marks[0][1]

    def to_dict(self) -> dict:
        marks = list(self.marks)
        t0 = marks[0][1]
        stages: dict[str, float] = {}
        prev = t0
        for name, t in marks[1:]:
            stages[name] = stages.get(name, 0.0) + (t - prev) * 1e3
            prev = t
        return {"request_id": self.request_id,
                "origin": marks[0][0],
                "total_ms": round((prev - t0) * 1e3, 3),
                "stages": {k: round(v, 3) for k, v in stages.items()},
                "notes": [{"event": e, "detail": d,
                           "at_ms": round((t - t0) * 1e3, 3)}
                          for e, d, t in self.notes]}


class Tracer:
    """Bounded ring of finished traces + slow sampler + stage sums.

    ``ring`` bounds memory (a deque of plain dicts); ``slow_ms`` set →
    any trace over the threshold also emits one structured JSONL line
    (``event: slow_request``) for after-the-fact tail debugging.  The
    per-stage aggregate (total seconds + samples per stage name) is
    what ``bench.py --serve`` reports as the pipeline breakdown.
    """

    def __init__(self, ring: int = 256, slow_ms: float | None = None,
                 enabled: bool | None = None):
        if enabled is None:
            enabled = os.environ.get("DVT_SERVE_TRACE", "1") != "0"
        self.enabled = bool(enabled)
        self.slow_ms = slow_ms
        self.ring: collections.deque[dict] = \
            collections.deque(maxlen=max(1, int(ring)))
        self._lock = new_lock("obs.trace.Tracer._lock")
        self.started = 0  # guarded-by: _lock
        self.finished = 0  # guarded-by: _lock
        self.slow_sampled = 0  # guarded-by: _lock
        self.slow_suppressed = 0  # guarded-by: _lock
        # optional zero-arg predicate: True → drop the slow_request
        # emission (the ring and stage sums still record).  The
        # brownout L1 hook (serve/brownout.py): an overloaded process
        # would otherwise log one line per request, since under
        # saturation EVERY request is slow.
        self.suppress_slow = None
        self._stage_s: dict[str, list] = {}  # stage -> [total_s, samples]; guarded-by: _lock

    def start(self, request_id: str | None = None,
              origin: str = "submit") -> Span | None:
        """A new span, or None when tracing is off (every downstream
        touch point guards on that None)."""
        if not self.enabled:
            return None
        with self._lock:
            self.started += 1
        return Span(request_id, origin)

    def finish(self, span: Span | None):
        """Seal a span into the ring (idempotent; never raises — it runs
        inside future done-callbacks)."""
        if span is None or span.finished:
            return
        span.finished = True
        try:
            d = span.to_dict()
        except Exception:  # noqa: BLE001 — observability must not throw
            return
        slow = self.slow_ms is not None and d["total_ms"] > self.slow_ms
        suppress = False
        if slow and self.suppress_slow is not None:
            try:
                suppress = bool(self.suppress_slow())
            except Exception:  # noqa: BLE001 — observability must not throw
                suppress = False
        with self._lock:
            self.finished += 1
            for stage, ms in d["stages"].items():
                agg = self._stage_s.setdefault(stage, [0.0, 0])
                agg[0] += ms / 1e3
                agg[1] += 1
            if slow:
                if suppress:
                    self.slow_suppressed += 1
                else:
                    self.slow_sampled += 1
            self.ring.append(d)
        if slow and not suppress:
            event(_log, "slow_request", **d)

    def recent(self, n: int = 32) -> list[dict]:
        with self._lock:
            return list(self.ring)[-max(0, int(n)):]

    def summary(self) -> dict:
        with self._lock:
            return {"enabled": self.enabled,
                    "started": self.started,
                    "finished": self.finished,
                    "slow_sampled": self.slow_sampled,
                    "slow_suppressed": self.slow_suppressed,
                    "slow_ms": self.slow_ms,
                    "ring": len(self.ring),
                    "stage_ms_avg": {
                        k: round(v[0] / v[1] * 1e3, 3)
                        for k, v in sorted(self._stage_s.items()) if v[1]},
                    "stage_s_total": {
                        k: round(v[0], 6)
                        for k, v in sorted(self._stage_s.items())},
                    "stage_samples": {
                        k: v[1] for k, v in sorted(self._stage_s.items())}}
