"""Serving MFU: analytic FLOPs over measured compute-stage seconds.

Training has had an MFU number since PR 1 (bench.py, 31.4% on the
reference step); serving had none.  The meter closes that: each bucket
program's FLOP count comes from XLA's own cost analysis on the AOT
executable (``jax.jit(...).lower(...).compile().cost_analysis()`` —
the registry attaches it to the bucket callable at compile time), and
the engine feeds in the measured per-batch compute-stage seconds it
already derives for admission control (completion minus the later of
dispatch or the previous batch's completion, i.e. device occupancy
under pipelining, not queue wait).

    serving_mfu = Σ(batches_b × flops_b) / Σ compute_s / peak_flops

Fallback, documented: when XLA cost analysis is unavailable (a loaded
StableHLO blob has no compiled object; some backends return no
``flops`` key) the registry substitutes ``2 × params × batch`` — a
dense-matmul LOWER BOUND that ignores convolution reuse — and labels
the source ``params_lower_bound`` so a too-good-to-be-true gauge is
never silently wrong.  Peak FLOP/s comes from the same public
spec-sheet table bench.py has always used (bf16 dense, per chip);
non-TPU backends fall back to the v5e figure, which makes CPU-run MFU
honest only as a "> 0 and sane" plumbing check, not a roofline.
"""

from __future__ import annotations

import threading

from deep_vision_tpu.analysis.sanitizer import new_lock

# peak dense bf16 TFLOP/s per chip by device kind (public spec sheets);
# bench.py imports this table — one source of truth for both MFUs
PEAK_BF16_TFLOPS = {
    "TPU v4": 275.0,
    "TPU v5 lite": 197.0,   # v5e
    "TPU v5": 459.0,        # v5p
    "TPU v6 lite": 918.0,   # Trillium
}

_DEFAULT_TFLOPS = 197.0  # conservative: v5e


def peak_tflops(device_kind: str | None = None) -> float:
    """Peak bf16 TFLOP/s for a device kind (current backend if None)."""
    if device_kind is None:
        import jax

        device_kind = jax.devices()[0].device_kind
    for k, v in PEAK_BF16_TFLOPS.items():
        if device_kind.startswith(k):
            return v
    return _DEFAULT_TFLOPS


def peak_flops_per_s(device_kind: str | None = None) -> float:
    return peak_tflops(device_kind) * 1e12


def compiled_flops(compiled) -> float | None:
    """FLOPs of one executable per XLA's cost analysis (honest MFU
    numerator — no hand-derived constants); None when the backend
    doesn't report it.  On a GSPMD-sharded executable the analysis
    covers ONE partition's program — per-shard FLOPs — which is exactly
    the per-chip numerator the meter wants against its per-chip peak
    (a 2×2 mesh running 4 shards shows the same MFU each chip does)."""
    try:
        cost = compiled.cost_analysis()
        ca = cost[0] if isinstance(cost, (list, tuple)) else cost
        return float(ca.get("flops", 0.0)) or None
    except Exception:  # noqa: BLE001 — cost analysis is best-effort
        return None


def params_flops_lower_bound(variables, batch: int,
                             devices: int = 1) -> float:
    """The documented fallback: 2 × param count × batch (one
    multiply-add per weight per image — exact for dense layers, a lower
    bound for convolutions, which reuse each weight spatially).

    Counts float leaves AND int8 leaves: a quantized variables tree
    (serve/quant.py) stores its conv/dense kernels as int8, but each
    dequantized weight still does one MAC per image — excluding them
    would collapse the int8 serving-MFU numerator to biases+scales.

    ``devices`` keeps the per-chip semantics on mesh views: the global
    2·params·batch work divides across the mesh, matching what
    ``compiled_flops`` reports for one partition of a sharded
    executable (the meter's peak is per chip)."""
    import jax
    import numpy as np

    i8 = np.dtype("int8")

    def _counts(a) -> bool:
        dt = getattr(a, "dtype", np.dtype("O"))
        return dt.kind == "f" or dt == i8

    n = sum(int(np.prod(a.shape))
            for a in jax.tree_util.tree_leaves(variables) if _counts(a))
    return 2.0 * n * batch / max(1, int(devices))


def round_mfu(mfu: float | None) -> float | None:
    """6 SIGNIFICANT digits, not 6 decimals: a CPU smoke run's honest
    ~1e-8 MFU must survive reporting instead of rounding to 0."""
    return float(f"{mfu:.6g}") if mfu is not None else None


class MfuMeter:
    """Accumulates (bucket flops × batches) and compute seconds.

    Thread-safe under its own lock: ``observe`` is called from the
    drainer (pipelined path) and from the synchronous retry path.  The
    peak resolves lazily on first ``report`` so constructing an engine
    never initializes the JAX backend.
    """

    def __init__(self, peak: float | None = None):
        self._lock = new_lock("obs.mfu.MfuMeter._lock")
        self._peak = peak
        self._bucket_flops: dict[int, float | None] = {}  # guarded-by: _lock
        self._source: str | None = None  # guarded-by: _lock
        self.batches = 0  # guarded-by: _lock
        self.images = 0  # guarded-by: _lock
        self.compute_s = 0.0  # guarded-by: _lock
        self.flops = 0.0  # guarded-by: _lock
        self.unknown_flops_batches = 0  # guarded-by: _lock

    def set_bucket_flops(self, bucket: int, flops: float | None,
                         source: str | None = None):
        with self._lock:
            self._bucket_flops[int(bucket)] = flops
            if source is not None:
                self._source = source

    def observe(self, bucket: int, images: int, compute_s: float):
        """One executed batch: its bucket, live image count, and
        measured compute-stage seconds."""
        with self._lock:
            self.batches += 1
            self.images += int(images)
            self.compute_s += max(0.0, float(compute_s))
            f = self._bucket_flops.get(int(bucket))
            if f:
                self.flops += f
            else:
                self.unknown_flops_batches += 1

    def peak(self) -> float:
        if self._peak is None:
            self._peak = peak_flops_per_s()
        return self._peak

    def mfu(self) -> float | None:
        with self._lock:
            if self.compute_s <= 0 or self.flops <= 0:
                return None
            flops, secs = self.flops, self.compute_s
        return flops / secs / self.peak()

    def report(self) -> dict:
        mfu = self.mfu()
        with self._lock:
            return {"serving_mfu": round_mfu(mfu),
                    "flops_total": self.flops,
                    "compute_s": round(self.compute_s, 6),
                    "batches": self.batches,
                    "images": self.images,
                    "unknown_flops_batches": self.unknown_flops_batches,
                    "peak_flops_per_s": self._peak,
                    "flops_source": self._source,
                    "flops_by_bucket": {
                        str(b): f for b, f in
                        sorted(self._bucket_flops.items())}}

    @staticmethod
    def merged_report(meters: list["MfuMeter"]) -> dict:
        """Fleet view over replica meters (same process, same peak):
        FLOPs and compute seconds sum; MFU recomputes from the sums."""
        flops = sum(m.flops for m in meters)
        secs = sum(m.compute_s for m in meters)
        peak = meters[0].peak() if meters else peak_flops_per_s()
        mfu = flops / secs / peak if secs > 0 and flops > 0 else None
        by_bucket: dict[str, float | None] = {}
        for m in meters:
            for b, f in m._bucket_flops.items():
                by_bucket.setdefault(str(b), f)
        return {"serving_mfu": round_mfu(mfu),
                "flops_total": flops,
                "compute_s": round(secs, 6),
                "batches": sum(m.batches for m in meters),
                "images": sum(m.images for m in meters),
                "unknown_flops_batches": sum(m.unknown_flops_batches
                                             for m in meters),
                "peak_flops_per_s": peak,
                "flops_source": next((m._source for m in meters
                                      if m._source), None),
                "flops_by_bucket": dict(sorted(by_bucket.items()))}
