from deep_vision_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    replicate,
    shard_batch,
    shard_batch_stacked,
    batch_sharding,
    replicated_sharding,
)
from deep_vision_tpu.parallel.pipeline import (
    PIPE_AXIS,
    pipeline_apply,
    stack_stages,
    unstack_stages,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "PIPE_AXIS",
    "pipeline_apply",
    "stack_stages",
    "unstack_stages",
    "make_mesh",
    "replicate",
    "shard_batch",
    "shard_batch_stacked",
    "batch_sharding",
    "replicated_sharding",
]
