from deep_vision_tpu.parallel.mesh import (
    DATA_AXIS,
    MODEL_AXIS,
    make_mesh,
    replicate,
    shard_batch,
    shard_batch_stacked,
    batch_sharding,
    replicated_sharding,
)

__all__ = [
    "DATA_AXIS",
    "MODEL_AXIS",
    "make_mesh",
    "replicate",
    "shard_batch",
    "shard_batch_stacked",
    "batch_sharding",
    "replicated_sharding",
]
