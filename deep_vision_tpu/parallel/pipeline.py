"""Pipeline (inter-layer) parallelism: a GPipe-style microbatch pipeline
over a ``pipe`` mesh axis.

The reference has no analog (its deepest model, Stacked Hourglass, runs
whole-network data parallel under MirroredStrategy —
Hourglass/tensorflow/train.py:195-226).  On TPU the natural pipelined
workload is exactly that model family: ``num_stack`` identical hourglass
stacks applied sequentially (hourglass104.py:113-159), each mapping a
(B, 64, 64, C) feature carry to the same shape plus a per-stack heatmap
head — same-shape sequential superblocks are the textbook pipeline stage.

Mechanism (idiomatic JAX, no schedule DSL):

- stage parameters are STACKED on a leading stage axis and sharded over
  the ``pipe`` mesh axis, so each device holds S/n consecutive stages;
- one ``lax.scan`` runs the ``M + n - 1`` pipeline ticks; each tick every
  device applies its stages to its in-flight microbatch and hands the
  activation to the next stage's device with a neighbour ``ppermute``
  (a linear shift chain — device 0 is fed by injection and the last
  device's hand-off is dropped; same ICI-neighbour collective the
  spatial halo exchange rides, parallel/spatial.py);
- device 0 injects a fresh microbatch per tick; warm-up/drain bubbles
  compute on zero padding and their results are dropped at collection
  time, so outputs and gradients are EXACTLY those of the sequential
  network (tested to zero error in tests/test_pipeline.py);
- reverse-mode autodiff differentiates the scan + ppermute directly
  (``ppermute``'s transpose is the reverse permutation), giving the
  standard backward pipeline for free — no hand-written schedule.

Composes with data parallelism: on a ``{"data": d, "pipe": p}`` mesh the
batch dim stays sharded over ``data`` while stages shard over ``pipe``;
per-stage state (BatchNorm running stats) is ``pmean``-ed over ``data``
(cross-replica BN semantics, the choice SURVEY §7 "hard part 3" asks to
make explicit).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import DATA_AXIS

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

PIPE_AXIS = "pipe"


def _pvary(x, axes=(PIPE_AXIS,)):
    """Mark ``x`` as varying over ``axes`` for shard_map's
    varying-manual-axes (VMA) type check; no-op on JAX versions without
    the check.  ``pcast(..., to="varying")`` is the current API (probed
    first, guarded since its signature may still move); deprecated
    ``pvary`` is the fallback for versions that predate it."""
    if hasattr(jax.lax, "pcast"):
        try:  # the current API (pvary is deprecated in its favor)
            return jax.lax.pcast(x, tuple(axes), to="varying")
        except TypeError:  # future signature drift: fall through
            pass
    if hasattr(jax.lax, "pvary"):
        return jax.lax.pvary(x, tuple(axes))
    return x

# stage_fn(stage_params, carry, stage_state) -> (carry, out, stage_state)
StageFn = Callable[[Any, jax.Array, Any], tuple[jax.Array, Any, Any]]


def stack_stages(variable_trees: list) -> Any:
    """Stack per-stage pytrees (e.g. S separate ``module.init`` results
    with identical structure) into one tree with a leading stage axis —
    the layout :func:`pipeline_apply` shards over ``pipe``."""
    return jax.tree_util.tree_map(
        lambda *leaves: jnp.stack(leaves), *variable_trees)


def unstack_stages(tree: Any) -> list:
    """Inverse of :func:`stack_stages` (host-side; for checkpoint export
    back to the per-stage layout)."""
    n = jax.tree_util.tree_leaves(tree)[0].shape[0]
    return [jax.tree_util.tree_map(lambda a, i=i: a[i], tree)
            for i in range(n)]


def pipeline_apply(
    stage_fn: StageFn,
    stage_params: Any,
    x: jax.Array,
    *,
    mesh: Mesh,
    num_microbatches: int,
    stage_state: Any = None,
) -> tuple[Any, Any]:
    """Run ``S`` same-shape stages as a microbatch pipeline over the
    ``pipe`` mesh axis.

    ``stage_params``: pytree with leading stage dim ``S`` on every leaf
    (see :func:`stack_stages`); ``S`` must be a multiple of the ``pipe``
    axis size — each device applies its ``S/n`` consecutive stages per
    tick.  ``x``: global ``(B, ...)`` input, which is also the carry
    shape — every stage must map its input shape to itself (the stacked
    hourglass contract).  ``B`` (per data shard) must be divisible by
    ``num_microbatches``.  ``stage_state``: optional per-stage pytree
    (leading dim ``S``) threaded device-locally through the ticks — BN
    running stats; updated only on real (non-bubble) microbatches, and
    averaged over the ``data`` axis when present.

    Returns ``(outs, new_state)`` where ``outs`` stacks every stage's
    per-microbatch output on a leading ``(S, B, ...)`` axis (sharded over
    ``pipe``) — the stacked hourglass's intermediate-supervision heads —
    and ``new_state`` mirrors ``stage_state``.  Both are ordinary global
    arrays; downstream loss code needs no collectives of its own.
    """
    n = mesh.shape[PIPE_AXIS]
    has_data = DATA_AXIS in mesh.shape
    extra = set(mesh.axis_names) - {PIPE_AXIS, DATA_AXIS}
    if extra:
        raise ValueError(f"pipeline_apply handles {{data, pipe}} meshes; "
                         f"mesh has extra axes {sorted(extra)}")
    M = num_microbatches
    S = jax.tree_util.tree_leaves(stage_params)[0].shape[0]
    if S % n:
        raise ValueError(f"stages S={S} not divisible by pipe axis {n}")
    if stage_state is None:
        stage_state = {}
    batch_spec = P(DATA_AXIS) if has_data else P()
    stage_spec = P(PIPE_AXIS)
    out_spec = P(PIPE_AXIS, DATA_AXIS) if has_data else P(PIPE_AXIS)

    def shard_fn(params, state, xs):
        # params/state leaves (S/n, ...); xs (B_local, ...)
        idx = jax.lax.axis_index(PIPE_AXIS)
        b_local = xs.shape[0]
        if b_local % M:
            raise ValueError(
                f"per-shard batch {b_local} not divisible by "
                f"num_microbatches={M}")
        mb = b_local // M
        xs_m = xs.reshape(M, mb, *xs.shape[1:])

        def superstage(carry, st):
            # this device's S/n stages, sequentially
            def body(c, ps):
                p, s = ps
                c, out, s = stage_fn(p, c, s)
                return c, (out, s)

            carry, (outs, st2) = jax.lax.scan(body, carry, (params, st))
            return carry, outs, st2  # outs leaves (S/n, mb, ...)

        ticks = jnp.arange(M + n - 1)
        # scan requires carry types to match: the zero carry becomes
        # pipe-varying after the first hand-off, and per-stage state
        # becomes data-varying once updated from data-sharded microbatches
        if has_data:
            state = jax.tree_util.tree_map(
                lambda a: _pvary(a, (DATA_AXIS,)), state)
        init = (_pvary(jnp.zeros_like(xs_m[0])), state)
        (_, state), outs_t = jax.lax.scan(
            _make_tick(xs_m, superstage, idx, M, n), init, ticks)

        # device d processed microbatch m at tick d + m: select its M
        # real ticks, drop the bubbles
        sel = idx + jnp.arange(M)

        def collect(o):  # (T, S/n, mb, ...) -> (S/n, B_local, ...)
            o = jnp.take(o, sel, axis=0)
            o = jnp.moveaxis(o, 1, 0)
            return o.reshape(o.shape[0], M * mb, *o.shape[3:])

        outs = jax.tree_util.tree_map(collect, outs_t)
        if has_data:  # cross-replica BN: average stats over data shards
            state = jax.tree_util.tree_map(
                lambda a: jax.lax.pmean(a, DATA_AXIS), state)
        return outs, state

    fn = shard_map(
        shard_fn, mesh=mesh,
        in_specs=(stage_spec, stage_spec, batch_spec),
        out_specs=(out_spec, stage_spec))
    return fn(stage_params, stage_state, x)


def _make_tick(xs_m, superstage, idx, M, n):
    """The per-tick scan body (split out for readability)."""

    def tick(c, t):
        carry, st = c
        inject = jax.lax.dynamic_index_in_dim(
            xs_m, jnp.clip(t, 0, M - 1), 0, keepdims=False)
        cur = jnp.where(idx == 0, inject, carry)
        y, outs, st2 = superstage(cur, st)
        valid = (t - idx >= 0) & (t - idx < M)
        st = jax.tree_util.tree_map(
            lambda a, b: jnp.where(valid, b, a), st, st2)
        y = jax.lax.ppermute(y, PIPE_AXIS,
                             [(i, i + 1) for i in range(n - 1)])
        return (y, st), outs

    return tick
