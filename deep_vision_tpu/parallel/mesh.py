"""Device-mesh helpers: the framework's single source of parallelism.

The reference reached multi-device scale three different ways
(``nn.DataParallel`` — ResNet/pytorch/train.py:352-355, ``multi_gpu_model`` —
ResNet/tensorflow/train.py:247-251, ``tf.distribute.MirroredStrategy`` —
YOLO/tensorflow/train.py:281-296).  Here there is exactly one mechanism: a
``jax.sharding.Mesh`` with a ``data`` axis (and an optional ``model`` axis for
tensor parallelism).  Batches are sharded over ``data``; parameters are
replicated (or sharded over ``model``); XLA inserts the gradient all-reduce
(the psum the reference got implicitly from NCCL) over ICI.

Everything works identically on 1 device, 8 CPU "virtual" devices (tests), or
a multi-host pod: ``jit`` + GSPMD scales without code changes.
"""

from __future__ import annotations

import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SPATIAL_AXIS = "spatial"  # image-row (context) axis — see parallel/spatial.py


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh.  Default: all devices on a single ``data`` axis.

    ``axis_sizes`` maps axis name -> size, e.g. ``{"data": 4, "model": 2}``.
    A size of -1 means "all remaining devices".
    """
    if devices is None:
        devices = jax.devices()
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:total]).reshape(tuple(sizes))
    return Mesh(grid, names)


def batch_sharding(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    """Sharding that splits dim 0 over the ``data`` axis (rest replicated)."""
    if ndim == 0:
        return NamedSharding(mesh, P(DATA_AXIS))
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh (params, opt state, ...).

    Works on multi-process meshes too: every process holds the same host
    value (same seed / same restore), so each contributes its addressable
    replicas via ``make_array_from_process_local_data``."""
    sharding = replicated_sharding(mesh)
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x: jax.make_array_from_process_local_data(
                sharding, np.asarray(x)), tree)
    return jax.device_put(tree, sharding)


def shard_batch(tree: Any, mesh: Mesh) -> Any:
    """Device-put a host batch with dim 0 split over the ``data`` axis.

    The global batch size must be divisible by the ``data`` axis size —
    the same contract MirroredStrategy enforced with
    ``global_batch = replicas * per_replica`` (YOLO/tensorflow/train.py:282).

    On a mesh with a ``spatial`` axis, image-like leaves (ndim ≥ 4, H
    divisible) additionally shard dim 1 (rows) over it — GSPMD then
    spatially partitions the convolutions downstream, inserting the halo
    collective-permutes itself, so activations larger than one chip's HBM
    train with NO model changes (the Trainer-reachable counterpart of the
    explicit shard_map kernel in parallel/spatial.py).
    """
    n_data = mesh.shape[DATA_AXIS]
    n_spatial = mesh.shape.get(SPATIAL_AXIS, 1)
    # multi-process: the host batch is this process's LOCAL shard (loaders
    # shard files per host); each process contributes its portion of the
    # global array (the tf.data per-worker dataset semantics)
    multiproc = jax.process_count() > 1

    def _put(x):
        if isinstance(x, jax.Array):  # already placed (e.g. prefetch thread)
            return x
        x = np.asarray(x)
        if x.ndim == 0:
            if multiproc:
                return jax.make_array_from_process_local_data(
                    replicated_sharding(mesh), x)
            return jax.device_put(x, replicated_sharding(mesh))
        global_batch = x.shape[0] * (jax.process_count() if multiproc else 1)
        if global_batch % n_data != 0:
            raise ValueError(
                f"global batch {global_batch} (local {x.shape[0]}) not "
                f"divisible by data axis {n_data}")
        spec = [DATA_AXIS] + [None] * (x.ndim - 1)
        if n_spatial > 1 and x.ndim >= 4 and x.shape[1] % n_spatial == 0:
            spec[1] = SPATIAL_AXIS  # rows over the spatial axis
        sharding = NamedSharding(mesh, P(*spec))
        if multiproc:
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(_put, tree)


def shard_batch_stacked(tree: Any, mesh: Mesh) -> Any:
    """:func:`shard_batch` for K-stacked batches: leaves are (K, B, ...)
    — dim 0 is the scan/step axis (replicated), dim 1 the batch (over
    ``data``), dim 2 image rows (over ``spatial`` where divisible).  The
    device layout of each step's slice matches what ``shard_batch`` would
    produce, so a ``lax.scan`` over dim 0 runs the identical sharded step
    (the Trainer's ``scan_steps`` multi-step dispatch)."""
    n_data = mesh.shape[DATA_AXIS]
    n_spatial = mesh.shape.get(SPATIAL_AXIS, 1)
    multiproc = jax.process_count() > 1

    def _put(x):
        if isinstance(x, jax.Array):
            return x
        x = np.asarray(x)
        if x.ndim <= 1:  # scalars / per-step vectors: replicate
            if multiproc:
                return jax.make_array_from_process_local_data(
                    replicated_sharding(mesh), x)
            return jax.device_put(x, replicated_sharding(mesh))
        global_batch = x.shape[1] * (jax.process_count() if multiproc else 1)
        if global_batch % n_data != 0:
            raise ValueError(
                f"global batch {global_batch} (local {x.shape[1]}) not "
                f"divisible by data axis {n_data}")
        spec = [None, DATA_AXIS] + [None] * (x.ndim - 2)
        if n_spatial > 1 and x.ndim >= 5 and x.shape[2] % n_spatial == 0:
            spec[2] = SPATIAL_AXIS
        sharding = NamedSharding(mesh, P(*spec))
        if multiproc:  # local leaves are this process's batch shard
            return jax.make_array_from_process_local_data(sharding, x)
        return jax.device_put(x, sharding)

    return jax.tree_util.tree_map(_put, tree)
