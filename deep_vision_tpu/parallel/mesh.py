"""Device-mesh helpers: the framework's single source of parallelism.

The reference reached multi-device scale three different ways
(``nn.DataParallel`` — ResNet/pytorch/train.py:352-355, ``multi_gpu_model`` —
ResNet/tensorflow/train.py:247-251, ``tf.distribute.MirroredStrategy`` —
YOLO/tensorflow/train.py:281-296).  Here there is exactly one mechanism: a
``jax.sharding.Mesh`` with a ``data`` axis (and an optional ``model`` axis for
tensor parallelism).  Batches are sharded over ``data``; parameters are
replicated (or sharded over ``model``); XLA inserts the gradient all-reduce
(the psum the reference got implicitly from NCCL) over ICI.

Everything works identically on 1 device, 8 CPU "virtual" devices (tests), or
a multi-host pod: ``jit`` + GSPMD scales without code changes.
"""

from __future__ import annotations

import math
from typing import Any, Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"
SPATIAL_AXIS = "spatial"  # image-row (context) axis — see parallel/spatial.py


def make_mesh(
    axis_sizes: Mapping[str, int] | None = None,
    devices: Sequence[jax.Device] | None = None,
) -> Mesh:
    """Build a Mesh.  Default: all devices on a single ``data`` axis.

    ``axis_sizes`` maps axis name -> size, e.g. ``{"data": 4, "model": 2}``.
    A size of -1 means "all remaining devices".
    """
    if devices is None:
        devices = jax.devices()
    if axis_sizes is None:
        axis_sizes = {DATA_AXIS: len(devices)}
    names = tuple(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = math.prod(s for s in sizes if s != -1)
        sizes[sizes.index(-1)] = len(devices) // known
    total = math.prod(sizes)
    if total > len(devices):
        raise ValueError(
            f"mesh {dict(zip(names, sizes))} needs {total} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:total]).reshape(tuple(sizes))
    return Mesh(grid, names)


def batch_sharding(mesh: Mesh, ndim: int = 0) -> NamedSharding:
    """Sharding that splits dim 0 over the ``data`` axis (rest replicated)."""
    if ndim == 0:
        return NamedSharding(mesh, P(DATA_AXIS))
    return NamedSharding(mesh, P(DATA_AXIS, *([None] * (ndim - 1))))


def replicated_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def replicate(tree: Any, mesh: Mesh) -> Any:
    """Fully replicate a pytree over the mesh (params, opt state, ...)."""
    return jax.device_put(tree, replicated_sharding(mesh))


def shard_batch(tree: Any, mesh: Mesh) -> Any:
    """Device-put a host batch with dim 0 split over the ``data`` axis.

    The global batch size must be divisible by the ``data`` axis size —
    the same contract MirroredStrategy enforced with
    ``global_batch = replicas * per_replica`` (YOLO/tensorflow/train.py:282).

    On a mesh with a ``spatial`` axis, image-like leaves (ndim ≥ 4, H
    divisible) additionally shard dim 1 (rows) over it — GSPMD then
    spatially partitions the convolutions downstream, inserting the halo
    collective-permutes itself, so activations larger than one chip's HBM
    train with NO model changes (the Trainer-reachable counterpart of the
    explicit shard_map kernel in parallel/spatial.py).
    """
    n_data = mesh.shape[DATA_AXIS]
    n_spatial = mesh.shape.get(SPATIAL_AXIS, 1)

    def _put(x):
        if isinstance(x, jax.Array):  # already placed (e.g. prefetch thread)
            return x
        x = np.asarray(x)
        if x.ndim == 0:
            return jax.device_put(x, replicated_sharding(mesh))
        if x.shape[0] % n_data != 0:
            raise ValueError(
                f"batch dim {x.shape[0]} not divisible by data axis {n_data}"
            )
        spec = [DATA_AXIS] + [None] * (x.ndim - 1)
        if n_spatial > 1 and x.ndim >= 4 and x.shape[1] % n_spatial == 0:
            spec[1] = SPATIAL_AXIS  # rows over the spatial axis
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(_put, tree)


def shard_batch_stacked(tree: Any, mesh: Mesh) -> Any:
    """:func:`shard_batch` for K-stacked batches: leaves are (K, B, ...)
    — dim 0 is the scan/step axis (replicated), dim 1 the batch (over
    ``data``), dim 2 image rows (over ``spatial`` where divisible).  The
    device layout of each step's slice matches what ``shard_batch`` would
    produce, so a ``lax.scan`` over dim 0 runs the identical sharded step
    (the Trainer's ``scan_steps`` multi-step dispatch)."""
    n_data = mesh.shape[DATA_AXIS]
    n_spatial = mesh.shape.get(SPATIAL_AXIS, 1)

    def _put(x):
        if isinstance(x, jax.Array):
            return x
        x = np.asarray(x)
        if x.ndim <= 1:  # scalars / per-step vectors: replicate
            return jax.device_put(x, replicated_sharding(mesh))
        if x.shape[1] % n_data != 0:
            raise ValueError(
                f"batch dim {x.shape[1]} not divisible by data axis {n_data}")
        spec = [None, DATA_AXIS] + [None] * (x.ndim - 2)
        if n_spatial > 1 and x.ndim >= 5 and x.shape[2] % n_spatial == 0:
            spec[2] = SPATIAL_AXIS
        return jax.device_put(x, NamedSharding(mesh, P(*spec)))

    return jax.tree_util.tree_map(_put, tree)
