"""Pipeline parallelism as a TRAINING MODE: a Trainer-compatible model
built from a stem module plus ``S`` identical same-shape stage modules,
run as a GPipe microbatch pipeline over the mesh's ``pipe`` axis
(parallel/pipeline.py).

The reference trains its deepest model (Stacked Hourglass,
Hourglass/tensorflow/train.py:195-226) whole-network data-parallel; here
``cli.train -m hourglass104 --mesh data=d,pipe=p`` shards the stack
sequence over devices instead: each device holds S/p stages' params and
optimizer state (placed with :meth:`PipelinedModel.state_partition_rule`)
and only its stages' activations — the memory that actually bounds deep
stacks.

Design notes:
- ``PipelinedModel`` duck-types a Flax module (``init``/``apply``) so the
  unified Trainer (core/trainer.py) uses it unchanged — grad-accum, EMA,
  divergence guard, checkpointing, and scan dispatch all compose.
- The stem runs data-parallel ahead of the pipeline (replicated over
  ``pipe`` — it is a few % of the FLOPs); stages run via
  :func:`pipeline_apply` with BatchNorm running stats threaded as
  device-local pipeline state and pmean-ed over ``data``.
- BN semantics: stages normalize per microbatch per data shard (the
  standard GPipe choice); the monolithic network normalizes over the
  global batch.  With ``num_microbatches=1`` on a ``data=1`` mesh the
  two coincide and the pipelined trajectory matches the monolithic
  :class:`~deep_vision_tpu.models.hourglass.StackedHourglass` exactly
  (tests/test_pipeline_trainer.py).
- Checkpoints store the pipelined layout ({stem, stages}); the
  per-family layout converters (``merge_fn``/``split_fn``, e.g.
  ``models.hourglass.merge_stacked_variables`` or
  ``models.centernet.merge_centernet_variables``) translate to/from the
  monolithic layout for serving and warm starts.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import PartitionSpec as P

from deep_vision_tpu.parallel.mesh import DATA_AXIS
from deep_vision_tpu.parallel.pipeline import (
    PIPE_AXIS,
    pipeline_apply,
    stack_stages,
    unstack_stages,
)


class PipelinedModel:
    """Stem + ``num_stages`` identical same-shape stages as one model.

    ``stage`` must map ``(carry) -> (new_carry, output)`` with carry
    shape preserved (the stacked-hourglass contract); intermediate
    outputs come back as a tuple, one per stage, matching the monolithic
    network's intermediate-supervision interface.

    ``num_microbatches`` defaults to the ``pipe`` axis size (the minimum
    that keeps every pipeline stage busy); it is reduced at trace time
    when a (smaller, e.g. final eval) batch isn't divisible — a static
    shape-derived fallback, numerically exact either way.
    """

    def __init__(self, stem, stage, num_stages: int, mesh,
                 num_microbatches: int | None = None,
                 merge_fn=None, split_fn=None):
        if PIPE_AXIS not in mesh.shape:
            raise ValueError(f"mesh {dict(mesh.shape)} has no "
                             f"'{PIPE_AXIS}' axis")
        if num_stages % mesh.shape[PIPE_AXIS]:
            raise ValueError(
                f"num_stages={num_stages} not divisible by pipe axis "
                f"size {mesh.shape[PIPE_AXIS]}")
        self.stem = stem
        self.stage = stage
        self.num_stages = num_stages
        self.mesh = mesh
        self.num_microbatches = (num_microbatches
                                 or max(mesh.shape[PIPE_AXIS], 1))
        # model-family layout converters (pipelined ↔ monolithic):
        # merge_fn(stem_vars, [stage_vars]) -> monolithic variables;
        # split_fn(variables, [template_stage_vars]) -> (stem, [stages])
        self._merge_fn = merge_fn
        self._split_fn = split_fn

    @classmethod
    def from_stacked_hourglass(cls, model, mesh,
                               num_microbatches: int | None = None):
        """Build the pipelined equivalent of a monolithic
        :class:`~deep_vision_tpu.models.hourglass.StackedHourglass`."""
        from deep_vision_tpu.models.hourglass import (
            HourglassStack,
            HourglassStem,
            StackedHourglass,
        )

        if not isinstance(model, StackedHourglass):
            raise TypeError(
                f"from_stacked_hourglass needs a StackedHourglass; "
                f"got {type(model).__name__}")
        from deep_vision_tpu.models.hourglass import (
            merge_stacked_variables,
            split_stacked_variables,
        )

        stem = HourglassStem(filters=model.filters, dtype=model.dtype)
        stage = HourglassStack(
            num_heatmap=model.num_heatmap, filters=model.filters,
            num_residual=model.num_residual, order=model.order,
            dtype=model.dtype)
        r = model.num_residual
        return cls(stem, stage, model.num_stack, mesh, num_microbatches,
                   merge_fn=lambda sv, sl: merge_stacked_variables(
                       sv, sl, num_residual=r),
                   split_fn=lambda v, tpl: split_stacked_variables(
                       v, tpl, num_residual=r))

    @classmethod
    def from_centernet(cls, model, mesh, num_microbatches: int | None = None):
        """Build the pipelined equivalent of a monolithic
        :class:`~deep_vision_tpu.models.centernet.CenterNet`."""
        from deep_vision_tpu.models.centernet import (
            CenterNet,
            CenterNetStack,
            CenterNetStem,
            merge_centernet_variables,
            split_centernet_variables,
        )

        if not isinstance(model, CenterNet):
            raise TypeError(
                f"from_centernet needs a CenterNet; "
                f"got {type(model).__name__}")
        stem = CenterNetStem(filters=model.filters, dtype=model.dtype)
        stage = CenterNetStack(
            num_classes=model.num_classes, order=model.order,
            filters=model.filters, dtype=model.dtype)
        return cls(stem, stage, model.num_stack, mesh, num_microbatches,
                   merge_fn=merge_centernet_variables,
                   split_fn=split_centernet_variables)

    @classmethod
    def for_model(cls, model, mesh, num_microbatches: int | None = None):
        """Dispatch on the monolithic model's family (what cli.train and
        cli.infer use: any stacked family reachable from a config)."""
        from deep_vision_tpu.models.centernet import CenterNet
        from deep_vision_tpu.models.hourglass import StackedHourglass

        if isinstance(model, StackedHourglass):
            return cls.from_stacked_hourglass(model, mesh, num_microbatches)
        if isinstance(model, CenterNet):
            return cls.from_centernet(model, mesh, num_microbatches)
        raise TypeError(
            f"pipeline training mode supports the stacked-hourglass "
            f"families (StackedHourglass, CenterNet); "
            f"got {type(model).__name__}")

    # ------------------------------------------------------- module protocol

    def init(self, rngs, x, train: bool = False) -> dict:
        """Flax-style init: stem init + ``num_stages`` stage inits stacked
        on a leading stage axis (the layout ``pipeline_apply`` shards)."""
        if not isinstance(rngs, dict):
            rngs = {"params": rngs}
        stem_vars = self.stem.init(rngs, x, train=False)
        carry = self.stem.apply(stem_vars, x, train=False)
        keys = jax.random.split(
            jax.random.fold_in(rngs["params"], 1), self.num_stages)
        stage_vars = [self.stage.init({"params": k}, carry, train=False)
                      for k in keys]
        out = {"params": {
            "stem": stem_vars["params"],
            "stages": stack_stages([v["params"] for v in stage_vars]),
        }}
        if "batch_stats" in stem_vars or "batch_stats" in stage_vars[0]:
            out["batch_stats"] = {
                "stem": stem_vars.get("batch_stats", {}),
                "stages": stack_stages(
                    [v.get("batch_stats", {}) for v in stage_vars]),
            }
        return out

    def apply(self, variables, x, train: bool = False, mutable=False,
              rngs=None):
        params = variables["params"]
        stats = variables.get("batch_stats", {})
        has_bn = bool(stats)
        # one switch for the WHOLE network: batch-statistics BN requires a
        # mutable stats channel, so train-mode without mutable coherently
        # degrades to eval-mode everywhere (stem and stages must never
        # disagree on BN semantics)
        bn_train = train and bool(mutable) and has_bn
        want_mutable = bool(mutable)

        stem_in = {"params": params["stem"]}
        if has_bn:
            stem_in["batch_stats"] = stats["stem"]
        if bn_train:
            carry, stem_upd = self.stem.apply(
                stem_in, x, train=True, mutable=["batch_stats"], rngs=rngs)
            new_stem_stats = stem_upd["batch_stats"]
        else:
            carry = self.stem.apply(stem_in, x, train=False)
            new_stem_stats = stem_in.get("batch_stats", {})

        stage, mesh = self.stage, self.mesh

        def stage_fn(p, c, s):
            vin = {"params": p}
            if has_bn:
                vin["batch_stats"] = s
            if bn_train:
                (c2, out), upd = stage.apply(
                    vin, c, train=True, mutable=["batch_stats"])
                return c2, out, upd["batch_stats"]
            c2, out = stage.apply(vin, c, train=False)
            return c2, out, s

        outs, new_stage_stats = pipeline_apply(
            stage_fn, params["stages"], carry, mesh=mesh,
            num_microbatches=self._microbatches_for(x.shape[0]),
            stage_state=stats.get("stages", {}) if has_bn else None)
        # per-stage outputs may be any pytree (hourglass: one heatmap
        # array; CenterNet: a (heat, wh, offset) tuple)
        outputs = tuple(unstack_stages(outs))
        if want_mutable:
            return outputs, {"batch_stats": {
                "stem": new_stem_stats, "stages": new_stage_stats}}
        return outputs

    def _microbatches_for(self, global_batch: int) -> int:
        """Largest M ≤ ``num_microbatches`` dividing the per-data-shard
        batch (static, shape-derived — eval batches may be smaller)."""
        per_shard = global_batch // self.mesh.shape.get(DATA_AXIS, 1)
        m = max(1, min(self.num_microbatches, per_shard))
        while per_shard % m:
            m -= 1
        return m

    # ------------------------------------------------------------- placement

    def state_partition_rule(self, path: str, leaf) -> P:
        """PartitionSpec for one TrainState leaf: stage-stacked leaves
        (params/EMA/optimizer moments under the ``stages`` subtree) shard
        their leading stage axis over ``pipe``; everything else is
        replicated.  Consumed by ``Trainer._place_state``."""
        if ("stages" in path and getattr(leaf, "ndim", 0) >= 1
                and leaf.shape[0] == self.num_stages):
            return P(PIPE_AXIS)
        return P()

    # ------------------------------------------------------------- export

    def import_monolithic_variables(self, variables, template_variables):
        """Monolithic model variables → pipelined layout (via the
        family's split_fn), so a pipe-mesh run can start from a
        monolithic checkpoint.
        ``template_variables`` is a pipelined ``init`` result — it donates
        the final stage's re-injection convs (absent in the monolithic
        net; they receive no gradient, so values are trajectory-neutral).
        """
        if self._split_fn is None:
            raise NotImplementedError(
                "this PipelinedModel was built without a layout split_fn")
        tp = unstack_stages(template_variables["params"]["stages"])
        has_bn = "batch_stats" in template_variables
        ts = unstack_stages(template_variables["batch_stats"]["stages"]) \
            if has_bn else [{} for _ in tp]
        tpl = []
        for p, s in zip(tp, ts):
            d = {"params": p}
            if s:
                d["batch_stats"] = s
            tpl.append(d)
        stem_v, stage_v = self._split_fn(variables, tpl)
        out = {"params": {
            "stem": stem_v["params"],
            "stages": stack_stages([t["params"] for t in stage_v]),
        }}
        if "batch_stats" in variables:
            out["batch_stats"] = {
                "stem": stem_v.get("batch_stats", {}),
                "stages": stack_stages(
                    [t.get("batch_stats", {}) for t in stage_v]),
            }
        return out

    def export_monolithic_variables(self, params, batch_stats) -> dict:
        """Pipeline-layout state → monolithic model variables (for
        ``cli.infer`` / single-device serving)."""
        if self._merge_fn is None:
            raise NotImplementedError(
                "this PipelinedModel was built without a layout merge_fn")
        params = jax.device_get(params)
        batch_stats = jax.device_get(batch_stats)
        stage_list = []
        p_list = unstack_stages(params["stages"])
        s_list = unstack_stages(batch_stats["stages"]) if batch_stats else \
            [{} for _ in p_list]
        for p, s in zip(p_list, s_list):
            sv = {"params": p}
            if s:
                sv["batch_stats"] = s
            stage_list.append(sv)
        stem_vars = {"params": params["stem"]}
        if batch_stats:
            stem_vars["batch_stats"] = batch_stats["stem"]
        return self._merge_fn(stem_vars, stage_list)
