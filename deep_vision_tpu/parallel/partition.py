"""Parameter partitioning rules for the ``model`` mesh axis (tensor
parallelism).

The reference has no tensor parallelism at all (SURVEY §2.5 — its only
strategy is single-host data parallelism), so this is TPU-native headroom,
not a port: wide trailing dimensions (the ImageNet classifier head, late-stage
2048-channel convs, GAN projection layers) shard over ``model``; everything
else replicates.  GSPMD then inserts the all-gathers/reduce-scatters over ICI.
"""

from __future__ import annotations

from typing import Any

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import MODEL_AXIS


def param_partition_spec(params: Any, mesh: Mesh, min_shard_dim: int = 1024
                         ) -> Any:
    """PartitionSpec pytree: shard a kernel's trailing (output-feature) dim
    over ``model`` when it is large and divisible; replicate the rest."""
    n_model = mesh.shape.get(MODEL_AXIS, 1)

    def spec(x):
        if (n_model > 1 and hasattr(x, "ndim") and x.ndim >= 2
                and x.shape[-1] >= min_shard_dim
                and x.shape[-1] % n_model == 0):
            return P(*([None] * (x.ndim - 1)), MODEL_AXIS)
        return P()

    return jax.tree_util.tree_map(spec, params)


def param_shardings(params: Any, mesh: Mesh, min_shard_dim: int = 1024) -> Any:
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        param_partition_spec(params, mesh, min_shard_dim),
        is_leaf=lambda x: isinstance(x, P))


def shard_params(params: Any, mesh: Mesh, min_shard_dim: int = 1024) -> Any:
    """device_put params according to the partition rules."""
    return jax.tree_util.tree_map(
        jax.device_put, params, param_shardings(params, mesh, min_shard_dim))
