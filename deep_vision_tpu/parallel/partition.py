"""Parameter partitioning for the ``model`` mesh axis (tensor parallelism).

The reference has no tensor parallelism at all (SURVEY §2.5 — its only
strategy is single-host data parallelism), so this is TPU-native headroom,
not a port.  Two mechanisms, layered:

  * **Regex rule tables** (``match_partition_rules``): an ordered list of
    ``(regex, PartitionSpec)`` pairs matched with ``re.search`` against
    each leaf's ``/``-joined path (``params/head/kernel``).  First match
    wins; ``strict=True`` additionally demands every leaf match EXACTLY
    one rule — the reviewable, exact-layout mode for production models.
    Per-model tables for the zoo's wide layers (the ImageNet classifier
    head, late 2048-channel convs, GAN projections) live in
    ``RULE_TABLES`` / ``rules_for``.
  * **First-divisible-axis fallback** (``first_divisible_spec``): when no
    table is given, shard the FIRST dim — scanning trailing→leading, so
    output features keep priority — whose size is ≥ ``min_shard_dim``
    and divisible by the ``model`` axis.  A leaf whose trailing dim is
    large but indivisible is no longer silently replicated: an earlier
    divisible dim is sharded instead, and anything left fully replicated
    above the threshold is LOGGED (no silent caps).

Everything else replicates; GSPMD then inserts the all-gathers /
reduce-scatters over ICI.  ``serve/registry.for_mesh`` consumes the
resulting sharding pytree to lay serving weights across a 2-D
``data × model`` mesh (docs/SERVING.md "2-D mesh serving").
"""

from __future__ import annotations

import re
from typing import Any, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.parallel.mesh import MODEL_AXIS

_log = get_logger("dvt.parallel.partition")


def leaf_paths(tree: Any) -> list[tuple[str, Any]]:
    """``/``-joined leaf names paired with leaves, in tree-flatten order
    (``params/Dense_0/kernel``) — the namespace the rule regexes match."""
    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    out = []
    for path, leaf in flat:
        parts = []
        for k in path:
            if hasattr(k, "key"):
                parts.append(str(k.key))
            elif hasattr(k, "idx"):
                parts.append(str(k.idx))
            else:
                parts.append(str(k))
        out.append(("/".join(parts), leaf))
    return out


def _is_scalar(leaf) -> bool:
    shape = getattr(leaf, "shape", ())
    return len(shape) == 0 or int(np.prod(shape)) == 1


def match_partition_rules(rules: Sequence[tuple[str, P]], params: Any,
                          *, strict: bool = False) -> Any:
    """Map an ordered ``(regex, PartitionSpec)`` table over ``params``.

    Each leaf's ``/``-joined path is matched with ``re.search``.  Scalars
    (and 1-element leaves) always replicate — no rule needed.  Default:
    first match wins, an unmatched leaf replicates.  ``strict=True`` is
    the exact-layout contract: every non-scalar leaf must match EXACTLY
    one rule — zero matches or an overlap raise ``ValueError`` naming
    the leaf and the offending rules, so a table that drifted from the
    checkpoint layout fails loudly at load, not silently at runtime.
    """
    compiled = [(re.compile(pat), pat, spec) for pat, spec in rules]
    specs = []
    for name, leaf in leaf_paths(params):
        if _is_scalar(leaf):
            specs.append(P())
            continue
        hits = [(pat, spec) for rx, pat, spec in compiled
                if rx.search(name)]
        if strict and len(hits) != 1:
            if not hits:
                raise ValueError(
                    f"strict partition rules: leaf '{name}' "
                    f"{tuple(getattr(leaf, 'shape', ()))} matches no rule "
                    f"(table: {[pat for _, pat, _ in compiled]})")
            raise ValueError(
                f"strict partition rules: leaf '{name}' matches "
                f"{len(hits)} rules {[pat for pat, _ in hits]} — each "
                f"leaf must match exactly one")
        specs.append(hits[0][1] if hits else P())
    treedef = jax.tree_util.tree_structure(params)
    return jax.tree_util.tree_unflatten(treedef, specs)


def first_divisible_spec(shape: tuple, n_model: int,
                         min_shard_dim: int = 1024) -> P:
    """The fallback sharder: shard the first dim (trailing→leading, so
    output features keep priority) that is ≥ ``min_shard_dim`` AND
    divisible by the ``model`` axis; replicate when none qualifies."""
    if n_model <= 1 or len(shape) < 2:
        return P()
    for dim in reversed(range(len(shape))):
        if shape[dim] >= min_shard_dim and shape[dim] % n_model == 0:
            spec = [None] * len(shape)
            spec[dim] = MODEL_AXIS
            return P(*spec)
    return P()


def param_partition_spec(params: Any, mesh: Mesh,
                         min_shard_dim: int = 1024,
                         rules: Sequence[tuple[str, P]] | None = None,
                         strict: bool = False) -> Any:
    """PartitionSpec pytree for ``params`` on ``mesh``.

    With ``rules``, the regex table decides (``match_partition_rules``).
    Without, the first-divisible-axis fallback shards wide leaves over
    ``model``.  Either way, every big leaf (≥ ``min_shard_dim`` trailing
    dim) left fully replicated is logged with its shape and the reason —
    replicated HBM is a capacity decision the operator should see, never
    a silent cap."""
    n_model = mesh.shape.get(MODEL_AXIS, 1)
    if rules is not None:
        specs = match_partition_rules(rules, params, strict=strict)
    else:
        treedef = jax.tree_util.tree_structure(params)
        specs = jax.tree_util.tree_unflatten(
            treedef,
            [first_divisible_spec(tuple(getattr(leaf, "shape", ())),
                                  n_model, min_shard_dim)
             for _, leaf in leaf_paths(params)])
    if n_model > 1:
        left_replicated = [
            (name, tuple(leaf.shape))
            for (name, leaf), (_, spec) in zip(leaf_paths(params),
                                               leaf_paths(specs))
            if spec == P() and not _is_scalar(leaf)
            and max(leaf.shape) >= min_shard_dim]
        for name, shape in left_replicated:
            event(_log, "partition_replicated", leaf=name,
                  shape=list(shape), model_axis=n_model,
                  reason="no dim >= min_shard_dim divisible by the "
                         "model axis" if rules is None
                  else "rule table replicates it")
    return specs


def param_shardings(params: Any, mesh: Mesh, min_shard_dim: int = 1024,
                    rules: Sequence[tuple[str, P]] | None = None,
                    strict: bool = False) -> Any:
    """NamedSharding pytree (same structure as ``params``)."""
    return jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s),
        param_partition_spec(params, mesh, min_shard_dim,
                             rules=rules, strict=strict),
        is_leaf=lambda x: isinstance(x, P))


def shard_variables(tree: Any, shardings: Any) -> Any:
    """Place a host variables pytree according to a sharding pytree.

    Single-process: one ``device_put`` (jax accepts a pytree of
    shardings).  Multi-process pods build each global array from the
    (identical) host value via ``make_array_from_callback`` — every
    process holds the full restore, so each addressable shard slices
    its piece locally, no cross-host transfer."""
    if jax.process_count() > 1:
        return jax.tree_util.tree_map(
            lambda x, s: jax.make_array_from_callback(
                np.asarray(x).shape, s,
                lambda idx, x=x: np.asarray(x)[idx]),
            tree, shardings)
    return jax.device_put(tree, shardings)


def shard_params(params: Any, mesh: Mesh, min_shard_dim: int = 1024,
                 rules: Sequence[tuple[str, P]] | None = None,
                 strict: bool = False) -> Any:
    """device_put params according to the partition rules."""
    return shard_variables(
        params, param_shardings(params, mesh, min_shard_dim,
                                rules=rules, strict=strict))


#: Per-model-family rule tables for the zoo's wide layers.  Regexes
#: target Flax param paths (``params/<module>/kernel``).  The tables
#: shard output-feature dims over ``model``; the catch-all replicate
#: rule covers norm/bias/BN stats under first-match-wins.  These are
#: NON-STRICT tables: the catch-all overlaps every specific rule, so
#: ``strict=True`` (exactly-one-match) rejects them by construction —
#: a strict production table must be written disjoint.
RULE_TABLES: dict[str, list[tuple[str, P]]] = {
    # ImageNet-style classifiers (ResNet/VGG/LeNet...): the classifier
    # head's output dim (1000-way) and the late wide convs / dense
    # layers carry most of the bytes — shard their trailing dim
    "classifier": [
        (r"(head|classifier|logits|fc\d*|Dense_\d+)/kernel$",
         P(None, MODEL_AXIS)),
        (r"conv.*/kernel$", P(None, None, None, MODEL_AXIS)),
        (r".*", P()),
    ],
    # GANs (DCGAN/CycleGAN): the generator's latent projection and the
    # discriminator's final dense are the wide matmuls
    "gan": [
        (r"(proj|project|Dense_\d+|fc\d*)/kernel$",
         P(None, MODEL_AXIS)),
        (r"(Conv|ConvTranspose).*/kernel$",
         P(None, None, None, MODEL_AXIS)),
        (r".*", P()),
    ],
}


def rules_for(task: str | None) -> list[tuple[str, P]] | None:
    """The rule table for a serving task family (None = use the
    first-divisible-axis fallback sharder)."""
    if task is None:
        return None
    if task in ("gan", "generation", "cyclegan", "dcgan"):
        return RULE_TABLES["gan"]
    if task in ("classification", "classify"):
        return RULE_TABLES["classifier"]
    return None


def parse_partition_rules(spec: str) -> list[tuple[str, P]]:
    """CLI syntax for ``--partition-rules``: ``;``-separated
    ``regex=axes`` entries, where ``axes`` is a ``,``-separated axis
    name per dim (``-`` or empty = replicate that dim) and an empty
    right-hand side replicates the whole leaf.  A bare table name
    (``classifier``/``gan``) selects the built-in table.

        head/kernel=-,model;conv.*/kernel=-,-,-,model;.*=
    """
    spec = spec.strip()
    if spec in RULE_TABLES:
        return RULE_TABLES[spec]
    rules: list[tuple[str, P]] = []
    for entry in spec.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        if "=" not in entry:
            raise ValueError(
                f"partition rule '{entry}': expected regex=axes "
                "(e.g. 'head/kernel=-,model') or a table name "
                f"{sorted(RULE_TABLES)}")
        pat, _, axes = entry.partition("=")
        axes = axes.strip()
        if not axes:
            rules.append((pat.strip(), P()))
            continue
        dims = [None if a.strip() in ("", "-", "None") else a.strip()
                for a in axes.split(",")]
        rules.append((pat.strip(), P(*dims)))
    if not rules:
        raise ValueError(f"partition rules '{spec}': no entries")
    return rules
