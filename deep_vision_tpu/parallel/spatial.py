"""Spatial (context) parallelism: shard ACTIVATIONS over the image height
axis with ring halo exchange.

The reference has no analog — its "big activation" axis is image resolution,
handled only by shrinking batch sizes (SURVEY §5 long-context: OOM notes
ResNet/pytorch/train.py:141-148).  TPU-native answer: treat H like a sequence
axis — a ``spatial`` mesh axis shards rows across chips, convolutions run on
row shards after exchanging ``halo`` boundary rows with ring neighbours via
``lax.ppermute`` (ICI neighbour traffic, the same pattern as ring attention's
block exchange), so images too large for one chip's HBM train without
changing the model.

Composable with data parallelism: mesh {"data": d, "spatial": s}.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import SPATIAL_AXIS  # single source

try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map


def _same_pad(dim: int, k: int, s: int) -> tuple[int, int]:
    """XLA's SAME padding split (low, high) for one dimension: total
    padding so out = ceil(dim/s), remainder goes to the high side."""
    total = max((-(-dim // s) - 1) * s + k - dim, 0)
    return total // 2, total - total // 2


def halo_exchange(x, halo: int, halo_bottom: int | None = None,
                  axis_name: str = SPATIAL_AXIS, fill_value=0.0):
    """Per-shard (B, H_shard, W, C) → (B, top + H_shard + bottom, W, C).

    ``halo`` rows arrive from the shard above and ``halo_bottom``
    (default: same) from the shard below, via two ring ppermutes; the
    outermost shards get ``fill_value`` rows instead (SAME-padding
    semantics at the true image edge: 0 for convolution, -inf for max
    pooling).  Asymmetric halos are what SAME-under-stride requires
    (XLA puts the odd padding row on the high side).
    """
    top = halo
    bottom = halo if halo_bottom is None else halo_bottom
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    parts = []
    if top:
        bot_rows = x[:, -top:]   # my last rows → neighbour below's top halo
        from_above = jax.lax.ppermute(bot_rows, axis_name, fwd)
        parts.append(jnp.where(idx == 0,
                               jnp.full_like(from_above, fill_value),
                               from_above))
    parts.append(x)
    if bottom:
        top_rows = x[:, :bottom]  # my first rows → neighbour above's bottom
        from_below = jax.lax.ppermute(top_rows, axis_name, bwd)
        parts.append(jnp.where(idx == n - 1,
                               jnp.full_like(from_below, fill_value),
                               from_below))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else x


def _check_row_split(H: int, n_sp: int, sh: int, kh: int):
    """Shared divisibility/halo validation; returns (rows, pad_t, pad_b)."""
    rows = H // n_sp
    if H % n_sp:
        raise ValueError(f"H={H} not divisible by spatial={n_sp}")
    if rows % sh:
        raise ValueError(
            f"rows/shard={rows} not divisible by row stride {sh}: shard "
            f"boundaries would fall between output rows — reshard first")
    pad_top, pad_bottom = _same_pad(H, kh, sh)
    if max(pad_top, pad_bottom) > rows:
        raise ValueError(
            f"halo {max(pad_top, pad_bottom)} exceeds rows/shard={rows}: "
            f"window too tall for this mesh")
    return rows, pad_top, pad_bottom


def spatial_max_pool(x, window=(2, 2), strides=None, *, mesh: Mesh):
    """SAME max-pool with x row-sharded over the ``spatial`` axis — the
    companion to :func:`spatial_conv` (ResNet stem 3×3/2 pool, Hourglass
    2×2/2 downsamples).  Identical to the unsharded ``nn.max_pool(...,
    padding="SAME")``.  Edge halos fill with -inf (the max identity), so
    true-edge windows see exactly XLA's SAME padding.
    """
    wh, ww = tuple(window)
    sh, sw = tuple(strides) if strides is not None else (wh, ww)
    H, W = x.shape[1], x.shape[2]
    _, pad_top, pad_bottom = _check_row_split(H, mesh.shape[SPATIAL_AXIS],
                                              sh, wh)
    pad_w = _same_pad(W, ww, sw)
    neg_inf = jnp.array(-jnp.inf, x.dtype)

    def shard_fn(xs):
        padded = halo_exchange(xs, pad_top, pad_bottom,
                               fill_value=-jnp.inf)
        return jax.lax.reduce_window(
            padded, neg_inf, jax.lax.max, (1, wh, ww, 1), (1, sh, sw, 1),
            ((0, 0), (0, 0), pad_w, (0, 0)))

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=P(None, SPATIAL_AXIS, None, None),
                   out_specs=P(None, SPATIAL_AXIS, None, None))
    x = jax.device_put(x, NamedSharding(mesh, P(None, SPATIAL_AXIS,
                                                None, None)))
    return fn(x)


def spatial_conv(x, kernel, mesh: Mesh, strides=(1, 1)):
    """SAME conv2d with x row-sharded over the ``spatial`` axis.

    x: GLOBAL (B, H, W, Cin) array (sharded or not — it is device_put to
    P(None, "spatial")); kernel: (kh, kw, Cin, Cout) replicated.  Returns
    the global result, identical to an unsharded SAME conv.

    Strides are supported by mapping XLA's asymmetric SAME-under-stride
    padding onto an asymmetric halo: each shard fetches ``pad_top`` rows
    from above and ``pad_bottom`` from below, then runs a VALID strided
    conv on its slab — output rows land exactly on this shard's slice of
    the global output.  Requires the per-shard row count to be a multiple
    of the row stride (so shard boundaries fall on output rows) and each
    halo to fit in one neighbour (max SAME pad side ≤ rows/shard, i.e.
    roughly kh ≤ 2·rows + stride).
    """
    sh, sw = tuple(strides)
    kh, kw = kernel.shape[0], kernel.shape[1]
    H, W = x.shape[1], x.shape[2]
    _, pad_top, pad_bottom = _check_row_split(H, mesh.shape[SPATIAL_AXIS],
                                              sh, kh)
    pad_w = _same_pad(W, kw, sw)

    def shard_fn(xs, ks):
        padded = halo_exchange(xs, pad_top, pad_bottom)
        return jax.lax.conv_general_dilated(
            padded, ks, window_strides=(sh, sw),
            padding=((0, 0), pad_w),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(None, SPATIAL_AXIS, None, None), P()),
                   out_specs=P(None, SPATIAL_AXIS, None, None))
    x = jax.device_put(x, NamedSharding(mesh, P(None, SPATIAL_AXIS,
                                                None, None)))
    kernel = jax.device_put(kernel, NamedSharding(mesh, P()))
    return fn(x, kernel)
