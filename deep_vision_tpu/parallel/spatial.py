"""Spatial (context) parallelism: shard ACTIVATIONS over the image height
axis with ring halo exchange.

The reference has no analog — its "big activation" axis is image resolution,
handled only by shrinking batch sizes (SURVEY §5 long-context: OOM notes
ResNet/pytorch/train.py:141-148).  TPU-native answer: treat H like a sequence
axis — a ``spatial`` mesh axis shards rows across chips, convolutions run on
row shards after exchanging ``halo`` boundary rows with ring neighbours via
``lax.ppermute`` (ICI neighbour traffic, the same pattern as ring attention's
block exchange), so images too large for one chip's HBM train without
changing the model.

Composable with data parallelism: mesh {"data": d, "spatial": s}.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import SPATIAL_AXIS  # single source


def halo_exchange(x, halo: int, axis_name: str = SPATIAL_AXIS):
    """Per-shard (B, H_shard, W, C) → (B, H_shard + 2·halo, W, C).

    Neighbour rows arrive via two ring ppermutes; the outermost shards get
    zero rows instead (SAME zero-padding semantics at the true image edge).
    """
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    top_rows = x[:, :halo]     # my first rows → neighbour above's bottom halo
    bot_rows = x[:, -halo:]    # my last rows → neighbour below's top halo
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    from_above = jax.lax.ppermute(bot_rows, axis_name, fwd)  # shard i-1's tail
    from_below = jax.lax.ppermute(top_rows, axis_name, bwd)  # shard i+1's head
    from_above = jnp.where(idx == 0, jnp.zeros_like(from_above), from_above)
    from_below = jnp.where(idx == n - 1, jnp.zeros_like(from_below),
                           from_below)
    return jnp.concatenate([from_above, x, from_below], axis=1)


def spatial_conv(x, kernel, mesh: Mesh, strides=(1, 1)):
    """Stride-1 SAME conv2d with x row-sharded over the ``spatial`` axis.

    x: GLOBAL (B, H, W, Cin) array (sharded or not — it is device_put to
    P(None, "spatial")); kernel: (kh, kw, Cin, Cout) replicated.  Returns
    the global result, identical to an unsharded SAME conv.

    Strided convs are rejected: XLA's SAME rule pads asymmetrically under
    stride, which a symmetric halo cannot reproduce — downsample with a
    stride-1 halo conv followed by pooling, or reshard first.
    """
    if tuple(strides) != (1, 1):
        raise ValueError(
            f"spatial_conv supports strides=(1,1) only, got {strides}")
    kh = kernel.shape[0]
    halo = (kh - 1) // 2

    def shard_fn(xs, ks):
        padded = halo_exchange(xs, halo) if halo else xs
        return jax.lax.conv_general_dilated(
            padded, ks, window_strides=strides,
            padding=((0, 0), ((ks.shape[1] - 1) // 2,) * 2),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(None, SPATIAL_AXIS, None, None), P()),
                   out_specs=P(None, SPATIAL_AXIS, None, None))
    x = jax.device_put(x, NamedSharding(mesh, P(None, SPATIAL_AXIS,
                                                None, None)))
    kernel = jax.device_put(kernel, NamedSharding(mesh, P()))
    return fn(x, kernel)
