"""Spatial (context) parallelism: shard ACTIVATIONS over the image height
axis with ring halo exchange.

The reference has no analog — its "big activation" axis is image resolution,
handled only by shrinking batch sizes (SURVEY §5 long-context: OOM notes
ResNet/pytorch/train.py:141-148).  TPU-native answer: treat H like a sequence
axis — a ``spatial`` mesh axis shards rows across chips, convolutions run on
row shards after exchanging ``halo`` boundary rows with ring neighbours via
``lax.ppermute`` (ICI neighbour traffic, the same pattern as ring attention's
block exchange), so images too large for one chip's HBM train without
changing the model.

Composable with data parallelism: mesh {"data": d, "spatial": s}.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from deep_vision_tpu.parallel.mesh import SPATIAL_AXIS  # single source


def _same_pad(dim: int, k: int, s: int) -> tuple[int, int]:
    """XLA's SAME padding split (low, high) for one dimension: total
    padding so out = ceil(dim/s), remainder goes to the high side."""
    total = max((-(-dim // s) - 1) * s + k - dim, 0)
    return total // 2, total - total // 2


def halo_exchange(x, halo: int, halo_bottom: int | None = None,
                  axis_name: str = SPATIAL_AXIS):
    """Per-shard (B, H_shard, W, C) → (B, top + H_shard + bottom, W, C).

    ``halo`` rows arrive from the shard above and ``halo_bottom``
    (default: same) from the shard below, via two ring ppermutes; the
    outermost shards get zero rows instead (SAME zero-padding semantics
    at the true image edge).  Asymmetric halos are what SAME-under-stride
    requires (XLA puts the odd padding row on the high side).
    """
    top = halo
    bottom = halo if halo_bottom is None else halo_bottom
    n = jax.lax.psum(1, axis_name)
    idx = jax.lax.axis_index(axis_name)
    fwd = [(i, (i + 1) % n) for i in range(n)]
    bwd = [(i, (i - 1) % n) for i in range(n)]
    parts = []
    if top:
        bot_rows = x[:, -top:]   # my last rows → neighbour below's top halo
        from_above = jax.lax.ppermute(bot_rows, axis_name, fwd)
        parts.append(jnp.where(idx == 0, jnp.zeros_like(from_above),
                               from_above))
    parts.append(x)
    if bottom:
        top_rows = x[:, :bottom]  # my first rows → neighbour above's bottom
        from_below = jax.lax.ppermute(top_rows, axis_name, bwd)
        parts.append(jnp.where(idx == n - 1, jnp.zeros_like(from_below),
                               from_below))
    return jnp.concatenate(parts, axis=1) if len(parts) > 1 else x


def spatial_conv(x, kernel, mesh: Mesh, strides=(1, 1)):
    """SAME conv2d with x row-sharded over the ``spatial`` axis.

    x: GLOBAL (B, H, W, Cin) array (sharded or not — it is device_put to
    P(None, "spatial")); kernel: (kh, kw, Cin, Cout) replicated.  Returns
    the global result, identical to an unsharded SAME conv.

    Strides are supported by mapping XLA's asymmetric SAME-under-stride
    padding onto an asymmetric halo: each shard fetches ``pad_top`` rows
    from above and ``pad_bottom`` from below, then runs a VALID strided
    conv on its slab — output rows land exactly on this shard's slice of
    the global output.  Requires the per-shard row count to be a multiple
    of the row stride (so shard boundaries fall on output rows) and each
    halo to fit in one neighbour (max SAME pad side ≤ rows/shard, i.e.
    roughly kh ≤ 2·rows + stride).
    """
    sh, sw = tuple(strides)
    kh, kw = kernel.shape[0], kernel.shape[1]
    n_sp = mesh.shape[SPATIAL_AXIS]
    H, W = x.shape[1], x.shape[2]
    rows = H // n_sp
    if H % n_sp:
        raise ValueError(f"H={H} not divisible by spatial={n_sp}")
    if rows % sh:
        raise ValueError(
            f"rows/shard={rows} not divisible by row stride {sh}: shard "
            f"boundaries would fall between output rows — reshard first")
    pad_top, pad_bottom = _same_pad(H, kh, sh)
    if max(pad_top, pad_bottom) > rows:
        raise ValueError(
            f"halo {max(pad_top, pad_bottom)} exceeds rows/shard={rows}: "
            f"kernel too tall for this mesh")
    pad_w = _same_pad(W, kw, sw)

    def shard_fn(xs, ks):
        padded = halo_exchange(xs, pad_top, pad_bottom)
        return jax.lax.conv_general_dilated(
            padded, ks, window_strides=(sh, sw),
            padding=((0, 0), pad_w),
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    try:
        from jax import shard_map
    except ImportError:  # older jax
        from jax.experimental.shard_map import shard_map

    fn = shard_map(shard_fn, mesh=mesh,
                   in_specs=(P(None, SPATIAL_AXIS, None, None), P()),
                   out_specs=P(None, SPATIAL_AXIS, None, None))
    x = jax.device_put(x, NamedSharding(mesh, P(None, SPATIAL_AXIS,
                                                None, None)))
    kernel = jax.device_put(kernel, NamedSharding(mesh, P()))
    return fn(x, kernel)
