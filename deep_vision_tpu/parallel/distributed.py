"""Multi-host initialization + pod-aware meshes.

The reference's multi-node story is aspirational (README lists a
``train_dist.py`` that does not exist — SURVEY §2.5); its real scope is
single-host multi-GPU with NCCL hidden inside DataParallel/MirroredStrategy.
Here multi-host is first-class and three lines:

    from deep_vision_tpu.parallel import distributed
    distributed.initialize()          # no-op single-host; JAX runtime on pods
    mesh = distributed.make_pod_mesh({"data": -1})

- ``initialize`` wires ``jax.distributed`` from standard cluster env vars
  (auto-detected on Cloud TPU pods; explicit coordinator for DCN clusters).
- ``make_pod_mesh`` builds hybrid ICI×DCN meshes with
  ``mesh_utils.create_hybrid_device_mesh`` so collectives ride ICI within a
  slice and only cross DCN on the outer (data) axis — the layout rule from
  the scaling playbook.
- Host-side loaders already shard per-process (data/imagenet.py uses
  ``jax.process_index``), so the same CLI runs on 1 chip or a v4-32 pod.
"""

from __future__ import annotations

import os
from typing import Mapping, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

_INITIALIZED = False


def initialize(coordinator_address: str | None = None,
               num_processes: int | None = None,
               process_id: int | None = None) -> None:
    """Initialize jax.distributed for multi-host runs.

    No-op when single-process (nothing configured and no cluster env).
    On Cloud TPU pods jax auto-detects everything; on DCN clusters pass the
    coordinator explicitly or set JAX_COORDINATOR_ADDRESS / JAX_NUM_PROCESSES
    / JAX_PROCESS_ID.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    env_np = os.environ.get("JAX_NUM_PROCESSES")
    env_pid = os.environ.get("JAX_PROCESS_ID")
    # auto-init only on a real TPU pod; CPU/virtual-device runs must stay
    # single-process.  Multi-host shows up either as a multi-entry worker
    # list (one slice, many hosts) or a megascale coordinator (multislice,
    # possibly one worker per slice).
    multi_worker = len([h for h in os.environ.get(
        "TPU_WORKER_HOSTNAMES", "").split(",") if h]) > 1
    multislice = bool(os.environ.get("MEGASCALE_COORDINATOR_ADDRESS"))
    on_tpu_pod = (multi_worker or multislice) and \
        jax.default_backend() == "tpu"
    if coordinator_address is None and not on_tpu_pod:
        return  # single host
    kwargs = {}
    if coordinator_address:
        kwargs["coordinator_address"] = coordinator_address
        # pass counts only when known — jax.distributed can infer them
        # from the cluster environment (SLURM, TPU metadata) otherwise
        np_val = num_processes if num_processes is not None else env_np
        pid_val = process_id if process_id is not None else env_pid
        if np_val is not None:
            kwargs["num_processes"] = int(np_val)
        if pid_val is not None:
            kwargs["process_id"] = int(pid_val)
    jax.distributed.initialize(**kwargs)
    _INITIALIZED = True


def make_pod_mesh(axis_sizes: Mapping[str, int],
                  dcn_axis: str = "data") -> Mesh:
    """Hybrid mesh: ``dcn_axis`` spans slices over DCN, every other axis
    stays inside a slice on ICI.  Falls back to a plain mesh on one slice.

    ``-1`` sizes are resolved against the global device count.
    """
    from jax.experimental import mesh_utils

    devices = jax.devices()
    names = list(axis_sizes.keys())
    sizes = list(axis_sizes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
    num_slices = max(getattr(d, "slice_index", 0) for d in devices) + 1
    if num_slices > 1 and dcn_axis in names:
        dcn_parallelism = [1] * len(names)
        dcn_parallelism[names.index(dcn_axis)] = num_slices
        ici = list(sizes)
        ici[names.index(dcn_axis)] = sizes[names.index(dcn_axis)] // num_slices
        grid = mesh_utils.create_hybrid_device_mesh(
            ici, dcn_parallelism, devices=devices)
    else:
        grid = mesh_utils.create_device_mesh(sizes, devices=devices)
    return Mesh(grid, tuple(names))
