"""Engine health: heartbeats + an explicit OK → DEGRADED → DEAD machine.

Production model servers treat deep health as first-class (Clipper,
NSDI'17: supervised containers behind health probes); a static 200 from
``/v1/healthz`` tells a load balancer nothing when the batcher thread is
dead and every future parks forever.  ``EngineHealth`` is the one place
the engine's failure signals converge:

  * **heartbeats** — the batcher and drainer publish a timestamp every
    loop iteration (a dict store, no lock: GIL-atomic); the watchdog and
    the health report read the age.
  * **state machine** — ``record_failure`` counts consecutive batch
    failures: ``>= degraded_after`` → DEGRADED, ``>= dead_after`` →
    DEAD; any successful batch resets to OK.  ``force_dead`` (restart
    budget exhausted) is sticky — only an operator restart revives it.
  * **healthz semantics** — ``/v1/healthz`` returns 503 while any
    engine *cannot serve*, and 200 again once it can.  A single
    ``BatchingEngine`` can't serve when DEGRADED or DEAD (drain
    traffic to healthy replicas); a ``ReplicatedEngine`` aggregates
    one ``EngineHealth`` per replica plus its own for the router, and
    can't serve only when the router is sticky-DEAD or *every* replica
    is DEAD — one dead replica out of N reports ``degraded`` with
    per-replica detail, still 200 (the ``can_serve`` key in each
    engine's report carries the distinction to ``http.py``).

The failure *counters* live on the engine (retries, quarantines,
timeouts — they're batch-plumbing); the *verdict* lives here.
"""

from __future__ import annotations

import threading

from deep_vision_tpu.analysis.sanitizer import new_lock
import time

OK = "ok"
DEGRADED = "degraded"
DEAD = "dead"


class EngineHealth:
    def __init__(self, degraded_after: int = 1, dead_after: int = 5):
        self.degraded_after = max(1, int(degraded_after))
        self.dead_after = max(self.degraded_after, int(dead_after))
        self._lock = new_lock("serve.health.EngineHealth._lock")
        self._beats: dict[str, float] = {}
        self.state = OK  # guarded-by: _lock
        self.consecutive_failures = 0  # guarded-by: _lock
        self.failures = 0  # guarded-by: _lock
        self.successes = 0  # guarded-by: _lock
        self.watchdog_restarts = 0  # guarded-by: _lock
        self.last_success_at: float | None = None  # guarded-by: _lock
        self.last_failure_at: float | None = None  # guarded-by: _lock
        self.dead_reason: str | None = None  # guarded-by: _lock
        self._forced_dead = False  # guarded-by: _lock

    # -- heartbeats --------------------------------------------------------

    def beat(self, name: str):
        self._beats[name] = time.monotonic()  # GIL-atomic store, no lock

    def heartbeat_age_s(self, name: str, now: float | None = None
                        ) -> float | None:
        t = self._beats.get(name)
        if t is None:
            return None
        return (now if now is not None else time.monotonic()) - t

    # -- state machine -----------------------------------------------------

    def record_failure(self, now: float | None = None):
        with self._lock:
            self.failures += 1
            self.consecutive_failures += 1
            self.last_failure_at = now if now is not None \
                else time.monotonic()
            if self._forced_dead:
                return
            if self.consecutive_failures >= self.dead_after:
                self.state = DEAD
                self.dead_reason = (f"{self.consecutive_failures} "
                                    f"consecutive batch failures")
            elif self.consecutive_failures >= self.degraded_after:
                self.state = DEGRADED

    def record_success(self, now: float | None = None):
        with self._lock:
            self.successes += 1
            self.consecutive_failures = 0
            self.last_success_at = now if now is not None \
                else time.monotonic()
            if not self._forced_dead:
                self.state = OK
                self.dead_reason = None

    def record_restart(self):
        with self._lock:
            self.watchdog_restarts += 1

    def force_dead(self, reason: str):
        """Sticky DEAD (restart budget exhausted): traffic can't revive
        it — only an operator stop()/start() cycle (``revive``)."""
        with self._lock:
            self.state = DEAD
            self.dead_reason = reason
            self._forced_dead = True

    def revive(self):
        with self._lock:
            self._forced_dead = False
            self.state = OK
            self.dead_reason = None
            self.consecutive_failures = 0

    @property
    def healthy(self) -> bool:
        return self.state == OK

    # -- observability -----------------------------------------------------

    def report(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._lock:
            out = {"state": self.state,
                   "consecutive_failures": self.consecutive_failures,
                   "failures": self.failures,
                   "successes": self.successes,
                   "watchdog_restarts": self.watchdog_restarts,
                   "dead_reason": self.dead_reason}
        out["heartbeat_age_s"] = {
            name: round(age, 4) for name in list(self._beats)
            if (age := self.heartbeat_age_s(name, now)) is not None}
        for k, attr in (("last_success_age_s", self.last_success_at),
                        ("last_failure_age_s", self.last_failure_at)):
            out[k] = round(now - attr, 4) if attr is not None else None
        return out
