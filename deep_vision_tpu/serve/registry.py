"""Model registry: name → ServingModel, loadable from two artifact kinds.

  * a training workdir — the shared restore path (``core/restore.py``:
    best-checkpoint preference, pipeline→monolithic conversion, EMA
    params), then per-bucket AOT compiles of ``model.apply``;
  * a StableHLO blob (``core/export.load_exported``) — Python-model-free
    serving of the export CLI's artifact, pinned to the batch shape it
    was traced at.

Both present the same surface to the engine: ``compile_bucket(b)`` hands
back a callable for a padded batch of exactly ``b`` images, so the
batcher owns WHEN to compile (and counts it) while the model owns HOW.

Execution contract (what the pipelined engine relies on):

  * callables accept either a host numpy batch or an already-transferred
    ``jax.Array`` (the engine stages + ``device_put``s itself so H2D
    overlaps the previous batch's compute; direct callers may pass
    numpy);
  * outputs are DEVICE-NATIVE and unblocked — the callable never calls
    ``block_until_ready``/``device_get``, so dispatch returns
    immediately and the engine's drainer performs the single bulk D2H
    per batch;
  * checkpoint-backed programs are compiled with the image argument
    DONATED (``donates_inputs``) where the runtime allows, recycling the
    padded batch's device allocation into the outputs; StableHLO blobs
    keep their exported (non-donating) signature.

Multi-device placement (serve/replicas.py, docs/SERVING.md):

  * ``placement`` is the model's input sharding (None = runtime default
    device).  The engine transfers every staged batch with
    ``jax.device_put(buf, placement)`` so the SAME engine code drives
    the default device, a pinned replica device, or a sharded mesh;
  * ``for_device(dev)`` returns a per-device VIEW: the variables are
    ``device_put`` to that device exactly once (at replica-set build,
    i.e. registry load time) and every bucket program is AOT-compiled
    pinned to it via sharded ``ShapeDtypeStruct``s — N replica views of
    one checkpoint share the host restore but own their device copies;
  * ``for_mesh(mesh)`` returns a mesh-sharded VIEW: bucket programs
    compiled with the batch dim laid across the ``data`` axis, so one
    logical padded mega-batch uses every chip (``--shard-batches``).
    On a 2-D ``data × model`` mesh the variables are additionally laid
    out by the regex partition rules (parallel/partition.py) — each
    chip holds only its addressable shard of the wide leaves, GSPMD
    inserts the ICI collectives, and ``param_bytes()`` prices the
    per-chip shard (``--mesh data,model`` + ``--partition-rules``).
"""

from __future__ import annotations

import warnings

import numpy as np

#: supported serving wire formats: what dtype the client ships and the
#: engine stages/H2D-transfers.  uint8 carries raw 0–255 pixels (4×
#: fewer bytes than float32) and moves normalization into the bucket
#: program's traced prologue (ops/preprocess.make_serve_preprocess);
#: float32 is the original host-normalized contract.
WIRE_DTYPES = ("float32", "uint8")
#: supported on-device compute dtypes (outputs are always float32):
#: bfloat16 casts params once at load; int8 post-training-quantizes
#: them (serve/quant.py) — int8-resident weights, fused ingest
#: quantize, float32 accumulation and outputs
INFER_DTYPES = ("float32", "bfloat16", "int8")


class ServingModel:
    """One deployable model: metadata + per-bucket compiled forwards."""

    #: whether compile_bucket programs donate their image input buffer
    donates_inputs = False

    def __init__(self, name: str, *, task: str, input_shape: tuple,
                 num_classes: int, config_name: str | None = None,
                 fixed_batch: int | None = None,
                 wire_dtype: str = "float32",
                 infer_dtype: str = "float32"):
        if str(wire_dtype) not in WIRE_DTYPES:
            raise ValueError(f"wire_dtype '{wire_dtype}' unsupported "
                             f"(have {WIRE_DTYPES})")
        if str(infer_dtype) not in INFER_DTYPES:
            raise ValueError(f"infer_dtype '{infer_dtype}' unsupported "
                             f"(have {INFER_DTYPES})")
        self.name = name
        self.task = task
        # (H, W, C) for image-in workloads, (latent_dim,) for
        # latent-in generative models — batch dim always excluded
        self.input_shape = tuple(input_shape)
        self.num_classes = num_classes
        self.config_name = config_name or name
        # the workload adapter serving this model's task: verbs,
        # codec, epilogue, SLO class, agreement metric
        # (serve/workloads.py — one shared stateless instance per verb)
        from deep_vision_tpu.serve.workloads import workload_for_task

        self.workload = workload_for_task(task)
        # wire dtype of the OUTPUT payload when the workload ships one
        # on-device-encoded (generate: "uint8"); None = small host-side
        # decode, no output wire contract
        self.output_wire: str | None = None
        # what the engine stages + transfers (np dtype: the StagingPool
        # buffers and the bulk H2D device_put carry exactly this)
        self.wire_dtype = np.dtype(str(wire_dtype))
        # what the bucket programs compute in (outputs stay float32)
        self.infer_dtype = str(infer_dtype)
        # StableHLO blobs are traced at one batch shape; checkpoint-backed
        # models compile any bucket (None = unconstrained)
        self.fixed_batch = fixed_batch
        # input sharding (jax.sharding.Sharding) the engine device_puts
        # staged batches with; None = runtime default device.  Set by
        # for_device()/for_mesh() views.
        self.placement = None
        # which checkpoint step the weights came from (None = random
        # init) and whether restore fell back past a corrupt newer step
        # — set by the registry loaders, surfaced in describe()
        self.restored_step: int | None = None
        self.restore_fallback = False
        # checkpoint-dir mtime + params byte digest (core/restore.py):
        # the control plane's "same weights?" identity for reload
        # detection, surfaced in describe() alongside the step
        self.restored_mtime: float | None = None
        self.params_digest: str | None = None
        # version number under the control plane's versioned model
        # table (serve/models.py); None outside plane-managed serving
        self.serve_version: int | None = None
        # cascade front-tier knob (serve/cascade.py): K > 0 makes the
        # classify workload fuse a softmax+top-K confidence epilogue
        # into this model's bucket programs, so the cascade router
        # reads (top1_class, top1_prob) off the bulk D2H instead of
        # dense logits.  0 = plain dense-logits serving.
        self.cascade_topk: int = 0
        # detect decode knobs (serve/workloads.py DetectWorkload),
        # read at bucket-compile time by make_epilogue and copied
        # across reloads by models._load_model.  "device" (default)
        # fuses decode → threshold → top-k → class-wise NMS into the
        # bucket programs so D2H ships K fixed-size boxes per image;
        # "host" keeps the dense pyramid on the wire and decodes in
        # respond() — the A/B baseline and D2H-comparison path.  The
        # score threshold is the compiled FLOOR: per-request
        # thresholds above it trim host-side.
        self.detect_decode: str = "device"
        self.detect_topk: int = 100
        self.detect_score_threshold: float = 0.05
        self.detect_iou_threshold: float = 0.5
        # suppression-rule knobs (ops/boxes.py): "off" keeps the
        # reference hard NMS bit-identical; "gaussian"/"linear" switch
        # to Soft-NMS score decay.  max_per_class > 0 caps how many
        # boxes each class keeps in the fixed-K output (0 = uncapped).
        self.detect_soft_nms: str = "off"
        self.detect_soft_sigma: float = 0.5
        self.detect_max_per_class: int = 0

    def compile_bucket(self, batch: int):
        raise NotImplementedError

    def release_device_weights(self) -> None:
        """Move this model's variables to host numpy, freeing their
        device (HBM) copy.  The control plane calls this once a retired
        version has drained, so versions retained for observability (or
        versioned ``registry.get``) cost host RAM, never HBM.  A later
        call still works — jax re-transfers host arrays on use — it is
        just no longer resident.  For mesh views ``device_get`` GATHERS
        every sharded leaf into its full logical host value first, so
        the spill is a complete checkpoint-equivalent copy whatever the
        device layout was."""
        variables = getattr(self, "_variables", None)
        if variables is None:
            return
        import jax

        self._variables = jax.tree_util.tree_map(
            np.asarray, jax.device_get(variables))

    def param_bytes(self) -> int:
        """PER-CHIP addressable bytes of the variable tree (the weight
        cache's HBM accounting unit for this model) — for int8 models
        this is the true quantized footprint (~0.26× f32: int8 kernels
        + f32 scales/biases), and for a model-sharded mesh view each
        leaf is priced at its ``shard_shape``, not the global logical
        size: a leaf split 4-way over ``model`` costs a chip a quarter
        of its bytes, and eviction budgets/spill decisions must see
        that.  Unsharded/replicated leaves price at full size, so
        single-device behavior is unchanged."""
        variables = getattr(self, "_variables", None)
        if variables is None:
            return 0
        import jax

        shardings = self._leaf_shardings()
        leaves = jax.tree_util.tree_leaves(variables)
        total = 0
        for i, a in enumerate(leaves):
            s = None
            if isinstance(a, jax.Array):
                s = a.sharding
            elif shardings is not None:
                # spilled host copy: the view's sharding tree still
                # describes how it lives on devices when re-admitted
                s = shardings[i]
            if s is not None:
                shard = s.shard_shape(tuple(a.shape))
                total += int(np.prod(shard)) * int(a.dtype.itemsize)
            else:
                # .nbytes is metadata on jax and numpy arrays — no D2H
                total += int(a.nbytes)
        return total

    def param_global_bytes(self) -> int:
        """Logical full-tree bytes (what replication would cost one
        chip) — the denominator for the sharding saving surfaced in
        /v1/stats next to the per-chip ``param_bytes()``."""
        variables = getattr(self, "_variables", None)
        if variables is None:
            return 0
        import jax

        return int(sum(int(np.prod(a.shape)) * int(a.dtype.itemsize)
                       for a in jax.tree_util.tree_leaves(variables)))

    def _leaf_shardings(self):
        """``_var_sharding`` flattened to a per-leaf list (None when no
        sharding view applies): single-Sharding views broadcast, mesh
        views carry a pytree congruent with ``_variables``."""
        import jax

        vs = getattr(self, "_var_sharding", None)
        if vs is None:
            return None
        if isinstance(vs, jax.sharding.Sharding):
            n = len(jax.tree_util.tree_leaves(
                getattr(self, "_variables", None)))
            return [vs] * n
        return jax.tree_util.tree_leaves(
            vs, is_leaf=lambda x: isinstance(x, jax.sharding.Sharding))

    def mesh_shape(self) -> dict | None:
        """``{"data": D, "model": M}`` for mesh views, None otherwise —
        advertised through engine stats → /v1/healthz → the gateway's
        fleet table."""
        mesh = getattr(self, "_mesh", None)
        if mesh is None:
            return None
        return {str(k): int(v) for k, v in mesh.shape.items()}

    def placement_desc(self) -> str | None:
        """Human-readable placement for stats/health (None = default)."""
        import jax

        if self.placement is None:
            return None
        devs = sorted(d.id for d in self.placement.device_set)
        if len(devs) == 1:
            return str(next(iter(self.placement.device_set)))
        return (f"sharded over {len(devs)} devices "
                f"{devs} ({jax.devices()[0].platform})")

    def describe(self) -> dict:
        d = {}
        if self.workload.verb == "detect":
            d["detect"] = {"decode": self.detect_decode,
                           "top_k": self.detect_topk,
                           "score_threshold": self.detect_score_threshold,
                           "iou_threshold": self.detect_iou_threshold,
                           "soft_nms": self.detect_soft_nms,
                           "soft_sigma": self.detect_soft_sigma,
                           "max_per_class": self.detect_max_per_class}
        return {"name": self.name, "task": self.task,
                "workload": self.workload.verb, **d,
                "input_shape": list(self.input_shape),
                "num_classes": self.num_classes,
                "fixed_batch": self.fixed_batch,
                "donates_inputs": self.donates_inputs,
                "wire_dtype": str(self.wire_dtype),
                "infer_dtype": self.infer_dtype,
                "output_wire": self.output_wire,
                "placement": self.placement_desc(),
                "mesh": self.mesh_shape(),
                "restored_step": self.restored_step,
                "restore_fallback": self.restore_fallback,
                "restored_mtime": self.restored_mtime,
                "params_digest": self.params_digest,
                "version": self.serve_version}


class CheckpointServingModel(ServingModel):
    """Workdir-checkpoint-backed: AOT-compile apply() per batch bucket."""

    donates_inputs = True

    def __init__(self, name: str, cfg, model, state,
                 wire_dtype: str = "float32",
                 infer_dtype: str = "float32",
                 calib_batches: int = 2,
                 calib_dir: str | None = None,
                 ingest: str = "pallas"):
        from deep_vision_tpu.serve.workloads import workload_for_task

        # the workload adapter owns the input codec: latent-in
        # generative models serve a (latent_dim,) float vector, not an
        # image, and override an operator-requested uint8 wire (a uint8
        # latent is meaningless); image-in workloads keep the config's
        # (H, W, C) and the requested wire
        wl = workload_for_task(cfg.task)
        super().__init__(
            name, task=cfg.task,
            input_shape=wl.serving_input_shape(cfg, model),
            num_classes=cfg.num_classes, config_name=cfg.name,
            wire_dtype=wl.wire_dtype_for(cfg, str(wire_dtype)),
            infer_dtype=infer_dtype)
        self.output_wire = wl.output_wire(cfg)
        self.cfg = cfg
        # which device-side normalization a uint8 wire needs — derived
        # from the config so it matches the host path the model trained
        # against (a float32 wire skips it: the client normalized)
        from deep_vision_tpu.ops.preprocess import serve_preprocess_kind

        self.preprocess_kind = serve_preprocess_kind(cfg.task, cfg.channels)
        # int8 calibration provenance (None / unused outside int8);
        # kept public so a hot reload rebuilds the same quantization
        # (serve/models.py _load_model) and describe() can price it
        self.quant = None
        self.calib_batches = int(calib_batches)
        self.calib_dir = calib_dir
        if str(ingest) not in ("pallas", "xla"):
            raise ValueError(f"ingest '{ingest}' unsupported "
                             f"(have ('pallas', 'xla'))")
        self.ingest = str(ingest)
        if self.infer_dtype == "bfloat16":
            import jax
            import jax.numpy as jnp

            # every zoo model threads its ``dtype`` attr through the
            # compute graph (x.astype(self.dtype) before the first conv)
            # — clone with bf16 so activations run in bf16, and cast the
            # float variable leaves ONCE here at load (half the param
            # HBM and per-device replica copies too)
            if hasattr(model, "dtype"):
                model = model.clone(dtype=jnp.bfloat16)
            state = state.replace(params=jax.tree_util.tree_map(
                lambda a: a.astype(jnp.bfloat16)
                if jnp.issubdtype(a.dtype, jnp.floating) else a,
                state.params))
        self._model = model
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        if self.infer_dtype == "int8":
            # post-training quantization AT LOAD (serve/quant.py):
            # calibrate activation ranges on a held-out (or synthetic)
            # batch, then swap the variable tree for the int8-resident
            # one — bucket programs dequantize inside the trace, the
            # WeightCache rounds the int8 leaves through spill/re-admit
            # untouched, and param_bytes() prices the real footprint
            from deep_vision_tpu.serve.quant import quantize_for_serving

            variables, self.quant = quantize_for_serving(
                model, variables, kind=self.preprocess_kind,
                input_shape=self.input_shape,
                calib_batches=self.calib_batches,
                calib_dir=self.calib_dir)
        self._variables = variables
        # variable sharding paired with ``placement`` (replicated on a
        # mesh, pinned on a single device); None = wherever restore left
        # them
        self._var_sharding = None
        # HBM residency manager (serve/models.py WeightCache) — when
        # registered, bucket programs resolve their variables through
        # the cache at CALL time (late binding), so an evicted model's
        # weights can spill to host RAM and be device_put back on demand
        # without recompiling any retained AOT executable
        self._cache = None

    def describe(self) -> dict:
        d = super().describe()
        if self.quant is not None:
            d["quant"] = dict(self.quant.describe(),
                              param_bytes=self.param_bytes(),
                              ingest=getattr(self, "ingest_path",
                                             self.ingest))
        return d

    def _live_variables(self):
        """The variables a bucket program should run with RIGHT NOW:
        the cache's resident copy when this model is under residency
        management (which may trigger an evict→re-admit cycle), else
        the load-time device arrays.  Called once per dispatched batch
        — never per request."""
        cache = self._cache
        if cache is not None:
            managed = cache.variables_for(self)
            if managed is not None:
                return managed
        return self._variables

    def for_device(self, device) -> "CheckpointServingModel":
        """Per-device replica view: SAME host restore, its OWN device
        copy of the variables (one ``device_put`` per device, here, at
        replica-set build — never per batch) and bucket programs pinned
        to ``device`` (serve/replicas.py builds one view per local
        device)."""
        import copy

        import jax
        from jax.sharding import SingleDeviceSharding

        view = copy.copy(self)
        sharding = SingleDeviceSharding(device)
        view.placement = sharding
        view._var_sharding = sharding
        view._variables = jax.device_put(self._variables, sharding)
        return view

    def for_mesh(self, mesh, partition_rules=None, strict: bool = False,
                 min_shard_dim: int = 1024) -> "CheckpointServingModel":
        """Mesh-sharded view: bucket programs compiled with the batch
        dim split across the ``data`` axis, and — on a 2-D
        ``data × model`` mesh — variables laid out by the partition
        rules (parallel/partition.py) so each chip holds only its
        addressable shard of the wide leaves; GSPMD inserts the ICI
        collectives the layout implies.  On a 1-D data mesh (legacy
        ``--shard-batches``) variables replicate, exactly as before.

        ``partition_rules`` is an ordered ``(regex, PartitionSpec)``
        table (``match_partition_rules``); None = the first-divisible-
        axis fallback sharder.  ``strict`` demands every leaf match
        exactly one rule.  Buckets must be divisible by the data-axis
        size (compile_bucket enforces it, naming both axes)."""
        import copy

        from deep_vision_tpu.parallel.mesh import (
            MODEL_AXIS,
            batch_sharding,
            replicate,
            replicated_sharding,
        )

        view = copy.copy(self)
        view.placement = batch_sharding(mesh, ndim=1 + len(self.input_shape))
        n_model = mesh.shape.get(MODEL_AXIS, 1)
        if n_model > 1 or partition_rules is not None:
            from deep_vision_tpu.parallel.partition import (
                param_shardings,
                shard_variables,
            )

            # pytree of NamedShardings, congruent with _variables —
            # compile_bucket's v_spec and the WeightCache's re-admit
            # device_put both consume it leaf-for-leaf
            shardings = param_shardings(
                self._variables, mesh, min_shard_dim,
                rules=partition_rules, strict=strict)
            view._var_sharding = shardings
            view._variables = shard_variables(self._variables, shardings)
        else:
            view._var_sharding = replicated_sharding(mesh)
            view._variables = replicate(self._variables, mesh)
        view._mesh = mesh
        return view

    def compile_bucket(self, batch: int):
        import jax
        import jax.numpy as jnp

        if getattr(self, "_mesh", None) is not None:
            from deep_vision_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS

            n_data = self._mesh.shape.get(DATA_AXIS, 1)
            n_model = self._mesh.shape.get(MODEL_AXIS, 1)
            if batch % n_data != 0:
                # only the batch dim splits over ``data``; ``model``
                # constrains nothing here but belongs in the message —
                # the operator picked one mesh, the error should name it
                nearest = max(n_data,
                              ((batch + n_data - 1) // n_data) * n_data)
                raise ValueError(
                    f"sharded serving of '{self.name}': bucket {batch} "
                    f"not divisible by the data axis of the "
                    f"{n_data}×{n_model} data×model mesh — "
                    f"nearest usable bucket is {nearest}; use buckets "
                    f"that are multiples of {n_data} "
                    f"(engine.sharded_buckets)")

        from deep_vision_tpu.ops.preprocess import (
            make_int8_ingest,
            make_serve_preprocess,
        )

        wire = jnp.dtype(str(self.wire_dtype))
        compute = jnp.bfloat16 if self.infer_dtype == "bfloat16" \
            else jnp.float32

        def _f32_outputs(out):  # dvtlint: traced
            return jax.tree_util.tree_map(
                lambda a: a.astype(jnp.float32)
                if jnp.issubdtype(a.dtype, jnp.floating) else a, out)

        # workload epilogue (serve/workloads.py), fused into the same
        # AOT program as the model body — the output-side mirror of the
        # normalize prologue: pose decodes heatmaps→keypoints on device
        # (D2H moves K coordinate pairs, not H×W×K heatmaps), generate
        # encodes [-1,1] floats→uint8 (D2H moves 1 byte/pixel), detect
        # decodes + NMSes down to K fixed-size boxes per image (D2H
        # moves ~K·28 B instead of the dense multi-scale pyramid)
        post = self.workload.make_epilogue(self)

        def _finish(out):  # dvtlint: traced
            out = _f32_outputs(out)
            return post(out) if post is not None else out

        if self.infer_dtype == "int8":
            # the fused Pallas ingest is the default on the uint8 wire;
            # on real TPUs it must pass the per-shape parity gate first
            # (Mosaic lowering is shape-sensitive), falling back to the
            # XLA prologue — NEVER recompiling any other model's
            # retained f32/bf16 bucket programs
            act_scale = float(self.quant.act_scale)
            # the fused kernel's constant table has no "gan" family —
            # GAN-kind ingest always takes the XLA prologue
            use_pallas = self.ingest == "pallas" and \
                jnp.issubdtype(wire, jnp.integer) and \
                self.preprocess_kind != "gan"
            if use_pallas and jax.default_backend() == "tpu":
                from deep_vision_tpu.ops.pallas_ops import ingest_parity_ok

                use_pallas = ingest_parity_ok(
                    (batch, *self.input_shape), self.preprocess_kind,
                    act_scale)
            self.ingest_path = "pallas" if use_pallas else "xla"
            pre_q = make_int8_ingest(self.preprocess_kind, wire,
                                     act_scale, use_pallas=use_pallas)
            from deep_vision_tpu.serve.quant import dequantize_params

            def apply(variables, x):  # dvtlint: traced
                # int8 activations dequantize into the first conv's
                # read; int8-resident weights dequantize in-trace (XLA
                # fuses both casts — no f32 weight copy persists in HBM)
                xq = pre_q(x)
                xf = xq.astype(jnp.float32) * act_scale
                v = dict(variables)
                scales = v.pop("param_scales")
                v["params"] = dequantize_params(v["params"], scales)
                out = self._model.apply(v, xf, train=False)
                return _finish(out)
        else:
            # traced prologue: a uint8 wire batch is cast + scaled +
            # normalized ON DEVICE (XLA fuses it into the first conv's
            # HBM read — the H2D carried 4× fewer bytes); a float32 wire
            # passes through (the client normalized).  Outputs always
            # leave the program as float32, whatever the compute dtype.
            pre = make_serve_preprocess(self.preprocess_kind, wire,
                                        compute)

            def apply(variables, x):
                out = self._model.apply(variables, pre(x), train=False)
                return _finish(out)

        x_spec = jax.ShapeDtypeStruct((batch, *self.input_shape),
                                      wire, sharding=self.placement)
        var_sharding = self._var_sharding
        if var_sharding is not None and \
                not isinstance(var_sharding, jax.sharding.Sharding):
            # mesh view: per-leaf sharding pytree (partition rules) —
            # each leaf's spec carries ITS layout into the AOT compile
            v_spec = jax.tree_util.tree_map(
                lambda a, s: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                                  sharding=s),
                self._variables, var_sharding)
        else:
            v_spec = jax.tree_util.tree_map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=var_sharding),
                self._variables)
        # AOT lower+compile: the engine's bucket dict is the jit cache,
        # so a served shape can never hit a surprise trace mid-request.
        # The image buffer is donated — each padded batch's device
        # allocation is recycled into the outputs (a no-op where the
        # backend declines; jax falls back to copying)
        with warnings.catch_warnings():
            # lowering warns when the donated image buffer can't alias
            # any output (e.g. classification logits are smaller than
            # the batch) — donation is best-effort by contract
            warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            compiled = jax.jit(apply, donate_argnums=(1,)).lower(
                v_spec, x_spec).compile()
        model = self  # late-bind variables: the weight cache may have
        # spilled + re-admitted them since this program compiled, and
        # the AOT executable must not pin the evicted device buffers

        placement = self.placement
        wire_np = self.wire_dtype

        def call(x):
            variables = model._live_variables()
            # keep donation meaningful for direct numpy callers too:
            # transfer first, hand the committed device buffer over —
            # honoring the view's placement (replica device / mesh)
            if not isinstance(x, jax.Array):
                x = jax.device_put(np.asarray(x, wire_np), placement)
            with warnings.catch_warnings():
                warnings.filterwarnings(
                    "ignore",
                    message="Some donated buffers were not usable")
                return compiled(variables, x)

        # analytic FLOPs ride on the callable for the engine's
        # serving-MFU meter: XLA's own cost analysis on the AOT
        # executable, or the documented 2·params·batch lower bound when
        # the backend doesn't report flops (obs/mfu.py)
        from deep_vision_tpu.obs.mfu import (
            compiled_flops,
            params_flops_lower_bound,
        )

        mesh = getattr(self, "_mesh", None)
        n_mesh = int(np.prod(list(mesh.shape.values()))) if mesh else 1
        flops = compiled_flops(compiled)
        if flops is not None:
            # sharded executables cost-analyze ONE partition — already
            # the per-chip numerator the meter's per-chip peak expects
            call.cost_flops = flops
            call.flops_source = ("xla_cost_analysis_per_shard"
                                 if n_mesh > 1 else "xla_cost_analysis")
        else:
            call.cost_flops = params_flops_lower_bound(
                self._variables, batch, devices=n_mesh)
            call.flops_source = ("params_lower_bound_per_shard"
                                 if n_mesh > 1 else "params_lower_bound")
        return call


class ExportedServingModel(ServingModel):
    """StableHLO-blob-backed (core/export): fixed batch, no Python model.

    Blobs serve exactly their exported signature — traced at float32
    with host-side normalization — so the wire/infer dtype knobs don't
    apply here (``wire_dtype``/``infer_dtype`` stay "float32";
    ``cli.serve`` forces the same when ``--stablehlo`` is given).
    """

    def __init__(self, name: str, cfg, call, variables, fixed_batch: int):
        super().__init__(
            name, task=cfg.task,
            input_shape=(cfg.image_size, cfg.image_size, cfg.channels),
            num_classes=cfg.num_classes, config_name=cfg.name,
            fixed_batch=fixed_batch)
        self.cfg = cfg
        self._call = call
        self._variables = variables
        #: every batch size the blob was exported with (today one trace
        #: per blob; kept a list so multi-bucket exports slot in) — the
        #: error surface for unavailable buckets, instead of the XLA
        #: shape-mismatch noise the raw call would raise
        self.bucket_sizes = [int(fixed_batch)]

    def _unavailable(self, batch: int) -> ValueError:
        return ValueError(
            f"StableHLO blob for '{self.name}' was exported with bucket "
            f"sizes {self.bucket_sizes}; batch {batch} unavailable — "
            f"re-export with --batch {batch} or serve from the checkpoint")

    def compile_bucket(self, batch: int):
        if batch not in self.bucket_sizes:
            raise self._unavailable(batch)
        call, variables = self._call, self._variables

        def run(x):
            # check HERE, not inside XLA: the deserialized call's shape
            # error names avals, not what the operator can act on
            if x.shape[0] not in self.bucket_sizes:
                raise self._unavailable(x.shape[0])
            return call(variables, x)

        # a deserialized blob exposes no compiled executable to cost-
        # analyze, so the MFU numerator uses the documented fallback
        from deep_vision_tpu.obs.mfu import params_flops_lower_bound

        run.cost_flops = params_flops_lower_bound(variables, batch)
        run.flops_source = "params_lower_bound"
        return run


class ModelRegistry:
    def __init__(self):
        self._models: dict[str, ServingModel] = {}
        # name → version → ServingModel: the control plane
        # (serve/models.py) publishes each promoted version here so
        # ``get(name, version=N)`` can answer for any retained version;
        # plain single-version serving never populates it
        self._versions: dict[str, dict[int, ServingModel]] = {}

    def add(self, model: ServingModel,
            version: int | None = None) -> ServingModel:
        self._models[model.name] = model
        if version is None:
            version = model.serve_version
        if version is not None:
            self._versions.setdefault(model.name, {})[int(version)] = model
        return model

    def remove_version(self, name: str, version: int) -> None:
        """Forget one retained version (the control plane prunes
        retired versions past its retain window here, so the registry's
        refs don't pin pruned weights forever).  The default unversioned
        ``_models`` entry is untouched."""
        table = self._versions.get(name)
        if table is not None:
            table.pop(int(version), None)
            if not table:
                self._versions.pop(name, None)

    def load_checkpoint(self, config_name: str, workdir: str,
                        name: str | None = None,
                        wire_dtype: str = "float32",
                        infer_dtype: str = "float32",
                        calib_batches: int = 2,
                        calib_dir: str | None = None,
                        ingest: str = "pallas",
                        cascade_topk: int = 0,
                        detect_decode: str = "device",
                        detect_topk: int = 100,
                        detect_score_threshold: float = 0.05,
                        detect_iou_threshold: float = 0.5,
                        detect_soft_nms: str = "off",
                        detect_soft_sigma: float = 0.5,
                        detect_max_per_class: int = 0
                        ) -> ServingModel:
        """``wire_dtype``: what clients ship and the engine H2D-transfers
        — "uint8" (raw 0–255 pixels, normalization fused into the bucket
        programs; the ``cli.serve`` default) or "float32" (the original
        host-normalized contract; the programmatic default, so existing
        direct callers are untouched).  ``infer_dtype``: "bfloat16" casts
        params once here and runs bucket programs in bf16 compute with
        float32 outputs; "int8" post-training-quantizes here
        (serve/quant.py) — ``calib_batches`` held-out batches from
        ``calib_dir`` (deterministic synthetic data when None) calibrate
        the activation scales, and ``ingest`` picks the fused Pallas
        serve-prologue ("pallas", the default) or the XLA fallback.
        ``cascade_topk`` > 0 marks a cascade FRONT tier: the classify
        workload fuses its confidence epilogue (softmax + top-K on
        device) into the bucket programs (serve/cascade.py).

        ``detect_*`` configure detection models' fused decode
        (serve/workloads.py DetectWorkload): ``detect_decode="device"``
        (default) traces decode → score floor → top-``detect_topk`` →
        class-wise NMS into the bucket programs so the bulk D2H ships
        K fixed-size boxes per image; "host" keeps the dense pyramid
        rows and decodes per request in respond() — the A/B baseline.
        ``detect_soft_nms`` ("gaussian"/"linear") switches the fused
        NMS to Soft-NMS score decay with ``detect_soft_sigma``, and
        ``detect_max_per_class`` > 0 caps each class's share of the
        fixed-K output.  Non-detect models ignore them."""
        from deep_vision_tpu.core.config import get_config
        from deep_vision_tpu.core.restore import load_state

        cfg = get_config(config_name)
        info: dict = {}
        model, state = load_state(cfg, workdir, tag="serve", info=info)
        sm = CheckpointServingModel(name or config_name, cfg, model, state,
                                    wire_dtype=wire_dtype,
                                    infer_dtype=infer_dtype,
                                    calib_batches=calib_batches,
                                    calib_dir=calib_dir,
                                    ingest=ingest)
        sm.cascade_topk = int(cascade_topk)
        if str(detect_decode) not in ("device", "host"):
            raise ValueError(f"detect_decode '{detect_decode}' "
                             f"unsupported (have ('device', 'host'))")
        sm.detect_decode = str(detect_decode)
        sm.detect_topk = int(detect_topk)
        sm.detect_score_threshold = float(detect_score_threshold)
        sm.detect_iou_threshold = float(detect_iou_threshold)
        if str(detect_soft_nms) not in ("off", "gaussian", "linear"):
            raise ValueError(f"detect_soft_nms '{detect_soft_nms}' "
                             f"unsupported (have ('off', 'gaussian', "
                             f"'linear'))")
        sm.detect_soft_nms = str(detect_soft_nms)
        sm.detect_soft_sigma = float(detect_soft_sigma)
        sm.detect_max_per_class = int(detect_max_per_class)
        sm.restored_step = info.get("step")
        sm.restore_fallback = bool(info.get("fallback"))
        sm.restored_mtime = info.get("mtime")
        sm.params_digest = info.get("digest")
        return self.add(sm)

    def load_exported(self, config_name: str, blob_path: str, workdir: str,
                      name: str | None = None,
                      wire_dtype: str = "float32",
                      infer_dtype: str = "float32") -> ServingModel:
        """Serve a ``cli.infer export`` artifact.

        The blob's inputs are (variables, x) — the same variables pytree
        the exporting process restored — so the companion workdir supplies
        them through the identical restore path.

        Exported blobs are f32-wire/f32-compute only: the StableHLO was
        traced at one float32 signature with host-side normalization, so
        neither wire decoding nor a compute-dtype rewrite (bfloat16 OR
        int8 quantization) can apply — those need the re-jitting
        checkpoint path.  Checked FIRST, before any file I/O, so the
        operator gets the dtype error rather than a restore traceback.
        """
        if str(wire_dtype) != "float32" or str(infer_dtype) != "float32":
            raise ValueError(
                "exported StableHLO blobs are f32-wire/f32-compute "
                "only: the blob serves exactly its traced float32 "
                f"signature, so wire_dtype='{wire_dtype}' / "
                f"infer_dtype='{infer_dtype}' (bfloat16 and int8 "
                "included) need the checkpoint path — serve without "
                "--stablehlo")
        from deep_vision_tpu.core.config import get_config
        from deep_vision_tpu.core.export import load_exported
        from deep_vision_tpu.core.restore import load_state

        cfg = get_config(config_name)
        info: dict = {}
        _, state = load_state(cfg, workdir, tag="serve", info=info)
        variables = {"params": state.params}
        if state.batch_stats:
            variables["batch_stats"] = state.batch_stats
        call = load_exported(blob_path)
        # the image input is the final positional arg, hence the last
        # flattened aval (variables dict leaves sort first)
        fixed_batch = int(call.in_avals[-1].shape[0])
        sm = ExportedServingModel(
            name or config_name, cfg, call, variables, fixed_batch)
        sm.restored_step = info.get("step")
        sm.restore_fallback = bool(info.get("fallback"))
        sm.restored_mtime = info.get("mtime")
        sm.params_digest = info.get("digest")
        return self.add(sm)

    def get(self, name: str | None = None,
            version: int | None = None) -> ServingModel:
        if name is None:
            if len(self._models) != 1:
                raise KeyError(
                    f"model name required (serving {sorted(self._models)})")
            if version is not None:
                return self.get(next(iter(self._models)), version)
            return next(iter(self._models.values()))
        if name not in self._models:
            raise KeyError(f"unknown model '{name}'; "
                           f"serving {sorted(self._models)}")
        if version is not None:
            table = self._versions.get(name, {})
            if int(version) not in table:
                raise KeyError(
                    f"model '{name}' has no version {version}; "
                    f"versions {sorted(table)}")
            return table[int(version)]
        return self._models[name]

    def names(self) -> list[str]:
        return sorted(self._models)

    def __contains__(self, name: str) -> bool:
        return name in self._models

    def __len__(self) -> int:
        return len(self._models)
