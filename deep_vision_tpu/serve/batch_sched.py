"""Trough-filling batch scheduler: bulk jobs strictly below every
interactive tenant.

One daemon thread drains ``JobStore`` shards through the existing
serving engines, one shard at a time, and only submits a shard when the
target engine's interactive pressure is LOW on both signals the
admission controller already maintains:

- ``engine.queue_depth <= max_interactive_depth`` (default 0 — any
  queued interactive request parks the batch tier outright), and
- ``queue_depth × bucket exec EWMA`` under ``pressure_high_ms`` — the
  same queue-depth × service-time product deploy/autoscale.py calls
  pressure, so "trough" means the same thing to the scheduler and the
  autoscaler.

That check plus the one-shard-in-flight discipline is the whole
priority-band mechanism: a shard is at most ``max_batch`` images (one
engine cohort), so the worst case an interactive request ever sees is
ONE batch-sized cohort ahead of it — the same worst case a burst of
interactive traffic already produces.  There is no preemption to build
and no priority queue to maintain; the band lives in *when* batch work
is submitted, not in how the engine treats it afterwards.

Starvation-freedom the other way is inherent: interactive troughs occur
between arrivals (the check samples queue depth, which an idle engine
holds at 0), so any workload short of 100% sustained interactive
saturation lets batch shards through; each completed shard is durably
checkpointed (serve/jobs.py), so progress is monotone across restarts.

Shed results (engine shutdown, queue races) retry the WHOLE shard
later — results are recorded shard-atomically or not at all, which is
what keeps the JSONL replay exactly-once.  Quarantined/decode-failed
items record as per-item ``error`` results: a poison item must not
wedge its job forever.

Lock order: ``BatchScheduler._lock`` guards only local counters and the
busy-interval window — it is a leaf, never held across ``submit`` or
any store/engine call.
"""

from __future__ import annotations

import threading
import time
from collections import deque

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.serve.admission import Shed
from deep_vision_tpu.serve.faults import Quarantined
from deep_vision_tpu.serve.jobs import Job, JobStore

_log = get_logger("dvt.serve.batch")


class BatchScheduler:
    """Drains job shards through serving engines during interactive
    troughs.

    ``resolve(model_name) -> (model, engine)`` is the routing closure
    the CLI wires up (registry + engines dict on the single-model path,
    the model control plane on ``--serve-models``); it raises KeyError
    for unknown/undeployed models, which fails the job terminally."""

    def __init__(self, store: JobStore, resolve, *,
                 interval_s: float = 0.02,
                 max_interactive_depth: int = 0,
                 pressure_high_ms: float = 10.0,
                 shard_timeout_s: float = 300.0,
                 occupancy_window_s: float = 10.0):
        self.store = store
        self._resolve = resolve
        self.interval_s = max(0.001, float(interval_s))
        self.max_interactive_depth = max(0, int(max_interactive_depth))
        self.pressure_high_ms = float(pressure_high_ms)
        self.shard_timeout_s = float(shard_timeout_s)
        self.occupancy_window_s = float(occupancy_window_s)
        self._lock = new_lock("serve.batch_sched.BatchScheduler._lock")
        # optional BrownoutController (serve/brownout.py): at L1+ the
        # batch tier is optional work — cohort admission freezes
        # entirely, jobs just drain more slowly; read racily
        self.brownout = None
        # rolling (t_end, busy_s) intervals of batch shard executions —
        # the dvt_batch_occupancy numerator
        self._busy: deque = deque()  # guarded-by: _lock
        self.images_total = 0  # guarded-by: _lock
        self.shards_done = 0  # guarded-by: _lock
        self.shards_shed = 0  # whole-shard retries, guarded-by: _lock
        self.deferred = 0  # trough checks that said "not now", guarded-by: _lock
        self.frozen_deferred = 0  # brownout L1+ freezes, guarded-by: _lock
        self.decode_errors = 0  # guarded-by: _lock
        self.item_errors = 0  # quarantined/timeout items, guarded-by: _lock
        self.jobs_failed = 0  # guarded-by: _lock
        self._kick = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> "BatchScheduler":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="batch-sched", daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._kick.set()
        t = self._thread
        if t is not None:
            t.join(timeout)

    def kick(self) -> None:
        """Wake the loop now (called by the HTTP handler on job
        submit, instead of waiting out the idle sleep)."""
        self._kick.set()

    # -- the band -----------------------------------------------------------

    def _trough(self, engine) -> bool:
        """True when interactive pressure is low enough to slip one
        batch shard in.  Both terms come from live interactive state:
        queue depth is requests *waiting* (batch's own in-flight shard
        does not count — it already left the queue), and the EWMA is
        the admission controller's per-bucket execution estimate."""
        depth = engine.queue_depth
        if depth > self.max_interactive_depth:
            return False
        ewma = engine.admission.bucket_ewma_s() or 0.0
        return depth * ewma * 1e3 <= self.pressure_high_ms

    # -- the loop -----------------------------------------------------------

    def _loop(self) -> None:
        while not self._stop.is_set():
            nxt = self.store.next_shard()
            if nxt is None:
                self._kick.wait(self.interval_s * 10)
                self._kick.clear()
                continue
            job, index = nxt
            try:
                model, engine = self._resolve(job.model)
            except KeyError as e:
                with self._lock:
                    self.jobs_failed += 1
                detail = e.args[0] if e.args else job.model
                self.store.fail(job.job_id,
                                f"model not servable: {detail}")
                continue
            bo = self.brownout
            if bo is not None and bo.at_least(1):
                # brownout L1+: admission frozen regardless of the
                # trough check — under overload the next cohort is
                # pure optional load on a saturated engine
                with self._lock:
                    self.deferred += 1
                    self.frozen_deferred += 1
                self._kick.wait(self.interval_s)
                self._kick.clear()
                continue
            if not self._trough(engine):
                with self._lock:
                    self.deferred += 1
                self._kick.wait(self.interval_s)
                self._kick.clear()
                continue
            self._run_shard(job, index, model, engine)

    def _run_shard(self, job: Job, index: int, model, engine) -> None:
        lo, hi = job.shard_range(index)
        items = job.manifest[lo:hi]  # manifest is immutable post-submit
        wl = model.workload
        inputs: list = []
        for item in items:
            try:
                inputs.append(wl.decode_manifest_item(item, model))
            except ValueError as e:
                inputs.append(e)  # permanent per-item error
        t0 = time.monotonic()
        # submit the whole shard as one cohort: no per-request deadline
        # (bulk work outlives any interactive SLO; the shard timeout
        # below bounds it instead)
        futures = [None if isinstance(x, ValueError)
                   else engine.submit(x) for x in inputs]
        deadline = t0 + self.shard_timeout_s
        rows: list = []
        for fut, x in zip(futures, inputs):
            if fut is None:
                rows.append(x)
                continue
            try:
                rows.append(fut.result(
                    timeout=max(0.1, deadline - time.monotonic())))
            except Exception as e:  # noqa: BLE001 — timeout/executor
                # faults map to a retriable shed: the engine may still
                # deliver later, but this shard attempt is over
                rows.append(Shed("timeout", detail=str(e)))
        if any(isinstance(r, Shed) for r in rows):
            # whole-shard retry: nothing recorded, nothing emitted —
            # shard results are all-or-nothing so replay stays
            # exactly-once
            with self._lock:
                self.shards_shed += 1
            event(_log, "shard_shed", job=job.job_id, shard=index,
                  sheds=sum(isinstance(r, Shed) for r in rows))
            self._kick.wait(self.interval_s)
            self._kick.clear()
            return
        t_end = time.monotonic()
        results: list = []
        served = 0
        decode_errs = item_errs = 0
        for item, row in zip(items, rows):
            if isinstance(row, ValueError):
                decode_errs += 1
                results.append({"error": f"bad manifest entry: {row}"})
            elif isinstance(row, Quarantined):
                item_errs += 1
                results.append({"error":
                                f"quarantined ({row.reason}): "
                                f"{row.detail}"})
            else:
                served += 1
                results.append(wl.respond(model, item, row))
        recorded = self.store.record_shard(job.job_id, index, results,
                                           served)
        with self._lock:
            self.decode_errors += decode_errs
            self.item_errors += item_errs
            if recorded:
                self.shards_done += 1
                self.images_total += served
                self._busy.append((t_end, t_end - t0))
                self._prune_busy_locked(t_end)

    # -- observability ------------------------------------------------------

    def _prune_busy_locked(self, now: float) -> None:
        horizon = now - self.occupancy_window_s
        while self._busy and self._busy[0][0] < horizon:
            self._busy.popleft()

    def occupancy(self) -> float:
        """Fraction of the trailing window the batch tier kept an
        engine busy — the trough-filling duty cycle (0 when idle or
        parked behind interactive load, →1 when saturating)."""
        now = time.monotonic()
        with self._lock:
            self._prune_busy_locked(now)
            busy = sum(dt for _, dt in self._busy)
        return min(1.0, busy / self.occupancy_window_s)

    def stats(self) -> dict:
        occ = self.occupancy()
        with self._lock:
            return {"running": self._thread is not None
                    and self._thread.is_alive(),
                    "images_total": self.images_total,
                    "shards_done": self.shards_done,
                    "shards_shed": self.shards_shed,
                    "deferred": self.deferred,
                    "frozen_deferred": self.frozen_deferred,
                    "decode_errors": self.decode_errors,
                    "item_errors": self.item_errors,
                    "jobs_failed": self.jobs_failed,
                    "occupancy": round(occ, 4),
                    "max_interactive_depth": self.max_interactive_depth,
                    "pressure_high_ms": self.pressure_high_ms}
