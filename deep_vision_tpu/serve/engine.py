"""Background-thread dynamic micro-batcher.

The training stack amortizes XLA dispatch over ``lax.scan`` steps; the
serving stack amortizes it over dynamically-formed batches.  Requests
enqueue with an optional deadline; the batcher thread drains the queue up
to ``max_batch`` or ``max_wait_ms`` (whichever comes first), pads the
batch to a small set of power-of-two buckets so every served shape hits
an already-compiled program (the bucket dict IS the jit cache — a miss is
an explicit, counted compile, never a surprise mid-request trace),
executes, and scatters the output rows back to per-request futures.

Deadline handling is two-phase: admission (``admission.py``) sheds
requests that cannot possibly make their deadline at submit time, and the
batcher re-checks at batch-formation time so a request that expired while
queued is dropped rather than executed late.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from deep_vision_tpu.core.metrics import LatencyHistogram, ThroughputMeter
from deep_vision_tpu.serve.admission import AdmissionController, Shed


def power_of_two_buckets(max_batch: int) -> list[int]:
    """1, 2, 4, ... plus ``max_batch`` itself when it isn't a power of 2."""
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return buckets


class _Request:
    __slots__ = ("image", "deadline", "enqueued_at", "future")

    def __init__(self, image, deadline, enqueued_at, future):
        self.image = image
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.future = future


class BatchingEngine:
    """Dynamic batcher for one ServingModel.

    Use as a context manager or call ``start()``/``stop()``.  ``submit``
    returns a ``concurrent.futures.Future`` resolving to either the
    output pytree row for that image or a ``Shed``; ``infer`` is the
    blocking convenience wrapper.
    """

    def __init__(self, model, *, max_batch: int = 32,
                 max_wait_ms: float = 5.0, buckets: list[int] | None = None,
                 admission: AdmissionController | None = None):
        self.model = model
        if model.fixed_batch is not None:
            # a StableHLO blob serves exactly its traced shape
            buckets = [model.fixed_batch]
        self.buckets = sorted(buckets) if buckets else \
            power_of_two_buckets(max_batch)
        self.max_batch = self.buckets[-1]
        self.max_wait_s = max_wait_ms / 1e3
        self.admission = admission or AdmissionController(
            max_wait_ms=max_wait_ms)
        self.latency = LatencyHistogram()
        self.throughput = ThroughputMeter(warmup_steps=1)
        self._queue: queue.Queue[_Request] = queue.Queue()
        self._executables: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self.submitted = 0
        self.served = 0
        self.batches = 0
        self.compiles = 0
        self.padded_images = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BatchingEngine":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"batcher-{self.model.name}",
                daemon=True)
            self._thread.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        # anything still queued will never run — tell its caller
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.future.set_result(Shed("shutdown", "engine stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def warmup(self, buckets: list[int] | None = None):
        """Compile ahead of traffic (persisted via core/compile_cache)."""
        import jax

        for b in (buckets or self.buckets):
            jax.block_until_ready(self._compiled(b)(np.zeros(
                (b, *self.model.input_shape), np.float32)))

    # -- request path ------------------------------------------------------

    def submit(self, image, deadline_ms: float | None = None) -> Future:
        now = time.monotonic()
        deadline = now + deadline_ms / 1e3 if deadline_ms is not None \
            else None
        with self._lock:
            self.submitted += 1
        fut: Future = Future()
        shed = self.admission.admit(self._queue.qsize(), deadline, now)
        if shed is not None:
            fut.set_result(shed)
            return fut
        self._queue.put(_Request(np.asarray(image, np.float32), deadline,
                                 now, fut))
        return fut

    def infer(self, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0):
        return self.submit(image, deadline_ms).result(timeout)

    # -- batcher thread ----------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            drain_until = time.monotonic() + self.max_wait_s
            while len(batch) < self.max_batch:
                remaining = drain_until - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                self._run_batch(batch)
            except Exception as e:  # deliver, don't kill the batcher
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _compiled(self, bucket: int):
        fn = self._executables.get(bucket)
        if fn is None:
            fn = self.model.compile_bucket(bucket)
            self._executables[bucket] = fn
            with self._lock:
                self.compiles += 1
        return fn

    def _run_batch(self, batch: list[_Request]):
        import jax

        live = []
        for req in batch:
            expired = self.admission.expired(req.deadline)
            if expired is not None:
                req.future.set_result(expired)
            else:
                live.append(req)
        if not live:
            return
        n = len(live)
        bucket = self._bucket_for(n)
        padded = np.zeros((bucket, *self.model.input_shape), np.float32)
        for i, req in enumerate(live):
            padded[i] = req.image
        fn = self._compiled(bucket)
        t0 = time.monotonic()
        out = jax.block_until_ready(fn(padded))
        self.admission.observe_exec(time.monotonic() - t0)
        now = time.monotonic()
        with self._lock:
            self.batches += 1
            self.served += n
            self.padded_images += bucket - n
        self.throughput.update(n)
        for i, req in enumerate(live):
            self.latency.record(now - req.enqueued_at)
            req.future.set_result(
                jax.tree_util.tree_map(lambda a: a[i], out))

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            out = {"model": self.model.name,
                   "submitted": self.submitted,
                   "served": self.served,
                   "batches": self.batches,
                   "compiles": self.compiles,
                   "padded_images": self.padded_images,
                   "queue_depth": self._queue.qsize(),
                   "buckets": list(self.buckets),
                   "compiled_buckets": sorted(self._executables),
                   "max_wait_ms": self.max_wait_s * 1e3}
        out["latency"] = self.latency.percentiles()
        out["img_per_sec"] = self.throughput.images_per_sec
        out["admission"] = self.admission.stats()
        return out
