"""Pipelined background-thread dynamic micro-batcher.

The training stack amortizes XLA dispatch over ``lax.scan`` steps and
hides host work behind device compute with double-buffered prefetch
(~2% dispatch idle, docs/PERF.md); the serving stack applies the same
argument to dynamically-formed request batches with a two-stage
pipeline:

  batcher thread   drains the queue up to ``max_batch``/``max_wait_ms``,
                   stages the batch into a REUSED preallocated host
                   buffer for its bucket (no per-batch ``np.zeros``),
                   issues the H2D transfer + compiled program
                   asynchronously (JAX dispatch returns before the
                   device finishes), and hands the in-flight record off;
  drainer thread   waits on completed batches in dispatch order, fetches
                   the WHOLE output pytree with one bulk
                   ``jax.device_get`` per batch (not one device slice
                   per request per leaf), and scatters numpy rows to
                   per-request futures on the host.

A ``pipeline_depth``-bounded semaphore caps dispatched-but-undrained
batches, so batch N+1's formation, staging, and H2D overlap batch N's
device compute while memory stays bounded.  ``pipeline_depth=1`` is the
synchronous mode: the batcher completes each batch inline (same staging
buffers, same single bulk transfer — bit-identical outputs, no overlap).

Bucketing is unchanged from the original engine: batches pad to a small
set of power-of-two buckets so every served shape hits an
already-compiled program (the bucket dict IS the jit cache — a miss is
an explicit, counted compile, never a surprise mid-request trace).
Compiled bucket programs donate their input buffer where the runtime
allows (registry.py), so the padded batch's device allocation is
recycled into the outputs.

Deadline handling is two-phase: admission (``admission.py``) sheds
requests that cannot possibly make their deadline at submit time —
using a per-bucket execution-time EWMA and the current in-flight depth
— and the batcher re-checks at batch-formation time so a request that
expired while queued is dropped rather than executed late.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from deep_vision_tpu.core.metrics import LatencyHistogram, ThroughputMeter
from deep_vision_tpu.serve.admission import AdmissionController, Shed


def power_of_two_buckets(max_batch: int) -> list[int]:
    """1, 2, 4, ... plus ``max_batch`` itself when it isn't a power of 2."""
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return buckets


class _Request:
    __slots__ = ("image", "deadline", "enqueued_at", "future")

    def __init__(self, image, deadline, enqueued_at, future):
        self.image = image
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.future = future


class _Inflight:
    """One dispatched batch awaiting its bulk D2H + scatter."""

    __slots__ = ("requests", "bucket", "out", "buffer", "dispatched_at")

    def __init__(self, requests, bucket, out, buffer, dispatched_at):
        self.requests = requests
        self.bucket = bucket
        self.out = out
        self.buffer = buffer
        self.dispatched_at = dispatched_at


class StagingPool:
    """Per-bucket free-list of preallocated host batch buffers.

    A buffer is checked out at batch formation, pinned for the batch's
    whole device lifetime (the H2D may read it asynchronously), and
    returned after the drainer's bulk fetch — so steady state holds at
    most ``pipeline_depth + 1`` buffers per active bucket, reused
    forever.  ``allocated``/``reused`` make the reuse testable.
    """

    def __init__(self, input_shape: tuple):
        self._input_shape = tuple(input_shape)
        self._free: dict[int, list[np.ndarray]] = {}
        self._lock = threading.Lock()
        self.allocated = 0
        self.reused = 0

    def acquire(self, bucket: int) -> np.ndarray:
        with self._lock:
            free = self._free.setdefault(bucket, [])
            if free:
                self.reused += 1
                return free.pop()
            self.allocated += 1
        return np.zeros((bucket, *self._input_shape), np.float32)

    def release(self, bucket: int, buf: np.ndarray):
        with self._lock:
            self._free.setdefault(bucket, []).append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {"allocated": self.allocated, "reused": self.reused,
                    "pooled": {b: len(v) for b, v in self._free.items()}}


class BatchingEngine:
    """Pipelined dynamic batcher for one ServingModel.

    Use as a context manager or call ``start()``/``stop()``.  ``submit``
    returns a ``concurrent.futures.Future`` resolving to either the
    output pytree row (numpy, host-side) for that image or a ``Shed``;
    ``infer`` is the blocking convenience wrapper.

    ``pipeline_depth`` bounds dispatched-but-undrained batches: depth 1
    is the strictly synchronous path (complete inline, no drainer
    thread); depth ≥ 2 overlaps batch N+1's formation/staging/H2D with
    batch N's device compute.
    """

    def __init__(self, model, *, max_batch: int = 32,
                 max_wait_ms: float = 5.0, buckets: list[int] | None = None,
                 admission: AdmissionController | None = None,
                 pipeline_depth: int = 2):
        self.model = model
        if model.fixed_batch is not None:
            # a StableHLO blob serves exactly its traced shape
            buckets = [model.fixed_batch]
        self.buckets = sorted(buckets) if buckets else \
            power_of_two_buckets(max_batch)
        self.max_batch = self.buckets[-1]
        self.max_wait_s = max_wait_ms / 1e3
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.admission = admission or AdmissionController(
            max_wait_ms=max_wait_ms)
        self.latency = LatencyHistogram()
        self.throughput = ThroughputMeter(warmup_steps=1)
        self.staging = StagingPool(model.input_shape)
        self._queue: queue.Queue[_Request] = queue.Queue()
        self._executables: dict = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._drainer: threading.Thread | None = None
        # in-flight window: acquired at dispatch, released after drain
        self._inflight_sem = threading.BoundedSemaphore(self.pipeline_depth)
        self._inflight_q: queue.Queue[_Inflight | None] = queue.Queue()
        self._inflight = 0
        self.max_inflight = 0
        self.submitted = 0
        self.served = 0
        self.batches = 0
        self.compiles = 0
        self.padded_images = 0
        self.bulk_transfers = 0
        self.bulk_transfer_bytes = 0
        # device-idle accounting (host proxy: wall time with an EMPTY
        # in-flight window between the first dispatch and the last drain)
        self._first_dispatch: float | None = None
        self._last_done: float | None = None
        self._idle_s = 0.0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BatchingEngine":
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name=f"batcher-{self.model.name}",
                daemon=True)
            self._thread.start()
            if self.pipeline_depth > 1:
                self._drainer = threading.Thread(
                    target=self._drain_loop,
                    name=f"drainer-{self.model.name}", daemon=True)
                self._drainer.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._drainer is not None:
            # batcher has exited: every dispatched batch is already in
            # the drain queue, so the sentinel lands after the last one
            self._inflight_q.put(None)
            self._drainer.join(timeout)
            self._drainer = None
        # anything still queued will never run — tell its caller
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            req.future.set_result(Shed("shutdown", "engine stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def warmup(self, buckets: list[int] | None = None):
        """Compile ahead of traffic (persisted via core/compile_cache)."""
        import jax

        for b in (buckets or self.buckets):
            jax.block_until_ready(self._compiled(b)(np.zeros(
                (b, *self.model.input_shape), np.float32)))

    # -- request path ------------------------------------------------------

    def submit(self, image, deadline_ms: float | None = None) -> Future:
        now = time.monotonic()
        deadline = now + deadline_ms / 1e3 if deadline_ms is not None \
            else None
        with self._lock:
            self.submitted += 1
            inflight = self._inflight
        fut: Future = Future()
        depth = self._queue.qsize()
        shed = self.admission.admit(
            depth, deadline, now,
            bucket=self._bucket_for(min(depth + 1, self.max_batch)),
            inflight=inflight)
        if shed is not None:
            fut.set_result(shed)
            return fut
        self._queue.put(_Request(np.asarray(image, np.float32), deadline,
                                 now, fut))
        return fut

    def infer(self, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0):
        return self.submit(image, deadline_ms).result(timeout)

    # -- batcher thread (stage + dispatch) ---------------------------------

    def _loop(self):
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.05)
            except queue.Empty:
                continue
            batch = [first]
            drain_until = time.monotonic() + self.max_wait_s
            while len(batch) < self.max_batch:
                remaining = drain_until - time.monotonic()
                if remaining <= 0:
                    break
                try:
                    batch.append(self._queue.get(timeout=remaining))
                except queue.Empty:
                    break
            try:
                self._dispatch(batch)
            except Exception as e:  # deliver, don't kill the batcher
                for req in batch:
                    if not req.future.done():
                        req.future.set_exception(e)

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _compiled(self, bucket: int):
        fn = self._executables.get(bucket)
        if fn is None:
            fn = self.model.compile_bucket(bucket)
            self._executables[bucket] = fn
            with self._lock:
                self.compiles += 1
        return fn

    def _acquire_slot(self) -> bool:
        """Block until an in-flight slot frees (or the engine stops)."""
        while not self._stop.is_set():
            if self._inflight_sem.acquire(timeout=0.05):
                return True
        return False

    def _dispatch(self, batch: list[_Request]):
        import jax

        live = []
        for req in batch:
            expired = self.admission.expired(req.deadline)
            if expired is not None:
                req.future.set_result(expired)
            else:
                live.append(req)
        if not live:
            return
        n = len(live)
        bucket = self._bucket_for(n)
        fn = self._compiled(bucket)  # compile OUTSIDE the in-flight window
        if not self._acquire_slot():
            for req in live:
                req.future.set_result(Shed("shutdown", "engine stopped"))
            return
        buf = self.staging.acquire(bucket)
        for i, req in enumerate(live):
            buf[i] = req.image
        if n < bucket:
            buf[n:] = 0.0  # reused buffer: clear stale pad rows
        t0 = time.monotonic()
        # async H2D + dispatch: jax returns device futures immediately;
        # the staged buffer stays checked out until the drainer is done
        # with the batch, so the transfer may read it at its leisure
        out = fn(jax.device_put(buf))
        rec = _Inflight(live, bucket, out, buf, t0)
        with self._lock:
            if self._inflight == 0 and self._last_done is not None:
                self._idle_s += t0 - self._last_done
            if self._first_dispatch is None:
                self._first_dispatch = t0
            self._inflight += 1
            self.max_inflight = max(self.max_inflight, self._inflight)
        if self.pipeline_depth > 1:
            self._inflight_q.put(rec)
        else:
            self._finish(rec)

    # -- drainer thread (bulk D2H + scatter) -------------------------------

    def _drain_loop(self):
        while True:
            rec = self._inflight_q.get()
            if rec is None:
                return
            self._finish(rec)

    def _finish(self, rec: _Inflight):
        try:
            self._complete(rec)
        except Exception as e:
            for req in rec.requests:
                if not req.future.done():
                    req.future.set_exception(e)
        finally:
            self.staging.release(rec.bucket, rec.buffer)
            with self._lock:
                self._inflight -= 1
                self._last_done = time.monotonic()
            self._inflight_sem.release()

    def _complete(self, rec: _Inflight):
        import jax

        # ONE bulk D2H for the whole output pytree — not a device slice
        # + transfer per request per leaf
        host = jax.device_get(rec.out)
        t_done = time.monotonic()
        n = len(rec.requests)
        # per-batch device occupancy ≈ completion minus the later of its
        # dispatch or the previous batch's completion (under pipelining,
        # dispatch→done includes waiting behind the batch ahead)
        with self._lock:
            busy_from = rec.dispatched_at if self._last_done is None \
                else max(rec.dispatched_at, self._last_done)
        self.admission.observe_exec(t_done - busy_from, bucket=rec.bucket)
        nbytes = int(sum(np.asarray(a).nbytes
                         for a in jax.tree_util.tree_leaves(host)))
        with self._lock:
            self.batches += 1
            self.served += n
            self.padded_images += rec.bucket - n
            self.bulk_transfers += 1
            self.bulk_transfer_bytes += nbytes
        self.throughput.update(n)
        for i, req in enumerate(rec.requests):
            self.latency.record(t_done - req.enqueued_at)
            req.future.set_result(
                jax.tree_util.tree_map(lambda a: np.asarray(a)[i], host))

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            span = None
            if self._first_dispatch is not None and \
                    self._last_done is not None:
                span = self._last_done - self._first_dispatch
            out = {"model": self.model.name,
                   "submitted": self.submitted,
                   "served": self.served,
                   "batches": self.batches,
                   "compiles": self.compiles,
                   "padded_images": self.padded_images,
                   "queue_depth": self._queue.qsize(),
                   "buckets": list(self.buckets),
                   "compiled_buckets": sorted(self._executables),
                   "max_wait_ms": self.max_wait_s * 1e3,
                   "pipeline": {
                       "depth": self.pipeline_depth,
                       "inflight": self._inflight,
                       "max_inflight": self.max_inflight,
                       "bulk_transfers": self.bulk_transfers,
                       "bulk_transfer_bytes": self.bulk_transfer_bytes,
                       # host proxy: fraction of the first-dispatch →
                       # last-drain span with an empty in-flight window
                       "device_idle_frac": (
                           round(self._idle_s / span, 4)
                           if span and span > 0 else None)}}
        out["pipeline"]["staging"] = self.staging.stats()
        out["latency"] = self.latency.percentiles()
        out["img_per_sec"] = self.throughput.images_per_sec
        out["admission"] = self.admission.stats()
        return out
