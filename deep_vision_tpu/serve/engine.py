"""Pipelined background-thread dynamic micro-batcher.

The training stack amortizes XLA dispatch over ``lax.scan`` steps and
hides host work behind device compute with double-buffered prefetch
(~2% dispatch idle, docs/PERF.md); the serving stack applies the same
argument to dynamically-formed request batches with a two-stage
pipeline:

  batcher thread   drains the queue up to ``max_batch``/``max_wait_ms``,
                   stages the batch into a REUSED preallocated host
                   buffer for its bucket (no per-batch ``np.zeros``),
                   issues the H2D transfer + compiled program
                   asynchronously (JAX dispatch returns before the
                   device finishes), and hands the in-flight record off;
  drainer thread   waits on completed batches in dispatch order, fetches
                   the WHOLE output pytree with one bulk
                   ``jax.device_get`` per batch (not one device slice
                   per request per leaf), and scatters numpy rows to
                   per-request futures on the host.

A ``pipeline_depth``-bounded semaphore caps dispatched-but-undrained
batches, so batch N+1's formation, staging, and H2D overlap batch N's
device compute while memory stays bounded.  ``pipeline_depth=1`` is the
synchronous mode: the batcher completes each batch inline (same staging
buffers, same single bulk transfer — bit-identical outputs, no overlap).

Bucketing is unchanged from the original engine: batches pad to a small
set of power-of-two buckets so every served shape hits an
already-compiled program (the bucket dict IS the jit cache — a miss is
an explicit, counted compile, never a surprise mid-request trace).
Compiled bucket programs donate their input buffer where the runtime
allows (registry.py), so the padded batch's device allocation is
recycled into the outputs.

Deadline handling is two-phase: admission (``admission.py``) sheds
requests that cannot possibly make their deadline at submit time —
using a per-bucket execution-time EWMA and the current in-flight depth
— and the batcher re-checks at batch-formation time so a request that
expired while queued is dropped rather than executed late.

Fault tolerance (docs/SERVING.md "Failure model & operations"):

  * both worker threads publish **heartbeats** (``serve/health.py``); a
    **watchdog** thread restarts a dead batcher/drainer (bounded by
    ``restart_budget``) and fast-fails the in-flight window when a
    batch's wall age exceeds ``exec_timeout`` = max(floor, k × the
    bucket's exec EWMA), so a hung device call can't park futures
    forever;
  * a dispatched batch that raises doesn't fail all N futures —
    **bisect-retry** re-executes cohort halves (bounded by
    ``retry_budget``, exponential backoff) to quarantine the poison
    request and serve the innocent ones; quarantined requests resolve
    to a structured ``Quarantined`` result;
  * failures feed the engine's OK → DEGRADED → DEAD **state machine**
    (``EngineHealth``), surfaced via ``/v1/healthz`` (503 when not OK)
    and the ``health`` block in stats;
  * ``submit`` before ``start()`` / after ``stop()`` fails fast with
    ``Shed("shutdown")``; ``stop(drain_deadline=...)`` rejects new
    submits immediately but finishes admitted work up to the deadline;
  * a deterministic **fault plane** (``serve/faults.py``, enabled via
    ``--faults`` / ``DVT_SERVE_FAULTS``) injects exceptions, latency,
    hangs, NaN output, poison requests, and thread deaths at each stage
    so all of the above is exercised by the chaos suite
    (``make serve-chaos``) — every injection point guards on
    ``faults.enabled`` first, keeping the no-faults hot path identical.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import deque
from concurrent.futures import Future

import numpy as np

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.core.metrics import LatencyHistogram, ThroughputMeter
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.obs.mfu import MfuMeter
from deep_vision_tpu.obs.trace import Tracer
from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.faults import (
    FaultPlane,
    InjectedFault,
    KillThread,
    Quarantined,
)
from deep_vision_tpu.serve.health import EngineHealth

_log = get_logger("dvt.serve.engine")


def power_of_two_buckets(max_batch: int) -> list[int]:
    """1, 2, 4, ... plus ``max_batch`` itself when it isn't a power of 2."""
    buckets, b = [], 1
    while b < max_batch:
        buckets.append(b)
        b *= 2
    buckets.append(max_batch)
    return buckets


def sharded_buckets(max_batch: int, num_devices: int) -> list[int]:
    """Bucket ladder for the sharded big-batch path (``--shard-batches``
    and mesh serving — pass the DATA-axis size, not the chip count: a
    2×2 data×model mesh splits each batch 2 ways): every bucket a
    multiple of ``num_devices`` so the padded mega-batch lays evenly
    across the mesh's data axis — n, 2n, 4n, ... max."""
    n = max(1, int(num_devices))
    top = max(1, max_batch // n)
    return [n * b for b in power_of_two_buckets(top)]


def device_hbm_headroom() -> int | None:
    """Per-chip free HBM bytes (``bytes_limit - bytes_in_use`` from the
    runtime's memory_stats), advertised through /v1/healthz so the
    gateway's fleet table can place models by capacity.  None where the
    backend doesn't report (host CPU devices) — absence means unknown,
    never zero."""
    import jax

    try:
        stats = jax.local_devices()[0].memory_stats()
        if not stats:
            return None
        limit = stats.get("bytes_limit")
        used = stats.get("bytes_in_use")
        if limit is None or used is None:
            return None
        return int(limit) - int(used)
    except Exception:  # noqa: BLE001 — memory_stats is best-effort, backend-specific
        return None


class _Request:
    __slots__ = ("image", "deadline", "enqueued_at", "future", "poison",
                 "span")

    def __init__(self, image, deadline, enqueued_at, future, poison=False,
                 span=None):
        self.image = image
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.future = future
        self.poison = poison
        # obs.trace.Span or None (tracing off): every touch point on
        # the hot path guards on that single None read, faults.py-style
        self.span = span


class _Inflight:
    """One dispatched batch awaiting its bulk D2H + scatter."""

    __slots__ = ("requests", "bucket", "out", "buffer", "dispatched_at",
                 "cancelled", "cancel")

    def __init__(self, requests, bucket, out, buffer, dispatched_at,
                 cancel=None):
        self.requests = requests
        self.bucket = bucket
        self.out = out
        self.buffer = buffer
        self.dispatched_at = dispatched_at
        self.cancelled = False   # watchdog fast-failed this window
        self.cancel = cancel     # Event breaking injected hangs (faults on)


class StagingPool:
    """Per-bucket free-list of preallocated host batch buffers.

    A buffer is checked out at batch formation, pinned for the batch's
    whole device lifetime (the H2D may read it asynchronously), and
    returned after the drainer's bulk fetch — so steady state holds at
    most ``pipeline_depth + 1`` buffers per active bucket, reused
    forever.  ``allocated``/``reused`` make the reuse testable.

    Buffers carry the model's WIRE dtype: a uint8 wire stages (and
    H2D-transfers) 4× fewer bytes per padded batch than the float32
    wire (docs/SERVING.md "Wire format & inference dtype").
    """

    def __init__(self, input_shape: tuple, dtype=np.float32):
        self._input_shape = tuple(input_shape)
        self.dtype = np.dtype(dtype)
        self._free: dict[int, list[np.ndarray]] = {}  # guarded-by: _lock
        self._lock = new_lock("serve.engine.StagingPool._lock")
        self.allocated = 0  # guarded-by: _lock
        self.reused = 0  # guarded-by: _lock

    def acquire(self, bucket: int) -> np.ndarray:
        with self._lock:
            free = self._free.setdefault(bucket, [])
            if free:
                self.reused += 1
                return free.pop()
            self.allocated += 1
        return np.zeros((bucket, *self._input_shape), self.dtype)

    def release(self, bucket: int, buf: np.ndarray):
        with self._lock:
            self._free.setdefault(bucket, []).append(buf)

    def stats(self) -> dict:
        with self._lock:
            return {"allocated": self.allocated, "reused": self.reused,
                    "dtype": str(self.dtype),
                    "pooled": {b: len(v) for b, v in self._free.items()}}


class BatchingEngine:
    """Pipelined dynamic batcher for one ServingModel.

    Use as a context manager or call ``start()``/``stop()``.  ``submit``
    returns a ``concurrent.futures.Future`` resolving to the output
    pytree row (numpy, host-side) for that image, a ``Shed``, or a
    ``Quarantined``; ``infer`` is the blocking convenience wrapper.

    ``pipeline_depth`` bounds dispatched-but-undrained batches: depth 1
    is the strictly synchronous path (complete inline, no drainer
    thread); depth ≥ 2 overlaps batch N+1's formation/staging/H2D with
    batch N's device compute.

    Supervision knobs (all off the hot path — see module docstring):
    ``watchdog_interval_s`` (0 disables the watchdog), ``restart_budget``
    (thread restarts before the engine goes sticky-DEAD),
    ``exec_timeout_k``/``exec_timeout_min_s`` (stuck-batch fast-fail),
    ``retry_budget``/``singleton_retries``/``retry_backoff_ms``
    (bisect-retry isolation), ``degraded_after``/``dead_after`` (state
    machine thresholds), ``faults`` (injection plane; defaults to the
    ``DVT_SERVE_FAULTS`` env spec, disabled when unset).
    """

    def __init__(self, model, *, max_batch: int = 32,
                 max_wait_ms: float = 5.0, buckets: list[int] | None = None,
                 admission: AdmissionController | None = None,
                 pipeline_depth: int = 2,
                 faults: FaultPlane | None = None,
                 watchdog_interval_s: float = 0.05,
                 restart_budget: int = 3,
                 exec_timeout_k: float = 10.0,
                 exec_timeout_min_s: float = 2.0,
                 retry_budget: int = 16,
                 singleton_retries: int = 1,
                 retry_backoff_ms: float = 2.0,
                 retry_backoff_max_ms: float = 100.0,
                 degraded_after: int = 1, dead_after: int = 5,
                 external_batcher: bool = False,
                 rescue=None,
                 tracer: Tracer | None = None,
                 validate_outputs: bool | None = None):
        self.model = model
        if model.fixed_batch is not None:
            # a StableHLO blob serves exactly its traced shapes; an
            # explicitly conflicting bucket list is an operator error —
            # name the exported sizes instead of overriding silently
            available = getattr(model, "bucket_sizes",
                                [model.fixed_batch])
            if buckets and any(b not in available for b in buckets):
                raise ValueError(
                    f"model '{model.name}' was exported with bucket "
                    f"sizes {available}; requested buckets "
                    f"{sorted(buckets)} unavailable — re-export or "
                    f"serve from the checkpoint")
            buckets = buckets or list(available)
        self.buckets = sorted(buckets) if buckets else \
            power_of_two_buckets(max_batch)
        self.max_batch = self.buckets[-1]
        self.max_wait_s = max_wait_ms / 1e3
        self.pipeline_depth = max(1, int(pipeline_depth))
        self.admission = admission or AdmissionController(
            max_wait_ms=max_wait_ms)
        self.latency = LatencyHistogram()
        self.throughput = ThroughputMeter(warmup_steps=1)
        # request tracing + serving-MFU accounting (obs/): the tracer is
        # shared with the HTTP front-end (and across replicas) so one
        # ring holds the whole process's recent traces
        self.tracer = tracer or Tracer()
        self.mfu = MfuMeter()
        # the model's wire format IS the staging/H2D dtype: submit casts
        # to it, pooled buffers allocate in it, the bulk device_put
        # ships it (uint8 wire = 4× fewer staged bytes than float32)
        self.wire_dtype = np.dtype(getattr(model, "wire_dtype",
                                           np.float32))
        self.staging = StagingPool(model.input_shape, self.wire_dtype)
        self.faults = faults or FaultPlane.from_env()
        self.health = EngineHealth(degraded_after=degraded_after,
                                   dead_after=dead_after)
        self.watchdog_interval_s = watchdog_interval_s
        self.restart_budget = restart_budget
        self.exec_timeout_k = exec_timeout_k
        self.exec_timeout_min_s = exec_timeout_min_s
        self.retry_budget = retry_budget
        self.singleton_retries = singleton_retries
        self.retry_backoff_ms = retry_backoff_ms
        self.retry_backoff_max_ms = retry_backoff_max_ms
        # NaN-output validation only costs when the fault plane is live;
        # validate_outputs=False opts out even then (the control plane's
        # canary gate wants a fault-injected "bad" version to SERVE its
        # NaNs so the gate — not the engine — catches them)
        self._validate = self.faults.enabled \
            if validate_outputs is None else bool(validate_outputs)
        # replica mode (serve/replicas.py): the ReplicatedEngine owns
        # the queue + batch formation and feeds formed cohorts through
        # dispatch_cohort(); no batcher thread runs here and the
        # watchdog supervises only the drainer
        self.external_batcher = external_batcher
        # rescue(requests, err) -> bool: offered the still-pending
        # requests of a fast-failed in-flight window BEFORE they get
        # their TimeoutError; True = another replica took them over
        self._rescue = rescue
        self._queue: queue.Queue[_Request] = queue.Queue()
        self._executables: dict = {}
        self._lock = new_lock("serve.engine.BatchingEngine._lock")
        self._stop = threading.Event()
        self._accepting = False
        self._thread: threading.Thread | None = None
        self._drainer: threading.Thread | None = None
        self._watchdog: threading.Thread | None = None
        # in-flight window: acquired at dispatch, released after drain
        self._inflight_sem = threading.BoundedSemaphore(self.pipeline_depth)
        self._inflight_q: queue.Queue[_Inflight | None] = queue.Queue()
        self._inflight = 0  # guarded-by: _lock
        self._forming = 0  # requests the batcher holds but hasn't dispatched
        self._inflight_recs: list[_Inflight] = []  # watchdog visibility; guarded-by: _lock
        self.max_inflight = 0  # guarded-by: _lock
        self.submitted = 0  # guarded-by: _lock
        self.served = 0  # guarded-by: _lock
        self.batches = 0  # guarded-by: _lock
        self.compiles = 0  # guarded-by: _lock
        self.padded_images = 0  # guarded-by: _lock
        self.bulk_transfers = 0  # guarded-by: _lock
        self.bulk_transfer_bytes = 0  # guarded-by: _lock
        # H2D accounting: bytes of staged wire-format batches shipped to
        # the device (the observable 4× win of the uint8 wire) — counted
        # at both the pipelined dispatch and the synchronous retry path
        self.h2d_transfers = 0  # guarded-by: _lock
        self.h2d_bytes = 0  # guarded-by: _lock
        self.h2d_bytes_by_bucket: dict[int, int] = {}  # guarded-by: _lock
        # D2H accounting: bytes the bulk per-batch device_get moved
        # back to the host — the output-side mirror of h2d_bytes.  For
        # the generate workload (uint8 epilogue fused into the bucket
        # programs, serve/workloads.py) this is where the 4× output-
        # wire win shows up; counted at the pipelined drain and the
        # synchronous retry path, same as the H2D pair
        self.d2h_bytes = 0  # guarded-by: _lock
        self.d2h_bytes_by_bucket: dict[int, int] = {}  # guarded-by: _lock
        # fault-tolerance accounting
        self.batch_failures = 0  # guarded-by: _lock
        self.retry_executions = 0  # guarded-by: _lock
        self.quarantined = 0  # guarded-by: _lock
        self.exec_timeouts = 0  # guarded-by: _lock
        self.shed_shutdown = 0  # guarded-by: _lock
        # device-idle accounting (host proxy: wall time with an EMPTY
        # in-flight window between the first dispatch and the last drain)
        self._first_dispatch: float | None = None  # guarded-by: _lock
        self._last_done: float | None = None  # guarded-by: _lock
        self._idle_s = 0.0  # guarded-by: _lock
        # compute-occupancy window: (t_done, busy_s) per executed batch,
        # busy_s being the same compute-stage measurement admission and
        # the MFU meter consume.  A ROLLING gauge (unlike the span-long
        # _idle_s proxy): the batch scheduler's trough maths and the
        # batchy-SLO autoscaler both need "busy lately", not "busy ever"
        self.occupancy_window_s = 10.0
        self._busy_events: deque = deque()  # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "BatchingEngine":
        if not self._accepting:
            self._stop.clear()
            self.faults.cancel.clear()
            self.health.revive()
            if not self.external_batcher:
                self._thread = threading.Thread(
                    target=self._loop, name=f"batcher-{self.model.name}",
                    daemon=True)
                self._thread.start()
            if self.pipeline_depth > 1:
                self._drainer = threading.Thread(
                    target=self._drain_loop,
                    name=f"drainer-{self.model.name}", daemon=True)
                self._drainer.start()
            if self.watchdog_interval_s > 0:
                self._watchdog = threading.Thread(
                    target=self._watchdog_loop,
                    name=f"watchdog-{self.model.name}", daemon=True)
                self._watchdog.start()
            self._accepting = True
        return self

    def stop(self, timeout: float = 5.0,
             drain_deadline: float | None = None):
        """Stop the engine.  New submits fail fast immediately; with a
        ``drain_deadline`` (seconds) admitted work is finished first —
        whatever hasn't completed by the deadline sheds as shutdown."""
        was_running = self._accepting
        self._accepting = False
        if drain_deadline is not None and was_running:
            t_end = time.monotonic() + drain_deadline
            while time.monotonic() < t_end:
                with self._lock:
                    busy = self._inflight
                if busy == 0 and self._forming == 0 \
                        and self._queue.qsize() == 0:
                    break
                time.sleep(0.005)
        self._stop.set()
        self.faults.cancel.set()  # release any injected hang
        if self._watchdog is not None:
            self._watchdog.join(timeout)
            self._watchdog = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        if self._drainer is not None:
            # batcher has exited: every dispatched batch is already in
            # the drain queue, so the sentinel lands after the last one
            self._inflight_q.put(None)
            self._drainer.join(timeout)
            self._drainer = None
        # anything still queued will never run — tell its caller
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_result(Shed("shutdown", "engine stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def warmup(self, buckets: list[int] | None = None):
        """Compile ahead of traffic (persisted via core/compile_cache)."""
        import jax

        for b in (buckets or self.buckets):
            jax.block_until_ready(self._compiled(b)(np.zeros(
                (b, *self.model.input_shape), self.wire_dtype)))

    # -- request path ------------------------------------------------------

    def submit(self, image, deadline_ms: float | None = None,
               span=None) -> Future:
        fut: Future = Future()
        # span ownership: a caller-provided span (HTTP front-end) is
        # marked here but finished by its creator; an engine-created
        # span seals itself on ANY terminal path via the future's
        # done-callback (served, shed, quarantined, timed out)
        if span is None and self.tracer.enabled:
            span = self.tracer.start()
            fut.add_done_callback(
                lambda _f, _s=span: self.tracer.finish(_s))
        if not self._accepting:
            # fail fast: nothing drains the queue before start()/after
            # stop(), so enqueueing would park the future forever
            with self._lock:
                self.submitted += 1
                self.shed_shutdown += 1
            if span is not None:
                span.note("shed", "shutdown")
            fut.set_result(Shed(
                "shutdown", "engine is not accepting requests "
                            "(stopped or not started)"))
            return fut
        now = time.monotonic()
        deadline = now + deadline_ms / 1e3 if deadline_ms is not None \
            else None
        with self._lock:
            self.submitted += 1
            inflight = self._inflight
        depth = self._queue.qsize()
        shed = self.admission.admit(
            depth, deadline, now,
            bucket=self._bucket_for(min(depth + 1, self.max_batch)),
            inflight=inflight)
        if shed is not None:
            if span is not None:
                span.note("shed", shed.reason)
            fut.set_result(shed)
            return fut
        self.admission.record_admit()
        poison = self.faults.mark_poison() if self.faults.enabled else False
        if span is not None:
            span.mark("admit")
        # the request rides the WIRE dtype end to end: uint8 clients hand
        # raw pixels straight through to the staged batch (no float copy)
        self._queue.put(_Request(np.asarray(image, self.wire_dtype),
                                 deadline, now, fut, poison, span))
        return fut

    def infer(self, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0, span=None):
        return self.submit(image, deadline_ms, span=span).result(timeout)

    # -- batcher thread (stage + dispatch) ---------------------------------

    def _loop(self):  # dvtlint: hot
        try:
            while not self._stop.is_set():
                self.health.beat("batcher")
                if self.faults.enabled:
                    self.faults.inject("batcher", stop=self._stop)
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                if first.span is not None:
                    first.span.mark("queue_wait")
                # non-zero while requests are in hand but not yet in the
                # in-flight window, so stop(drain_deadline=...) can't
                # slip between queue drain and dispatch
                self._forming = 1
                try:
                    batch = [first]
                    drain_until = time.monotonic() + self.max_wait_s
                    while len(batch) < self.max_batch:
                        remaining = drain_until - time.monotonic()
                        if remaining <= 0:
                            break
                        try:
                            req = self._queue.get(timeout=remaining)
                        except queue.Empty:
                            break
                        if req.span is not None:
                            req.span.mark("queue_wait")
                        batch.append(req)
                    self.dispatch_cohort(batch)
                finally:
                    self._forming = 0
        except KillThread:
            return  # injected death: the watchdog notices and restarts

    def dispatch_cohort(self, batch: list[_Request]):  # dvtlint: hot
        """Dispatch an already-formed cohort into this engine's
        pipeline.  The internal batcher calls it after queue drain; in
        replica mode (``external_batcher=True``) the ReplicatedEngine's
        router calls it directly — blocking here while this replica's
        in-flight window is full is the router's backpressure.
        Exceptions are delivered to the cohort's futures, never raised
        (a failed batch must not kill the calling thread)."""
        self._forming = max(self._forming, len(batch))
        try:
            self._dispatch(batch)
        except Exception as e:  # noqa: BLE001 — deliver the failure to waiters, don't kill the caller
            for req in batch:
                if not req.future.done():
                    req.future.set_exception(e)
            self.health.record_failure()
        finally:
            self._forming = 0

    def _bucket_for(self, n: int) -> int:
        for b in self.buckets:
            if b >= n:
                return b
        return self.buckets[-1]

    def _compiled(self, bucket: int):
        fn = self._executables.get(bucket)
        if fn is None:
            fn = self.model.compile_bucket(bucket)
            self._executables[bucket] = fn
            with self._lock:
                self.compiles += 1
            # registry attaches the bucket program's analytic FLOPs at
            # compile time (XLA cost analysis, or the documented
            # params-based lower bound) — the serving-MFU numerator
            self.mfu.set_bucket_flops(
                bucket, getattr(fn, "cost_flops", None),
                getattr(fn, "flops_source", None))
        return fn

    def _fill(self, buf: np.ndarray, requests: list[_Request]):
        """Stage a cohort into a pooled buffer: scatter rows, zero the
        stale pad tail (buffers are REUSED, so old rows linger)."""
        n = len(requests)
        for i, req in enumerate(requests):
            buf[i] = req.image
        if n < buf.shape[0]:
            buf[n:] = 0.0

    def _put(self, buf: np.ndarray):
        """H2D transfer honoring the model view's placement: the
        replica's pinned device or the big-batch mesh sharding
        (registry.for_device/for_mesh); None = runtime default.  Both
        the pipelined dispatch and the synchronous retry path transfer
        through here, so they can never diverge on placement."""
        import jax

        return jax.device_put(buf, self.model.placement)

    def _acquire_slot(self) -> bool:
        """Block until an in-flight slot frees (or the engine stops)."""
        while not self._stop.is_set():
            self.health.beat("batcher")
            if self._inflight_sem.acquire(timeout=0.05):
                return True
        return False

    def _dispatch(self, batch: list[_Request]):  # dvtlint: hot
        live = []
        for req in batch:
            expired = self.admission.expired(req.deadline)
            if expired is not None:
                if req.span is not None:
                    req.span.note("shed", "deadline expired in queue")
                req.future.set_result(expired)
            else:
                if req.span is not None:
                    req.span.mark("batch_form")
                live.append(req)
        if not live:
            return
        n = len(live)
        bucket = self._bucket_for(n)
        fn = self._compiled(bucket)  # compile OUTSIDE the in-flight window
        if not self._acquire_slot():
            for req in live:
                req.future.set_result(Shed("shutdown", "engine stopped"))
            return
        buf = self.staging.acquire(bucket)
        try:
            if self.faults.enabled:
                self.faults.inject("staging", stop=self._stop)
            self._fill(buf, live)
            # the staging segment covers compile (first hit only), the
            # in-flight-slot wait (pipeline backpressure) and the buffer
            # fill — everything between formation and the H2D issue
            for req in live:
                if req.span is not None:
                    req.span.mark("staging")
            t0 = time.monotonic()
            if self.faults.enabled:
                self.faults.inject("dispatch", stop=self._stop)
                self.faults.inject("compute", stop=self._stop)
                if self.faults.cohort_poisoned(live):
                    raise InjectedFault(
                        f"poisoned request in cohort of {n}")
            # async H2D + dispatch: jax returns device futures
            # immediately; the staged buffer stays checked out until the
            # drainer is done with the batch, so the transfer may read
            # it at its leisure
            out = fn(self._put(buf))
        except Exception as e:  # noqa: BLE001 — dispatch-side batch failure: free the slot, then isolate

            self.staging.release(bucket, buf)
            self._inflight_sem.release()
            self._cohort_failed(live, e)
            return
        for req in live:
            if req.span is not None:
                req.span.mark("h2d_dispatch")
        rec = _Inflight(live, bucket, out, buf, t0,
                        threading.Event() if self.faults.enabled else None)
        with self._lock:
            self.h2d_transfers += 1
            self.h2d_bytes += buf.nbytes
            self.h2d_bytes_by_bucket[bucket] = \
                self.h2d_bytes_by_bucket.get(bucket, 0) + buf.nbytes
            if self._inflight == 0 and self._last_done is not None:
                self._idle_s += t0 - self._last_done
            if self._first_dispatch is None:
                self._first_dispatch = t0
            self._inflight += 1
            self.max_inflight = max(self.max_inflight, self._inflight)
            self._inflight_recs.append(rec)
        if self.pipeline_depth > 1:
            self._inflight_q.put(rec)
        else:
            self._finish(rec)

    # -- drainer thread (bulk D2H + scatter) -------------------------------

    def _drain_loop(self):  # dvtlint: hot
        try:
            while True:
                self.health.beat("drainer")
                try:
                    rec = self._inflight_q.get(timeout=0.25)
                except queue.Empty:
                    if self._stop.is_set():
                        return
                    continue
                if rec is None:
                    if self._stop.is_set():
                        return  # shutdown sentinel
                    continue  # stale sentinel from a previous stop
                self._finish(rec)
        except KillThread:
            return  # injected death: the watchdog notices and restarts

    def _finish(self, rec: _Inflight):
        try:
            self._complete(rec)
        except Exception as e:  # noqa: BLE001 — completion failure fails the cohort, not the drainer
            self._cohort_failed(rec.requests, e)
        finally:
            self.staging.release(rec.bucket, rec.buffer)
            with self._lock:
                self._inflight -= 1
                try:
                    self._inflight_recs.remove(rec)
                except ValueError:
                    pass
                self._last_done = time.monotonic()
            self._inflight_sem.release()

    def _complete(self, rec: _Inflight):  # dvtlint: hot
        import jax

        mode = None
        if self.faults.enabled:
            mode = self.faults.inject("d2h", stop=self._stop,
                                      cancel=rec.cancel)
        # ONE bulk D2H for the whole output pytree — not a device slice
        # + transfer per request per leaf
        host = jax.device_get(rec.out)  # dvtlint: disable=DVT003 — the single bulk D2H per batch
        if mode == "nan":
            # corrupt only FLOAT leaves: integer outputs (class ids,
            # valid masks) can't hold NaN and _check_outputs skips them
            host = jax.tree_util.tree_map(
                lambda a: np.full_like(np.asarray(a), np.nan)
                if np.asarray(a).dtype.kind == "f" else np.asarray(a),
                host)
        if self._validate:
            self._check_outputs(host)
        if rec.cancelled:
            return  # watchdog already fast-failed these futures
        t_done = time.monotonic()
        n = len(rec.requests)
        # per-batch device occupancy ≈ completion minus the later of its
        # dispatch or the previous batch's completion (under pipelining,
        # dispatch→done includes waiting behind the batch ahead)
        with self._lock:
            busy_from = rec.dispatched_at if self._last_done is None \
                else max(rec.dispatched_at, self._last_done)
            self._busy_events.append((t_done, t_done - busy_from))
            self._prune_busy_locked(t_done)
        self.admission.observe_exec(t_done - busy_from, bucket=rec.bucket)
        # the same device-occupancy measurement is the serving-MFU
        # denominator: compute-stage seconds, not queue or drain wait
        self.mfu.observe(rec.bucket, n, t_done - busy_from)
        nbytes = int(sum(np.asarray(a).nbytes
                         for a in jax.tree_util.tree_leaves(host)))
        with self._lock:
            self.batches += 1
            self.served += n
            self.padded_images += rec.bucket - n
            self.bulk_transfers += 1
            self.bulk_transfer_bytes += nbytes
            self.d2h_bytes += nbytes
            self.d2h_bytes_by_bucket[rec.bucket] = \
                self.d2h_bytes_by_bucket.get(rec.bucket, 0) + nbytes
        self.throughput.update(n)
        for i, req in enumerate(rec.requests):
            self.latency.record(t_done - req.enqueued_at)
            if req.span is not None:
                # marked BEFORE resolving the future: the span's owner
                # (HTTP handler / done-callback) takes over at resolve,
                # so the engine never appends to a span concurrently
                req.span.mark("compute_d2h")
            if not req.future.done():
                req.future.set_result(
                    jax.tree_util.tree_map(lambda a: np.asarray(a)[i],
                                           host))
        self.health.record_success(t_done)

    @staticmethod
    def _check_outputs(host):
        import jax

        for leaf in jax.tree_util.tree_leaves(host):
            arr = np.asarray(leaf)
            if arr.dtype.kind == "f" and np.isnan(arr).any():
                raise InjectedFault("NaN in model output")

    # -- batch-failure isolation (bisect-retry) ----------------------------

    def _cohort_failed(self, requests: list[_Request], err: Exception):
        """A dispatched or drained cohort raised: record the failure,
        then bisect-retry to quarantine the poison request(s) and serve
        the innocent ones.  Runs synchronously in the failing thread —
        off the happy path, bounded by ``retry_budget``."""
        with self._lock:
            self.batch_failures += 1
        self.health.record_failure()
        pending = [r for r in requests if not r.future.done()]
        event(_log, "batch_failure", model=self.model.name,
              cohort=len(requests), pending=len(pending),
              error=f"{type(err).__name__}: {err}")
        if not pending:
            return
        for r in pending:
            if r.span is not None:
                r.span.note("batch_failure", type(err).__name__)
        budget = [self.retry_budget]
        self._isolate(pending, err, budget)

    def _backoff(self, budget: list[int]):
        attempt = self.retry_budget - budget[0]
        delay_ms = min(self.retry_backoff_max_ms,
                       self.retry_backoff_ms * (2 ** max(0, attempt)))
        if delay_ms > 0:
            time.sleep(delay_ms / 1e3)

    def _isolate(self, cohort: list[_Request], err: Exception,
                 budget: list[int]):
        if self._stop.is_set():
            for r in cohort:
                if not r.future.done():
                    r.future.set_result(Shed("shutdown", "engine stopped"))
            return
        if len(cohort) == 1:
            # transient benefit of the doubt before quarantining
            for _ in range(self.singleton_retries):
                if budget[0] <= 0:
                    break
                self._backoff(budget)
                budget[0] -= 1
                try:
                    self._execute_subset(cohort)
                    return
                except Exception as e:  # noqa: BLE001 — keep isolating
                    err = e
            self._quarantine(cohort[0], err, exhausted=False)
            return
        mid = len(cohort) // 2
        for sub in (cohort[:mid], cohort[mid:]):
            if budget[0] <= 0:
                for r in sub:
                    self._quarantine(r, err, exhausted=True)
                continue
            self._backoff(budget)
            budget[0] -= 1
            try:
                self._execute_subset(sub)
            except Exception as e:  # noqa: BLE001 — keep bisecting
                self._isolate(sub, e, budget)

    def _quarantine(self, req: _Request, err: Exception, exhausted: bool):
        with self._lock:
            self.quarantined += 1
        reason = "retry_budget" if exhausted else "poison"
        if req.span is not None:
            req.span.note("quarantined", reason)
        event(_log, "quarantine", model=self.model.name, reason=reason,
              request_id=req.span.request_id if req.span else None,
              error=f"{type(err).__name__}: {err}")
        if not req.future.done():
            req.future.set_result(Quarantined(
                reason, f"{type(err).__name__}: {err}"))

    def _execute_subset(self, requests: list[_Request]):
        """Synchronous re-execution of a retry cohort: own staging
        buffer, inline D2H — deliberately outside the pipeline window so
        retries can't wedge the happy path."""
        import jax

        with self._lock:
            self.retry_executions += 1
        n = len(requests)
        for req in requests:
            if req.span is not None:
                req.span.note("bisect_retry", f"cohort of {n}")
        bucket = self._bucket_for(n)
        fn = self._compiled(bucket)
        t0 = time.monotonic()
        # same allocation contract as the pipelined path: pooled staging
        # buffer + the shared placement-aware transfer — never a fresh
        # np.zeros / bare device_put per retry batch
        buf = self.staging.acquire(bucket)
        try:
            self._fill(buf, requests)
            if self.faults.enabled:
                self.faults.inject("compute", stop=self._stop)
                if self.faults.cohort_poisoned(requests):
                    raise InjectedFault(
                        f"poisoned request in retry cohort of {n}")
            with self._lock:
                self.h2d_transfers += 1
                self.h2d_bytes += buf.nbytes
                self.h2d_bytes_by_bucket[bucket] = \
                    self.h2d_bytes_by_bucket.get(bucket, 0) + buf.nbytes
            host = jax.device_get(fn(self._put(buf)))
            if self._validate:
                self._check_outputs(host)
        finally:
            self.staging.release(bucket, buf)
        t_done = time.monotonic()
        # the retry ran synchronously, so its wall time IS its compute
        # occupancy — feed the MFU meter the same way the drainer does
        self.mfu.observe(bucket, n, t_done - t0)
        nbytes = int(sum(np.asarray(a).nbytes
                         for a in jax.tree_util.tree_leaves(host)))
        with self._lock:
            self.batches += 1
            self.served += n
            self.padded_images += bucket - n
            self.bulk_transfers += 1
            self.bulk_transfer_bytes += nbytes
            self.d2h_bytes += nbytes
            self.d2h_bytes_by_bucket[bucket] = \
                self.d2h_bytes_by_bucket.get(bucket, 0) + nbytes
            self._busy_events.append((t_done, t_done - t0))
            self._prune_busy_locked(t_done)
        self.throughput.update(n)
        for i, req in enumerate(requests):
            self.latency.record(t_done - req.enqueued_at)
            if req.span is not None:
                req.span.mark("retry_exec")
            if not req.future.done():
                req.future.set_result(
                    jax.tree_util.tree_map(lambda a: np.asarray(a)[i],
                                           host))
        self.health.record_success(t_done)

    # -- watchdog thread (supervision) -------------------------------------

    def _watchdog_loop(self):
        while not self._stop.is_set():
            time.sleep(self.watchdog_interval_s)
            if self._stop.is_set():
                return
            try:
                self._watchdog_tick(time.monotonic())
            except Exception:  # noqa: BLE001 — the supervisor never dies
                pass

    def _watchdog_tick(self, now: float):
        t = self._thread
        if not self.external_batcher and t is not None \
                and not t.is_alive():
            self._restart("batcher")
        d = self._drainer
        if self.pipeline_depth > 1 and d is not None and not d.is_alive():
            self._restart("drainer")
        # stuck compute: any in-flight batch older than its exec budget
        with self._lock:
            recs = [r for r in self._inflight_recs if not r.cancelled]
        for rec in recs:
            ewma = self.admission.bucket_ewma_s(rec.bucket)
            limit = self.exec_timeout_min_s if not ewma else \
                max(self.exec_timeout_min_s, self.exec_timeout_k * ewma)
            if now - rec.dispatched_at > limit:
                self._fail_inflight_window(now - rec.dispatched_at, limit)
                break

    def _restart(self, which: str):
        if self._stop.is_set():
            return
        self.health.record_failure()
        if self.health.watchdog_restarts >= self.restart_budget:
            self.health.force_dead(
                f"{which} died and the restart budget "
                f"({self.restart_budget}) is exhausted")
            event(_log, "engine_dead", model=self.model.name, which=which,
                  restart_budget=self.restart_budget)
            return
        self.health.record_restart()
        event(_log, "watchdog_restart", model=self.model.name, which=which,
              restarts=self.health.watchdog_restarts,
              budget=self.restart_budget)
        thread = threading.Thread(
            target=self._loop if which == "batcher" else self._drain_loop,
            name=f"{which}-{self.model.name}", daemon=True)
        if which == "batcher":
            self._thread = thread
        else:
            self._drainer = thread
        thread.start()

    def _fail_inflight_window(self, age_s: float, limit_s: float):
        """A batch exceeded its exec timeout: fail every in-flight
        future fast so callers aren't parked behind a hung device call.
        The drainer's eventual result for a cancelled record is
        discarded (``rec.cancelled``); injected hangs are released via
        each record's cancel event."""
        with self._lock:
            recs = [r for r in self._inflight_recs if not r.cancelled]
            for rec in recs:
                rec.cancelled = True
            self.exec_timeouts += 1
        if not recs:
            return
        self.health.record_failure()
        event(_log, "exec_timeout", model=self.model.name,
              age_ms=round(age_s * 1e3, 1), limit_ms=round(limit_s * 1e3, 1),
              windows=len(recs))
        err = TimeoutError(
            f"in-flight batch exceeded exec timeout: age {age_s * 1e3:.0f}"
            f"ms > limit {limit_s * 1e3:.0f}ms; failing the window fast")
        for rec in recs:
            if rec.cancel is not None:
                rec.cancel.set()
            for r in rec.requests:
                if r.span is not None and not r.future.done():
                    r.span.note("exec_timeout",
                                f"age {age_s * 1e3:.0f}ms")
            pending = [r for r in rec.requests if not r.future.done()]
            if pending and self._rescue is not None:
                # replica mode: offer the cohort to a healthy replica
                # before failing anyone (serve/replicas.py bisect-retries
                # it there) — rescue must never raise into the watchdog
                try:
                    if self._rescue(pending, err):
                        continue
                except Exception:  # noqa: BLE001 — rescue is best-effort; fall through to deliver the error
                    pass
            for req in pending:
                if not req.future.done():
                    req.future.set_exception(err)

    # -- observability -----------------------------------------------------

    def health_report(self) -> dict:
        now = time.monotonic()
        rep = self.health.report(now)
        t, d = self._thread, self._drainer
        # external-batcher replicas have no batcher thread of their own
        rep["batcher_alive"] = None if self.external_batcher else \
            bool(t is not None and t.is_alive())
        rep["drainer_alive"] = bool(d is not None and d.is_alive()) \
            if self.pipeline_depth > 1 else None
        rep["accepting"] = self._accepting
        # what /v1/healthz keys 503 on: a single engine serves only
        # while fully OK; a ReplicatedEngine overrides this to "any
        # replica not DEAD" (docs/SERVING.md)
        rep["can_serve"] = rep["state"] == "ok"
        rep["placement"] = self.model.placement_desc() \
            if hasattr(self.model, "placement_desc") else None
        # mesh advertisement for the gateway's fleet table: how this
        # engine's weights are laid out and how much per-chip HBM is
        # left (None on backends without memory_stats, i.e. CPU)
        rep["mesh_shape"] = self.model.mesh_shape() \
            if hasattr(self.model, "mesh_shape") else None
        rep["param_shard_bytes"] = self.model.param_bytes() \
            if hasattr(self.model, "param_bytes") else None
        rep["hbm_headroom_bytes"] = device_hbm_headroom()
        with self._lock:
            rep["inflight"] = self._inflight
            rep["batch_failures"] = self.batch_failures
            rep["retry_executions"] = self.retry_executions
            rep["quarantined"] = self.quarantined
            rep["exec_timeouts"] = self.exec_timeouts
            rep["shed_shutdown"] = self.shed_shutdown
            done = self._last_done
        rep["last_batch_age_s"] = round(now - done, 4) \
            if done is not None else None
        if self.faults.enabled:
            rep["faults"] = self.faults.stats()
        return rep

    @property
    def queue_depth(self) -> int:
        """Requests awaiting batch formation right now — the edge QoS
        pressure signal (``Queue.qsize`` is already thread-safe)."""
        return self._queue.qsize()

    def _prune_busy_locked(self, now: float) -> None:
        horizon = now - self.occupancy_window_s
        while self._busy_events and self._busy_events[0][0] < horizon:
            self._busy_events.popleft()

    def _occupancy_locked(self, now: float) -> float:
        self._prune_busy_locked(now)
        busy = sum(dt for _, dt in self._busy_events)
        return min(1.0, max(0.0, busy / self.occupancy_window_s))

    def occupancy(self) -> float:
        """Fraction of the trailing ``occupancy_window_s`` spent in
        batch execution — the compute-stage duty cycle.  This is the
        throughput-workload pressure signal (deploy/autoscale.py): a
        saturated batchy engine shows occupancy →1 with queue depth 0,
        exactly the state queue-based pressure can't see."""
        with self._lock:
            return self._occupancy_locked(time.monotonic())

    def stats(self) -> dict:
        now = time.monotonic()
        with self._lock:
            span = None
            if self._first_dispatch is not None and \
                    self._last_done is not None:
                span = self._last_done - self._first_dispatch
            out = {"model": self.model.name,
                   "version": getattr(self.model, "serve_version", None),
                   "submitted": self.submitted,
                   "served": self.served,
                   "batches": self.batches,
                   "compiles": self.compiles,
                   "padded_images": self.padded_images,
                   "queue_depth": self._queue.qsize(),
                   "buckets": list(self.buckets),
                   "compiled_buckets": sorted(self._executables),
                   "max_wait_ms": self.max_wait_s * 1e3,
                   "workload": getattr(
                       getattr(self.model, "workload", None),
                       "verb", None),
                   "wire_dtype": str(self.wire_dtype),
                   "infer_dtype": getattr(self.model, "infer_dtype",
                                          "float32"),
                   # the served weights' byte footprint (int8 models
                   # report the true quantized size — bench.py's
                   # weight-HBM pricing and the /metrics gauge).
                   # param_bytes is PER-CHIP on mesh views: a leaf
                   # split over ``model`` prices its addressable shard
                   "weight_hbm_bytes": self.model.param_bytes()
                   if hasattr(self.model, "param_bytes") else None,
                   "param_shard_bytes": self.model.param_bytes()
                   if hasattr(self.model, "param_bytes") else None,
                   "param_global_bytes": self.model.param_global_bytes()
                   if hasattr(self.model, "param_global_bytes")
                   else None,
                   "mesh_shape": self.model.mesh_shape()
                   if hasattr(self.model, "mesh_shape") else None,
                   "pipeline": {
                       "depth": self.pipeline_depth,
                       "inflight": self._inflight,
                       "max_inflight": self.max_inflight,
                       "bulk_transfers": self.bulk_transfers,
                       "bulk_transfer_bytes": self.bulk_transfer_bytes,
                       "h2d_transfers": self.h2d_transfers,
                       "h2d_bytes": self.h2d_bytes,
                       "h2d_bytes_by_bucket": dict(
                           self.h2d_bytes_by_bucket),
                       "d2h_bytes": self.d2h_bytes,
                       "d2h_bytes_by_bucket": dict(
                           self.d2h_bytes_by_bucket),
                       # host proxy: fraction of the first-dispatch →
                       # last-drain span with an empty in-flight window
                       "device_idle_frac": (
                           round(self._idle_s / span, 4)
                           if span and span > 0 else None),
                       # rolling compute duty cycle (trailing window) —
                       # the batch-tier/autoscaler signal
                       "occupancy": round(
                           self._occupancy_locked(now), 4)}}
        out["pipeline"]["staging"] = self.staging.stats()
        out["latency"] = self.latency.percentiles()
        # full histogram state rides along so upstream aggregators (the
        # gateway) can LatencyHistogram.merge real distributions instead
        # of eyeballing per-backend percentiles
        out["latency_hist"] = self.latency.state_dict()
        out["img_per_sec"] = self.throughput.images_per_sec
        out["admission"] = self.admission.stats()
        out["health"] = self.health_report()
        out["mfu"] = self.mfu.report()
        out["trace"] = self.tracer.summary()
        return out
