"""Workload adapters: everything the serving tier must know per model
*kind*, in one object per verb.

Through PR 14 the serving stack special-cased exactly two verbs —
``/v1/classify`` and ``/v1/detect`` — in ten different places (HTTP
routing, response builders, shadow comparison, gateway allowlists,
bench input synthesis).  The zoo is bigger than that: Stacked Hourglass
pose and DCGAN/CycleGAN generation train fine (tasks/pose.py,
tasks/gan.py) but had no serving path.  This module replaces the
hardcoded pair with a registry of ``Workload`` adapters; making the
next zoo model servable means writing one adapter here instead of
touching ten files.

Each adapter declares:

- ``verb`` — the route segment (``/v1/<verb>`` and
  ``/v1/models/{name}/<verb>``), and the key in ``WORKLOADS``;
- ``slo`` — the workload's service class (deadline + queue bound),
  consumed by the CLI when it builds each model's
  ``AdmissionController`` and used as the default ``deadline_ms`` when
  a client omits one;
- ``serving_input_shape`` / ``wire_dtype_for`` — the input codec.
  Generative latent-in models invert the usual contract: the input is
  a float latent vector (never a uint8 image), so DCGAN forces a
  float32 wire regardless of the CLI's uint8 default;
- ``decode`` — optional body → input-array decode (DCGAN reads
  ``latent``/``seed`` from the JSON body); returning None defers to
  the generic image decode in serve/http.py;
- ``make_epilogue`` — an optional *traced* output transform fused into
  the compiled bucket programs (serve/registry.py), mirroring the PR 5
  normalize *prologue* on the output side.  Pose decodes heatmaps to
  keypoints on device (D2H moves K coordinate pairs per image instead
  of an H×W×K heatmap stack); generate encodes the generator's [-1,1]
  float output to uint8 on device, so the bulk ``device_get`` moves
  1 byte/pixel and returns wire-ready bytes — the PR 5/13 uint8-wire
  win applied in reverse, to the output-dominated traffic shape;
  detect fuses the whole detection epilogue (decode → threshold →
  top-k → class-wise NMS for YOLO, peak decode for CenterNet) so D2H
  ships K fixed-size boxes per image instead of the dense multi-scale
  pyramid — ≥100× fewer bytes at 416²;
- ``respond`` — row → JSON response schema (the bodies that used to
  live in ``_Handler._classify`` / ``_detect``);
- ``cacheable`` — per-workload response-cache size guard: generated
  images are large but highly cacheable (same latent → same image),
  so generate gets a bigger per-entry allowance;
- ``agree`` — the shadow/canary agreement metric for this workload
  (serve/models.py ``_compare_shadow``): top-1 for classify, PCK-style
  keypoint proximity for pose, output-digest equality for generate,
  greedy IoU≥0.5 class-matched pairing fraction (the mAP proxy) for
  detect; None means "not comparable" (Shed/Quarantined rows, dense
  host-path pyramids).

Import discipline: this module is imported by the gateway and edge for
route tables, so module import stays stdlib-only — numpy/jax/tasks
imports are deferred into the methods that need them.
"""

from __future__ import annotations


class SLO:
    """A workload's service class: the default per-request deadline and
    the per-model admission queue bound.

    Deadlines are generous on purpose — they are the *default* for
    clients that omit ``deadline_ms``, and the first request after a
    (re)load pays bucket compilation, which takes tens of seconds on a
    CPU host.  The queue bound caps the CLI's ``--max-queue`` per
    workload (``bound_queue``): generative batches occupy the device
    ~an order of magnitude longer than classify batches, so a shorter
    queue sheds earlier instead of stacking up deadline misses."""

    def __init__(self, name: str, deadline_ms: float, max_queue: int):
        self.name = name
        self.deadline_ms = float(deadline_ms)
        self.max_queue = int(max_queue)

    def bound_queue(self, requested: int) -> int:
        """The admission queue bound: the operator's ``--max-queue``
        capped by this workload's class."""
        return min(int(requested), self.max_queue)

    def describe(self) -> dict:
        return {"class": self.name, "deadline_ms": self.deadline_ms,
                "max_queue": self.max_queue}


class Workload:
    """Base adapter: the image-in defaults every subclass overrides
    piecemeal.  Stateless by design — one shared instance per verb
    serves every model and every thread (nothing to lock)."""

    verb = ""
    slo = SLO("interactive", deadline_ms=30_000.0, max_queue=256)
    #: per-entry response-cache allowance (bytes); ``cacheable`` guard
    cacheable_bytes = 256 * 1024

    def serving_input_shape(self, cfg, model=None) -> tuple:
        """Per-example input shape for this (cfg, model) — delegates to
        core/restore so the restore-time init and the serving buffers
        can never disagree."""
        from deep_vision_tpu.core.restore import serving_input_shape
        return serving_input_shape(cfg, model)

    def wire_dtype_for(self, cfg, requested: str) -> str:
        """The wire dtype actually used, given what the operator asked
        for.  Image-in workloads honor the request."""
        return requested

    def output_wire(self, cfg) -> str | None:
        """Wire dtype of the *output* side, when the workload ships an
        output payload (generate's uint8 image encode); None for
        workloads whose outputs are small host-side decodes."""
        return None

    def decode(self, body: dict, model):
        """Body → one input array in the model's wire dtype, or None to
        defer to the generic image decode (serve/http._decode_pixels).
        Raise ValueError for malformed payloads (the edge maps it to a
        400)."""
        return None

    def make_epilogue(self, model):
        """Traced output transform fused into the bucket programs after
        ``_f32_outputs``, or None for no epilogue.  ``model`` is the
        ServingModel (dtype/attr introspection only — the returned fn
        must close over nothing that changes across reloads)."""
        return None

    def decode_manifest_item(self, item: dict, model):
        """One batch-job manifest entry → input array (serve/jobs.py).

        The per-verb manifest codec: the workload's own ``decode``
        first (generate accepts ``latent``/``seed`` entries), then the
        generic image decode — the same ``pixels``/``image_b64`` schema
        an interactive body uses, so a manifest is just a list of
        request bodies.  Raises ValueError on a malformed entry (the
        scheduler records it as that item's error result; one bad entry
        never poisons its shard)."""
        if not isinstance(item, dict):
            raise ValueError(
                f"manifest entry must be an object, got "
                f"{type(item).__name__}")
        x = self.decode(item, model)
        if x is not None:
            return x
        # deferred import: http imports this module at its top level
        from deep_vision_tpu.serve.http import ServeError, _decode_pixels

        try:
            return _decode_pixels(item, model)
        except ServeError as e:
            raise ValueError(str(e)) from e

    def respond(self, model, body: dict, row) -> dict:
        raise NotImplementedError

    def cacheable(self, nbytes: int) -> bool:
        """Whether a serialized 200 of ``nbytes`` may enter the
        response cache — the per-workload size guard."""
        return int(nbytes) <= self.cacheable_bytes

    def agree(self, primary_row, shadow_row):
        """Shadow/canary agreement verdict: True/False, or None when
        the rows aren't comparable (counted as discarded, like the
        pre-workload behavior for detection pytrees)."""
        return None

    def cascade_rule(self):
        """This verb's :class:`CascadeWorkloadRule`, or None when the
        verb cannot cascade (no escalation signal on its rows — pose
        and generate today).  serve/cascade.py resolves the rule from
        the BIG tier's workload at router construction."""
        return None

    def describe(self) -> dict:
        return {"verb": self.verb, "slo": self.slo.describe(),
                "cacheable_bytes": self.cacheable_bytes}


class CascadeWorkloadRule:
    """How one verb's rows drive the cascade (serve/cascade.py).

    ``signal(row)`` extracts the escalation signal from a cheap tier's
    row: ``(class, confidence)`` where ``confidence`` ∈ [0, 1] feeds
    the hop's AgreementHistogram bucket and threshold comparison, and
    ``class`` keys the optional per-class threshold axis (None = no
    class, pooled threshold only).  ``(None, None)`` means the row
    carries no signal (Shed/Quarantined, dense host rows) — the router
    never guesses and escalates.  ``agree(tier_row, big_row)`` scores
    one dual-run calibration sample: True/False, or None for
    not-comparable (discarded).  Stateless, like the adapters."""

    def signal(self, row) -> tuple:
        raise NotImplementedError

    def agree(self, tier_row, big_row):
        raise NotImplementedError


class ClassifyWorkload(Workload):
    verb = "classify"
    slo = SLO("interactive", deadline_ms=30_000.0, max_queue=256)

    def make_epilogue(self, model):
        """Confidence reduction fused on DEVICE for cascade front
        tiers: softmax + top-K in the bucket program, so the bulk D2H
        moves 3·K scalars per image instead of the dense logits and the
        cascade router's escalation decision reads ``topk_prob[0]`` /
        ``topk_class[0]`` off the already-fetched row.  Gated on the
        model's ``cascade_topk`` attribute (set by cli.serve for the
        front tier only; copied across reloads by models._load_model),
        so plain classify serving keeps its dense-logits rows and
        escalated answers stay bit-identical to big-only serving."""
        k = int(getattr(model, "cascade_topk", 0) or 0)
        if k <= 0:
            return None
        import jax
        import jax.numpy as jnp

        def post(out):  # dvtlint: traced
            logits = out
            kk = min(k, logits.shape[-1])
            probs = jax.nn.softmax(logits, axis=-1)
            top_p, top_i = jax.lax.top_k(probs, kk)
            top_l = jnp.take_along_axis(logits, top_i, axis=-1)
            return {"topk_class": top_i.astype(jnp.int32),
                    "topk_prob": top_p.astype(jnp.float32),
                    "topk_logit": top_l.astype(jnp.float32)}

        return post

    @staticmethod
    def top1(row):
        """``(class, prob)`` of a classify row — dense logits OR the
        confidence-epilogue dict — or ``(None, None)`` for rows with no
        top-1 (Shed/Quarantined, foreign shapes).  The one place that
        knows both row shapes; the cascade router and ``agree`` both
        route through it so the two shapes always compare."""
        import numpy as np

        if isinstance(row, dict):
            try:
                cls = np.asarray(row["topk_class"]).reshape(-1)
                prob = np.asarray(row["topk_prob"]).reshape(-1)
            except (KeyError, TypeError, ValueError):
                return None, None
            if cls.size == 0 or prob.size == 0:
                return None, None
            return int(cls[0]), float(prob[0])
        if isinstance(row, np.ndarray) and row.ndim >= 1 and row.size:
            logits = row.astype(np.float64)
            z = np.exp(logits - logits.max())
            c = int(np.argmax(logits))
            return c, float(z[c] / z.sum())
        return None, None

    def respond(self, model, body: dict, row) -> dict:
        import numpy as np

        if isinstance(row, dict):
            # confidence-epilogue row: top-K already reduced on device
            cls = np.asarray(row["topk_class"]).reshape(-1)
            prob = np.asarray(row["topk_prob"]).reshape(-1)
            logit = np.asarray(row["topk_logit"]).reshape(-1)
            k = min(int(body.get("top_k", 5)), cls.shape[0])
            return {"model": model.name,
                    "top": [{"class": int(cls[j]),
                             "prob": float(prob[j]),
                             "logit": float(logit[j])}
                            for j in range(k)]}
        logits = np.asarray(row)
        k = min(int(body.get("top_k", 5)), logits.shape[-1])
        top = np.argsort(logits)[-k:][::-1]
        z = np.exp(logits - logits.max())
        probs = z / z.sum()
        return {"model": model.name,
                "top": [{"class": int(c), "prob": float(probs[c]),
                         "logit": float(logits[c])} for c in top]}

    def agree(self, primary_row, shadow_row):
        p, _ = self.top1(primary_row)
        s, _ = self.top1(shadow_row)
        if p is None or s is None:
            return None
        return p == s

    def cascade_rule(self):
        return _ClassifyCascadeRule()


class DetectWorkload(Workload):
    """Both detection families (YOLOv3 multi-scale heads, CenterNet
    heatmap peaks) behind one verb, decoded ON DEVICE by default: the
    fused epilogue traces decode → score threshold → pre-NMS top-k →
    class-wise static-shape NMS (tasks/detection.postprocess /
    tasks/centernet.decode_detections) down to a fixed-size
    ``{boxes (K,4), scores (K), classes (K), valid (K)}`` per image,
    so the drainer's bulk D2H ships ~K·28 B instead of the dense
    multi-scale pyramid (≥100× fewer bytes at 416²).  ``respond`` is a
    trim-by-valid formatter over that row; the ``detect_decode="host"``
    knob keeps the dense pyramid on the wire (the A/B baseline) and
    routes the SAME decode math host-side, so both paths answer
    identically.  Small canonical payloads also make detect responses
    practically cacheable (the inherited 256 KiB guard now always
    passes: K=100 rows serialize to a few KB)."""

    verb = "detect"
    slo = SLO("interactive", deadline_ms=30_000.0, max_queue=256)
    #: shadow agreement (the mAP proxy): greedy same-class pairing at
    #: IoU ≥ ``iou_match`` over the valid rows of both sides; agreement
    #: is matched / max(n_primary, n_shadow) and must reach
    #: ``min_match_frac`` for the candidate to count as agreeing
    iou_match = 0.5
    min_match_frac = 0.6
    #: fallback response threshold when the client omits one (the
    #: pre-epilogue default, kept for response-schema continuity)
    default_score_threshold = 0.3

    @staticmethod
    def knobs(model) -> tuple:
        """The model's compiled decode knobs ``(top_k, score floor,
        iou threshold)`` — ServingModel attributes threaded from
        ``registry.load_checkpoint`` / cli.serve ``--detect-*`` flags
        and copied across reloads by models._load_model, with the same
        defaults for bare models (tests, bench)."""
        return (int(getattr(model, "detect_topk", 100) or 100),
                float(getattr(model, "detect_score_threshold", 0.05)),
                float(getattr(model, "detect_iou_threshold", 0.5)))

    @staticmethod
    def nms_knobs(model) -> tuple:
        """The suppression-variant knobs ``(soft_nms, soft_sigma,
        max_per_class)`` — same attribute-threading contract as
        ``knobs`` (``--detect-soft-nms`` / ``--detect-soft-sigma`` /
        ``--detect-max-per-class``), defaults keeping the reference
        hard-NMS behavior.  Kept separate so ``knobs``'s 3-tuple shape
        stays stable for existing callers."""
        return (str(getattr(model, "detect_soft_nms", "off") or "off"),
                float(getattr(model, "detect_soft_sigma", 0.5)),
                int(getattr(model, "detect_max_per_class", 0) or 0))

    def make_epilogue(self, model):
        """Detection decode fused into the bucket programs, family-
        switched on the model's task: YOLO traces the full
        decode→threshold→top-k→class-wise-NMS postprocess; CenterNet
        traces its NMS-free 3×3-peak + top-K decode (boxes normalized
        to [0,1] to match the YOLO contract).  The compiled score
        threshold is a FLOOR: per-request thresholds ≥ the floor trim
        host-side in ``respond`` — greedy NMS selects in descending
        score order and lower-scored boxes never suppress higher ones,
        so NMS-at-floor-then-trim keeps exactly the boxes NMS-at-the-
        higher-threshold would.  Skipped when ``detect_decode`` was
        pinned to "host" (the A/B baseline and D2H-comparison knob)."""
        if getattr(model, "detect_decode", "device") != "device":
            return None
        k, floor, iou = self.knobs(model)
        soft, sigma, per_cls_k = self.nms_knobs(model)
        num_classes = int(model.num_classes)
        if getattr(model, "task", "") == "centernet":
            import jax.numpy as jnp

            from deep_vision_tpu.tasks.centernet import decode_detections

            def post(out):  # dvtlint: traced
                # per-stack (heat, wh, offset) tuples; serve decodes
                # only the last (most refined) stack, like pose
                heat, wh, offset = out[-1]
                grid = heat.shape[1]
                boxes, scores, cls = decode_detections(
                    heat, wh, offset, k=k)
                return {"boxes": boxes / grid, "scores": scores,
                        "classes": cls.astype(jnp.int32),
                        "valid": (scores >= floor).astype(jnp.float32)}

            return post
        import jax.numpy as jnp

        from deep_vision_tpu.tasks.detection import postprocess

        def post(out):  # dvtlint: traced
            boxes, scores, classes, valid = postprocess(
                out, num_classes, max_outputs=k, iou_threshold=iou,
                score_threshold=floor, class_aware=True,
                soft_nms=soft, soft_sigma=sigma,
                max_per_class=per_cls_k)
            return {"boxes": boxes, "scores": scores,
                    "classes": classes.astype(jnp.int32),
                    "valid": valid}

        return post

    def _decoded(self, model, row) -> dict:
        """One image's epilogue-shaped detection dict whatever the row
        shape: device-decoded dict rows pass through; dense host rows
        (``detect_decode="host"``) decode through the SAME math the
        epilogue traces, with the same knobs, so the two paths answer
        byte-identically."""
        if isinstance(row, dict):
            return row
        import jax
        import numpy as np

        k, floor, iou = self.knobs(model)
        # row is one image's head outputs; the decoders want a batch dim
        outs = jax.tree_util.tree_map(lambda a: a[None], row)
        if getattr(model, "task", "") == "centernet":
            from deep_vision_tpu.tasks.centernet import decode_detections

            heat, wh, offset = outs[-1]
            grid = heat.shape[1]
            boxes, scores, cls = decode_detections(heat, wh, offset, k=k)
            scores = np.asarray(scores[0])
            return {"boxes": np.asarray(boxes[0]) / grid,
                    "scores": scores,
                    "classes": np.asarray(cls[0]),
                    "valid": (scores >= floor).astype(np.float32)}
        from deep_vision_tpu.tasks.detection import postprocess

        soft, sigma, per_cls_k = self.nms_knobs(model)
        boxes, scores, classes, valid = postprocess(
            outs, model.num_classes, max_outputs=k, iou_threshold=iou,
            score_threshold=floor, class_aware=True,
            soft_nms=soft, soft_sigma=sigma, max_per_class=per_cls_k)
        return {"boxes": np.asarray(boxes[0]),
                "scores": np.asarray(scores[0]),
                "classes": np.asarray(classes[0]),
                "valid": np.asarray(valid[0])}

    def respond(self, model, body: dict, row) -> dict:
        import numpy as np

        dec = self._decoded(model, row)
        boxes = np.asarray(dec["boxes"])
        scores = np.asarray(dec["scores"]).reshape(-1)
        classes = np.asarray(dec["classes"]).reshape(-1)
        valid = np.asarray(dec["valid"]).reshape(-1)
        _, floor, _ = self.knobs(model)
        # the compiled floor bounds the request threshold from below:
        # boxes under the floor never survived NMS, so a lower request
        # threshold can't resurrect them
        thr = max(float(body.get(
            "score_threshold", self.default_score_threshold)), floor)
        keep = np.nonzero((valid > 0) & (scores >= thr))[0]
        return {"model": model.name, "num_detections": int(len(keep)),
                "detections": [
                    {"box": boxes[j].round(4).tolist(),
                     "score": float(scores[j]),
                     "class": int(classes[j])} for j in keep]}

    @staticmethod
    def _agree_rows(row):
        """(valid boxes, valid classes) of an epilogue-shaped row, or
        None when the row isn't one (Shed/Quarantined, dense host
        pyramids, foreign shapes) — not comparable, like pre-epilogue
        detect rows."""
        import numpy as np

        if not isinstance(row, dict):
            return None
        try:
            b = np.asarray(row["boxes"], np.float32)
            s = np.asarray(row["scores"], np.float32).reshape(-1)
            c = np.asarray(row["classes"]).reshape(-1).astype(np.int64)
            v = np.asarray(row["valid"], np.float32).reshape(-1)
        except (KeyError, TypeError, ValueError):
            return None
        if b.ndim != 2 or b.shape[-1] != 4 or b.shape[0] != v.shape[0] \
                or s.shape[0] != v.shape[0] or c.shape[0] != v.shape[0]:
            return None
        keep = v > 0
        return b[keep], c[keep]

    def agree(self, primary_row, shadow_row):
        """The detect shadow/canary verdict — the mAP proxy: greedy
        IoU ≥ 0.5 class-matched pairing in primary score order (rows
        arrive score-sorted from the decoders), then the matched
        fraction over max(n_primary, n_shadow) against
        ``min_match_frac``.  Both-empty agrees (a candidate that also
        finds nothing is consistent); non-epilogue rows are not
        comparable (None → discarded)."""
        import numpy as np

        p = self._agree_rows(primary_row)
        s = self._agree_rows(shadow_row)
        if p is None or s is None:
            return None
        pb, pc = p
        sb, sc = s
        n_p, n_s = len(pb), len(sb)
        if n_p == 0 and n_s == 0:
            return True
        if n_p == 0 or n_s == 0:
            return False
        taken = np.zeros(n_s, bool)
        matched = 0
        for i in range(n_p):
            cand = np.nonzero(~taken & (sc == pc[i]))[0]
            if not len(cand):
                continue
            lo = np.maximum(pb[i, :2], sb[cand, :2])
            hi = np.minimum(pb[i, 2:], sb[cand, 2:])
            wh = np.maximum(hi - lo, 0.0)
            inter = wh[:, 0] * wh[:, 1]
            area_p = max(float((pb[i, 2] - pb[i, 0])
                               * (pb[i, 3] - pb[i, 1])), 0.0)
            area_s = np.maximum(sb[cand, 2] - sb[cand, 0], 0.0) * \
                np.maximum(sb[cand, 3] - sb[cand, 1], 0.0)
            iou = inter / np.maximum(area_p + area_s - inter, 1e-9)
            j = int(np.argmax(iou))
            if iou[j] >= self.iou_match:
                taken[cand[j]] = True
                matched += 1
        return matched / max(n_p, n_s) >= self.min_match_frac

    def cascade_rule(self):
        return _DetectCascadeRule(self)


class _ClassifyCascadeRule(CascadeWorkloadRule):
    """Classify cascades on the fused top-1: confidence is the front
    row's ``topk_prob[0]`` (softmax of dense logits for hosts without
    the epilogue), class is its ``topk_class[0]``, and a dual-run
    sample agrees when the two tiers' top-1 classes match — exactly
    the PR 17 behavior, now behind the rule interface."""

    def signal(self, row) -> tuple:
        return ClassifyWorkload.top1(row)

    def agree(self, tier_row, big_row):
        t, _ = ClassifyWorkload.top1(tier_row)
        b, _ = ClassifyWorkload.top1(big_row)
        if t is None or b is None:
            return None
        return t == b


class _DetectCascadeRule(CascadeWorkloadRule):
    """Detect cascades on the device-decoded row: the escalation
    signal is valid-count + max-score — an empty answer (zero valid
    boxes) signals confidence 0.0 so empty scenes escalate unless the
    calibration sample proves the cheap tier reliably agrees on them
    (bin 0 qualifying), and a non-empty answer signals its best box's
    score with that box's class keying the per-class axis.  Dual-run
    agreement is the greedy-IoU mAP proxy (``DetectWorkload.agree``).
    Dense host rows carry no signal → ``(None, None)`` → escalate."""

    def __init__(self, workload):
        self._workload = workload

    def signal(self, row) -> tuple:
        import numpy as np

        if not isinstance(row, dict):
            return None, None
        try:
            s = np.asarray(row["scores"], np.float32).reshape(-1)
            c = np.asarray(row["classes"]).reshape(-1)
            v = np.asarray(row["valid"], np.float32).reshape(-1)
        except (KeyError, TypeError, ValueError):
            return None, None
        if s.shape[0] != v.shape[0] or c.shape[0] != v.shape[0]:
            return None, None
        keep = v > 0
        if not keep.any():
            return None, 0.0
        s, c = s[keep], c[keep]
        j = int(np.argmax(s))
        return int(c[j]), float(min(max(s[j], 0.0), 1.0))

    def agree(self, tier_row, big_row):
        return self._workload.agree(tier_row, big_row)


class PoseWorkload(Workload):
    verb = "pose"
    slo = SLO("interactive", deadline_ms=30_000.0, max_queue=256)
    #: shadow agreement: fraction of keypoints within ``pck_px`` heatmap
    #: pixels that must match for the candidate to count as agreeing
    pck_px = 2.0
    pck_min_frac = 0.8

    def make_epilogue(self, model):
        from deep_vision_tpu.tasks.pose import decode_heatmaps

        def post(out):  # dvtlint: traced
            # stacked-hourglass apply returns the per-stack heatmap
            # tuple; serve only decodes the last (most refined) stack
            hm = out[-1] if isinstance(out, (tuple, list)) else out
            return decode_heatmaps(hm)

        return post

    def respond(self, model, body: dict, row) -> dict:
        import numpy as np

        kp = np.asarray(row["keypoints"])
        sc = np.asarray(row["scores"])
        return {"model": model.name, "space": "heatmap",
                "keypoints": [
                    {"x": float(kp[j, 0]), "y": float(kp[j, 1]),
                     "score": float(sc[j])} for j in range(kp.shape[0])]}

    def agree(self, primary_row, shadow_row):
        import numpy as np

        try:
            pk = np.asarray(primary_row["keypoints"])
            sk = np.asarray(shadow_row["keypoints"])
        except (TypeError, KeyError, IndexError):
            return None  # Shed/Quarantined rows, or a non-pose row
        if pk.shape != sk.shape or pk.ndim < 2:
            return None
        d = np.linalg.norm(pk.astype(np.float32) - sk.astype(np.float32),
                           axis=-1)
        return float((d <= self.pck_px).mean()) >= self.pck_min_frac


class GenerateWorkload(Workload):
    verb = "generate"
    #: generative batches hold the device ~an order of magnitude longer
    #: than classify batches: longer deadline, shorter queue (shed
    #: early instead of stacking deadline misses)
    slo = SLO("batchy", deadline_ms=60_000.0, max_queue=64)
    #: a 256×256×3 uint8 image is ~260 KB once base64'd — allow it
    cacheable_bytes = 2 * 2**20

    def serving_input_shape(self, cfg, model=None) -> tuple:
        from deep_vision_tpu.core.restore import serving_input_shape
        return serving_input_shape(cfg, model)

    def wire_dtype_for(self, cfg, requested: str) -> str:
        """Latent-in models (DCGAN) take a float latent vector — a
        uint8 input wire is meaningless there, so the CLI's uint8
        default is overridden.  Image-in translation (CycleGAN) keeps
        the requested wire (uint8 in → "gan" prologue on device)."""
        if getattr(cfg, "task", "") == "gan_dcgan":
            return "float32"
        return requested

    def output_wire(self, cfg) -> str | None:
        return "uint8"

    def decode(self, body: dict, model):
        """Latent-in decode: ``latent`` (list of floats, exact shape)
        or ``seed`` (int — deterministic host-side standard-normal
        draw, the demo/cache-friendly path; defaults to 0).  Image-in
        generate models return None → generic image decode."""
        if len(model.input_shape) != 1:
            return None
        import numpy as np

        z = body.get("latent")
        if z is None:
            seed = body.get("seed", 0)
            try:
                seed = int(seed)
            except (TypeError, ValueError) as e:
                raise ValueError(f"bad seed: {seed!r}") from e
            rng = np.random.default_rng(seed)
            return rng.standard_normal(model.input_shape).astype(np.float32)
        try:
            x = np.asarray(z, np.float32)
        except (ValueError, TypeError, OverflowError) as e:
            raise ValueError(f"bad latent payload: {e}") from e
        if x.shape != model.input_shape:
            raise ValueError(
                f"latent shape {list(x.shape)} != model input "
                f"{list(model.input_shape)}")
        if not np.isfinite(x).all():
            raise ValueError("latent contains non-finite values (NaN/Inf)")
        return x

    def make_epilogue(self, model):
        """[-1,1] float generator output → uint8 on DEVICE: the D2H
        copy moves 1 byte/pixel (4× fewer bytes than f32 — the exact
        mirror of the PR 5 uint8 input wire) and the host hands back
        wire-ready bytes with zero post-processing.  Skipped when the
        model's ``output_wire`` was pinned to float32 (the A/B baseline
        in tests/test_workloads.py)."""
        if getattr(model, "output_wire", "uint8") == "float32":
            return None
        import jax.numpy as jnp

        def post(out):  # dvtlint: traced
            return jnp.clip(jnp.round((out + 1.0) * 127.5),
                            0.0, 255.0).astype(jnp.uint8)

        return post

    def respond(self, model, body: dict, row) -> dict:
        import base64

        import numpy as np

        img = np.ascontiguousarray(np.asarray(row))
        return {"model": model.name,
                "image": {"b64": base64.b64encode(img.tobytes()).decode(
                              "ascii"),
                          "shape": list(img.shape),
                          "dtype": str(img.dtype)}}

    def agree(self, primary_row, shadow_row):
        import hashlib

        import numpy as np

        comparable = (isinstance(primary_row, np.ndarray)
                      and isinstance(shadow_row, np.ndarray)
                      and primary_row.shape == shadow_row.shape
                      and primary_row.dtype == shadow_row.dtype)
        if not comparable:
            return None

        def dig(a):
            return hashlib.blake2b(np.ascontiguousarray(a).tobytes(),
                                   digest_size=8).hexdigest()

        return dig(primary_row) == dig(shadow_row)


#: verb → the shared adapter instance
WORKLOADS = {w.verb: w for w in (ClassifyWorkload(), DetectWorkload(),
                                 PoseWorkload(), GenerateWorkload())}

#: config task → verb; unknown tasks fall back to classify so a future
#: zoo task degrades to the logits-style default instead of crashing
#: model load (the pre-workload behavior for every non-detection task)
_TASK_TO_VERB = {
    "classification": "classify",
    "detection": "detect",
    "centernet": "detect",
    "pose": "pose",
    "gan_dcgan": "generate",
    "gan_cyclegan": "generate",
}

#: operator lifecycle verbs on /v1/models/{name}/<verb> — NOT workload
#: inference verbs, listed here so every router shares one source
LIFECYCLE_VERBS = ("reload", "promote", "rollback")


def workload_for_task(task: str) -> Workload:
    """The adapter serving models of config ``task``."""
    return WORKLOADS[_TASK_TO_VERB.get(str(task), "classify")]


def infer_verbs() -> tuple:
    """Every inference verb, sorted — the route allowlist for the edge
    and the gateway (unknown verbs 404 with this list in the body)."""
    return tuple(sorted(WORKLOADS))


def infer_paths() -> tuple:
    """The canonical ``/v1/<verb>`` inference routes."""
    return tuple(f"/v1/{v}" for v in infer_verbs())
