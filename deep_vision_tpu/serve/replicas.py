"""Engine replication: one admission queue, N devices, one process.

PRs 1–3 built a batcher/pipeline/fault-plane stack that drives exactly
one device, leaving the other 7/8 of a pod slice idle under inference
load.  ``ReplicatedEngine`` scales that stack across every local device
the way Clipper-style replica scheduling does (NSDI'17) — without
changing the per-device execution path at all:

  one queue      ``submit`` feeds a single admission-controlled queue
                 (the shed estimate divides its exec term by the number
                 of routable replicas, admission.py);
  one batcher    a shared router thread forms cohorts exactly like the
                 single-engine batcher (first request + drain window)
                 — batch formation semantics are identical at any
                 replica count;
  N replicas     one ``BatchingEngine`` per device in external-batcher
                 mode: its OWN device copy of the params (``device_put``
                 once per device via ``registry.for_device``, at build
                 — never per batch), its OWN per-bucket AOT compiles
                 pinned to its device, its OWN staging pool, pipeline
                 window, drainer, and watchdog (PR 3 supervision is
                 per-replica);
  routing        each formed cohort goes to the replica with the least
                 outstanding work — (in-flight + forming batches) × the
                 bucket's exec EWMA — with a round-robin tie-break so
                 an idle fleet still spreads (and warms every replica's
                 pipeline) instead of piling onto replica 0.

Failure semantics (docs/SERVING.md "Multi-device serving"):

  * a replica's watchdog fast-fails its stuck window as before, but in
    replica mode the still-pending requests are first OFFERED to a
    healthy replica (``rescue`` hook) and bisect-retried there — the
    caller sees a served result, not a TimeoutError;
  * a replica that goes DEAD (restart budget exhausted, consecutive
    failures) is masked out of routing and out of the admission
    divisor; the supervisor EVACUATES its in-flight cohorts onto a
    healthy replica, so killing a replica mid-load loses zero admitted
    requests (poison quarantines excepted);
  * ``/v1/healthz`` reports per-replica state and answers 503 only when
    NO replica can serve (all DEAD, or the router's restart budget is
    spent) — a degraded replica drains, it doesn't take the fleet down.

The replica set is ELASTIC (PR 11): ``add_replica()`` spawns a new
per-device view on a spare local device and opens it to routing;
``remove_replica(drain_deadline=)`` masks a slot out of routing and the
admission divisor, drains its in-flight cohorts (evacuating stragglers
onto a healthy peer — scale-down never drops admitted work), then stops
the engine and releases the view's device weights (the ``WeightCache``
entry is dropped with them).  Slots are append-only: a removed replica
is masked, never popped, so rescue closures and routing counters keep
stable indices.  ``deploy/autoscale.py`` drives both ends from the
admission controller's observed load.

The big-batch path is separate: ``--shard-batches`` builds ONE engine
over ``registry.for_mesh`` so a single padded mega-batch spans the data
axis of every chip (``engine.sharded_buckets`` keeps buckets divisible
by the mesh).  Replication parallelizes many small batches; sharding
parallelizes one large one.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future

import numpy as np

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.core.metrics import LatencyHistogram
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.obs.mfu import MfuMeter
from deep_vision_tpu.obs.trace import Tracer
from deep_vision_tpu.serve.admission import AdmissionController, Shed
from deep_vision_tpu.serve.engine import (
    BatchingEngine,
    _Request,
    device_hbm_headroom,
)
from deep_vision_tpu.serve.faults import FaultPlane, KillThread
from deep_vision_tpu.serve.health import DEAD, OK, EngineHealth

_log = get_logger("dvt.serve.replicas")


def local_devices(limit: int | None = None) -> list:
    """The local device set serving replicates over (``--serve-devices``
    caps it; asking for more than exist is an operator error, not a
    silent truncation)."""
    import jax

    devs = jax.local_devices()
    if limit is not None:
        n = int(limit)
        if n < 1:
            raise ValueError(f"--serve-devices {n}: need at least 1")
        if n > len(devs):
            raise ValueError(
                f"--serve-devices {n}: only {len(devs)} local "
                f"device(s) present ({devs[0].platform})")
        devs = devs[:n]
    return devs


class ReplicatedEngine:
    """N per-device ``BatchingEngine`` replicas behind one queue.

    Drop-in for a single engine everywhere the serving stack touches
    one: ``start/stop/submit/infer/warmup/stats/health_report`` and the
    ``faults``/``admission`` attributes match ``BatchingEngine``.
    Extra engine knobs (exec timeouts, retry budgets, state thresholds)
    pass through to every replica via ``**engine_kwargs``.
    """

    def __init__(self, model, *, devices: list | None = None,
                 max_batch: int = 32, max_wait_ms: float = 5.0,
                 buckets: list[int] | None = None,
                 admission: AdmissionController | None = None,
                 pipeline_depth: int = 2,
                 faults: FaultPlane | None = None,
                 watchdog_interval_s: float = 0.05,
                 restart_budget: int = 3,
                 tracer: Tracer | None = None,
                 **engine_kwargs):
        self.devices = list(devices) if devices is not None \
            else local_devices()
        if len(self.devices) > 1 and not hasattr(model, "for_device"):
            raise ValueError(
                f"model '{model.name}' ({type(model).__name__}) has no "
                f"per-device view (for_device) — StableHLO blobs serve "
                f"single-device; replicate from the checkpoint path")
        self.model = model
        self.max_wait_s = max_wait_ms / 1e3
        self.admission = admission or AdmissionController(
            max_wait_ms=max_wait_ms)
        self.faults = faults or FaultPlane.from_env()
        self.watchdog_interval_s = watchdog_interval_s
        self.restart_budget = restart_budget
        # the ROUTER's own health (each replica owns its machine); its
        # heartbeats/restarts feed the aggregate health_report
        self.health = EngineHealth()
        # one tracer (one ring, one slow sampler) for the whole fleet —
        # a request's span crosses replica boundaries on rescue, so the
        # trace state must not be per-replica
        self.tracer = tracer or Tracer()
        self.replicas: list[BatchingEngine] = []
        # replica-construction kwargs, retained so add_replica() builds
        # later replicas identically to the originals
        self._replica_kwargs = dict(
            max_batch=max_batch, max_wait_ms=max_wait_ms,
            buckets=buckets, pipeline_depth=pipeline_depth,
            watchdog_interval_s=watchdog_interval_s,
            restart_budget=restart_budget, **engine_kwargs)
        for i, dev in enumerate(self.devices):
            self.replicas.append(self._build_replica(i, dev))
        self.buckets = self.replicas[0].buckets
        # replicas added later must reuse the resolved bucket ladder,
        # not re-derive it — _bucket_for must agree across the fleet
        self._replica_kwargs["buckets"] = list(self.buckets)
        self.max_batch = self.replicas[0].max_batch
        self.pipeline_depth = self.replicas[0].pipeline_depth
        # every replica view shares the source model's wire format
        self.wire_dtype = self.replicas[0].wire_dtype
        # DEAD replicas drop out of the shed estimate as they drop out
        # of routing; retired slots drop out of both gauges
        self.admission.set_free_replicas(self._free_replicas)
        self.admission.set_live_replicas(self.live_replicas)
        self._queue: queue.Queue[_Request] = queue.Queue()
        self._lock = new_lock("serve.replicas.ReplicatedEngine._lock")
        self._stop = threading.Event()
        self._accepting = False
        self._forming = 0
        self._thread: threading.Thread | None = None
        self._supervisor: threading.Thread | None = None
        self._rr = 0  # round-robin tie-break cursor
        self._evacuated = [False] * len(self.replicas)
        # slots are append-only (rescue closures and routing counters
        # are index-keyed): a removed replica is MASKED here, never
        # popped, so indices stay stable for the life of the engine
        self._retired = [False] * len(self.replicas)  # guarded-by: _lock
        self.submitted = 0  # guarded-by: _lock
        self.shed_shutdown = 0  # guarded-by: _lock
        self.routed_batches = [0] * len(self.replicas)  # guarded-by: _lock
        self.rescued_requests = 0  # guarded-by: _lock
        self.evacuations = 0  # guarded-by: _lock
        self.shed_all_dead = 0  # guarded-by: _lock
        self.replicas_added = 0  # guarded-by: _lock
        self.replicas_removed = 0  # guarded-by: _lock

    def _build_replica(self, i: int, dev) -> BatchingEngine:
        view = self.model.for_device(dev) \
            if hasattr(self.model, "for_device") else self.model
        return BatchingEngine(
            view, admission=self.admission, faults=self.faults,
            external_batcher=True,
            rescue=(lambda pending, err, _i=i:
                    self._rescue_from(_i, pending, err)),
            tracer=self.tracer, **self._replica_kwargs)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "ReplicatedEngine":
        if not self._accepting:
            self._stop.clear()
            self.health.revive()
            self._evacuated = [False] * len(self.replicas)
            for i, rep in enumerate(self.replicas):
                if not self._retired[i]:
                    rep.start()
            self._thread = threading.Thread(
                target=self._route_loop,
                name=f"router-{self.model.name}", daemon=True)
            self._thread.start()
            self._supervisor = threading.Thread(
                target=self._supervise_loop,
                name=f"supervisor-{self.model.name}", daemon=True)
            self._supervisor.start()
            self._accepting = True
        return self

    def stop(self, timeout: float = 5.0,
             drain_deadline: float | None = None):
        """Same contract as ``BatchingEngine.stop``: submits fail fast
        immediately; with ``drain_deadline`` admitted work finishes
        across ALL replicas first."""
        was_running = self._accepting
        self._accepting = False
        if drain_deadline is not None and was_running:
            t_end = time.monotonic() + drain_deadline
            while time.monotonic() < t_end:
                if self._queue.qsize() == 0 and self._forming == 0 \
                        and self.total_inflight() == 0:
                    break
                time.sleep(0.005)
        self._stop.set()
        self.faults.cancel.set()
        if self._supervisor is not None:
            self._supervisor.join(timeout)
            self._supervisor = None
        if self._thread is not None:
            self._thread.join(timeout)
            self._thread = None
        for rep in self.replicas:
            rep.stop(timeout)
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                break
            if not req.future.done():
                req.future.set_result(Shed("shutdown", "engine stopped"))

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    def warmup(self, buckets: list[int] | None = None):
        for i, rep in enumerate(self.replicas):
            if not self._retired[i]:
                rep.warmup(buckets)

    # -- request path ------------------------------------------------------

    def total_inflight(self) -> int:
        return sum(r._inflight + r._forming for r in self.replicas)

    def submit(self, image, deadline_ms: float | None = None,
               span=None) -> Future:
        fut: Future = Future()
        # same ownership contract as BatchingEngine.submit: borrowed
        # spans are marked here, engine-created spans self-seal via the
        # future's done-callback
        if span is None and self.tracer.enabled:
            span = self.tracer.start()
            fut.add_done_callback(
                lambda _f, _s=span: self.tracer.finish(_s))
        if not self._accepting:
            with self._lock:
                self.submitted += 1
                self.shed_shutdown += 1
            if span is not None:
                span.note("shed", "shutdown")
            fut.set_result(Shed(
                "shutdown", "engine is not accepting requests "
                            "(stopped or not started)"))
            return fut
        now = time.monotonic()
        deadline = now + deadline_ms / 1e3 if deadline_ms is not None \
            else None
        with self._lock:
            self.submitted += 1
        depth = self._queue.qsize()
        shed = self.admission.admit(
            depth, deadline, now,
            bucket=self.replicas[0]._bucket_for(
                min(depth + 1, self.max_batch)),
            inflight=self.total_inflight())
        if shed is not None:
            if span is not None:
                span.note("shed", shed.reason)
            fut.set_result(shed)
            return fut
        self.admission.record_admit()
        poison = self.faults.mark_poison() if self.faults.enabled else False
        if span is not None:
            span.mark("admit")
        self._queue.put(_Request(np.asarray(image, self.wire_dtype),
                                 deadline, now, fut, poison, span))
        return fut

    def infer(self, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0, span=None):
        return self.submit(image, deadline_ms, span=span).result(timeout)

    # -- shared batcher + router -------------------------------------------

    def _route_loop(self):  # dvtlint: hot
        """Identical cohort formation to the single-engine batcher
        (engine._loop), then a routing decision instead of a local
        dispatch.  Dying here is survivable: the supervisor restarts
        the router within ``restart_budget``."""
        try:
            while not self._stop.is_set():
                self.health.beat("batcher")
                if self.faults.enabled:
                    self.faults.inject("batcher", stop=self._stop)
                try:
                    first = self._queue.get(timeout=0.05)
                except queue.Empty:
                    continue
                if first.span is not None:
                    first.span.mark("queue_wait")
                self._forming = 1
                try:
                    batch = [first]
                    drain_until = time.monotonic() + self.max_wait_s
                    while len(batch) < self.max_batch:
                        remaining = drain_until - time.monotonic()
                        if remaining <= 0:
                            break
                        try:
                            req = self._queue.get(timeout=remaining)
                        except queue.Empty:
                            break
                        if req.span is not None:
                            req.span.mark("queue_wait")
                        batch.append(req)
                    self._route(batch)
                finally:
                    self._forming = 0
        except KillThread:
            return  # injected death: the supervisor restarts the router

    def _route(self, batch: list[_Request]):  # dvtlint: hot
        bucket = self.replicas[0]._bucket_for(len(batch))
        i = self._pick(bucket)
        if i is None:
            with self._lock:
                self.shed_all_dead += len(batch)
            for req in batch:
                if not req.future.done():
                    req.future.set_result(
                        Shed("shutdown", "all replicas are DEAD"))
            return
        with self._lock:
            self.routed_batches[i] += 1
        # blocking while replica i's in-flight window is full IS the
        # router's backpressure (least-outstanding-work makes a full
        # window unlikely unless every replica is saturated)
        self.replicas[i].dispatch_cohort(batch)
        self.health.record_success()

    def _pick(self, bucket: int) -> int | None:  # dvtlint: hot
        """Least outstanding work = (in-flight + forming batches) × the
        bucket's exec EWMA, over non-DEAD replicas.  Scores tie whenever
        the fleet is idle (everything × EWMA = 0), so scanning starts at
        a rotating offset and strict less-than keeps the first-seen
        minimum — ties round-robin instead of piling onto replica 0.
        None = nothing routable."""
        ewma = self.admission.bucket_ewma_s(bucket) or 1.0
        n = len(self.replicas)
        start = self._rr % n
        self._rr += 1
        best = best_score = None
        for k in range(n):
            i = (start + k) % n
            rep = self.replicas[i]
            if self._retired[i] or rep.health.state == DEAD:
                continue
            score = (rep._inflight + rep._forming) * ewma
            if best_score is None or score < best_score:
                best, best_score = i, score
        return best

    def _free_replicas(self) -> int:
        return sum(1 for i, r in enumerate(self.replicas)
                   if not self._retired[i] and r.health.state != DEAD)

    def live_replicas(self) -> int:
        """Provisioned (non-retired) slots, DEAD included — the capacity
        the autoscaler reasons about (a DEAD replica still occupies its
        device until revived or retired)."""
        return sum(1 for f in self._retired if not f)

    # -- elasticity (deploy/autoscale.py drives these) ---------------------

    def _spare_device(self):
        used = {self.devices[i] for i in range(len(self.replicas))
                if not self._retired[i]}
        for dev in local_devices():
            if dev not in used:
                return dev
        raise ValueError(
            f"no free local device: {len(local_devices())} present, "
            f"{self.live_replicas()} live replica(s)")

    def add_replica(self, device=None) -> int:
        """Scale up: build one more per-device replica (its own weight
        view, AOT compile cache, pipeline window, watchdog) and open it
        to routing.  Returns the new slot index.  The view registers
        with the source model's weight cache (when one manages it) so
        replica residency is budgeted like any version's weights —
        scale-up can evict a colder model's weights, scale-down gives
        the bytes back."""
        if not hasattr(self.model, "for_device"):
            raise ValueError(
                f"model '{self.model.name}' has no per-device view "
                f"(for_device) — StableHLO blobs serve single-device")
        if device is None:
            device = self._spare_device()
        with self._lock:
            i = len(self.replicas)
            rep = self._build_replica(i, device)
            self.replicas.append(rep)
            self.devices.append(device)
            self.routed_batches.append(0)
            self._evacuated.append(False)
            self._retired.append(False)
            self.replicas_added += 1
        cache = getattr(self.model, "_cache", None)
        if cache is not None and rep.model is not self.model:
            cache.register(rep.model)
        if self._accepting:
            rep.start()
        event(_log, "replica_added", model=self.model.name, replica=i,
              device=str(device), live=self.live_replicas())
        return i

    def remove_replica(self, index: int | None = None,
                       drain_deadline: float = 5.0) -> int:
        """Scale down without dropping admitted work: mask the replica
        out of routing (and out of the admission divisor), let its
        in-flight cohorts finish, evacuate whatever outlives
        ``drain_deadline`` onto a healthy peer, then stop it and release
        its device weights.  Refuses to retire the last live replica.
        Returns the retired slot index."""
        with self._lock:
            live = [i for i in range(len(self.replicas))
                    if not self._retired[i]]
            if len(live) <= 1:
                raise ValueError(
                    "refusing to retire the last live replica")
            if index is None:
                # idlest live slot; ties break to the HIGHEST index so
                # repeated scale-downs unwind recent scale-ups first
                index = max(live, key=lambda i: (
                    -(self.replicas[i]._inflight
                      + self.replicas[i]._forming), i))
            elif index not in live:
                raise ValueError(f"replica {index} is not live")
            self._retired[index] = True
            self.replicas_removed += 1
        rep = self.replicas[index]
        t_end = time.monotonic() + drain_deadline
        while time.monotonic() < t_end:
            if rep._inflight + rep._forming == 0:
                break
            time.sleep(0.005)
        if rep._inflight + rep._forming > 0:
            # deadline blown: same re-homing path as replica death, so
            # the cohorts finish elsewhere instead of being dropped
            self._evacuated[index] = True
            self._evacuate(index, reason="scale-down drain deadline")
        rep.stop(timeout=5.0)
        view = rep.model
        if view is not self.model:
            cache = getattr(self.model, "_cache", None)
            if cache is not None:
                cache.drop(view)
            if hasattr(view, "release_device_weights"):
                view.release_device_weights()
        event(_log, "replica_removed", model=self.model.name,
              replica=index, live=self.live_replicas())
        return index

    # -- failure handling (rescue + evacuation) ----------------------------

    def _rescue_from(self, source: int, pending: list[_Request],
                     err: Exception) -> bool:
        """Re-home a failed cohort from ``source`` onto the least-loaded
        healthy replica and bisect-retry it there (innocents served,
        poison quarantined — same isolation as a local batch failure).
        False = nobody else can take it; the caller fails the futures."""
        target = None
        best_score = None
        for i, rep in enumerate(self.replicas):
            if i == source or self._retired[i] \
                    or rep.health.state == DEAD:
                continue
            score = rep._inflight + rep._forming
            if best_score is None or score < best_score:
                target, best_score = i, score
        if target is None:
            return False
        with self._lock:
            self.rescued_requests += len(pending)
        for r in pending:
            if r.span is not None:
                r.span.note("rescued", f"replica {source} -> {target}")
        event(_log, "rescue", model=self.model.name, source=source,
              target=target, requests=len(pending),
              error=f"{type(err).__name__}: {err}")
        # straight to isolation: the failure is SOURCE's, not the
        # target's — going through target._cohort_failed would ding the
        # healthy replica's state machine for its neighbor's crime
        rep = self.replicas[target]
        rep._isolate(pending, err, [rep.retry_budget])
        return True

    def _supervise_loop(self):
        while not self._stop.is_set():
            time.sleep(self.watchdog_interval_s)
            if self._stop.is_set():
                return
            try:
                self._supervise_tick()
            except Exception:  # noqa: BLE001 — the supervisor never dies
                pass

    def _supervise_tick(self):
        t = self._thread
        if t is not None and not t.is_alive():
            self._restart_router()
        for i, rep in enumerate(self.replicas):
            if self._retired[i]:
                continue  # scale-down owns its own drain/evacuation
            if rep.health.state == DEAD and not self._evacuated[i]:
                self._evacuated[i] = True
                self._evacuate(i)
            elif rep.health.state != DEAD and self._evacuated[i]:
                self._evacuated[i] = False  # operator revived it

    def _restart_router(self):
        if self._stop.is_set():
            return
        self.health.record_failure()
        if self.health.watchdog_restarts >= self.restart_budget:
            self.health.force_dead(
                f"router died and the restart budget "
                f"({self.restart_budget}) is exhausted")
            event(_log, "router_dead", model=self.model.name,
                  restart_budget=self.restart_budget)
            return
        self.health.record_restart()
        event(_log, "router_restart", model=self.model.name,
              restarts=self.health.watchdog_restarts,
              budget=self.restart_budget)
        self._thread = threading.Thread(
            target=self._route_loop,
            name=f"router-{self.model.name}", daemon=True)
        self._thread.start()

    def _evacuate(self, i: int, reason: str | None = None):
        """A replica left service with cohorts in flight (went DEAD, or
        blew its scale-down drain deadline): cancel its window records
        (a late drain on a zombie thread is discarded) and re-home every
        still-pending request on a healthy replica.  Admitted work
        survives replica departure; only an all-DEAD fleet fails
        futures."""
        rep = self.replicas[i]
        if reason is None:
            reason = f"DEAD: {rep.health.dead_reason}"
        with rep._lock:  # dvtlint: lock=serve.engine.BatchingEngine._lock
            recs = [r for r in rep._inflight_recs if not r.cancelled]
            for r in recs:
                r.cancelled = True
        for r in recs:
            if r.cancel is not None:
                r.cancel.set()  # release any injected hang
        with self._lock:
            self.evacuations += 1
        pending = [q for r in recs for q in r.requests
                   if not q.future.done()]
        event(_log, "evacuation", model=self.model.name, replica=i,
              reason=reason, requests=len(pending))
        if not pending:
            return
        for q in pending:
            if q.span is not None:
                q.span.note("evacuated", f"replica {i}: {reason}")
        err = RuntimeError(
            f"replica {i} left service ({reason}); cohort re-routed")
        if not self._rescue_from(i, pending, err):
            for q in pending:
                if not q.future.done():
                    q.future.set_exception(err)

    # -- observability -----------------------------------------------------

    def health_report(self) -> dict:
        now = time.monotonic()
        rep = self.health.report(now)
        router_state = rep["state"]
        t = self._thread
        rep["batcher_alive"] = bool(t is not None and t.is_alive())
        rep["drainer_alive"] = None  # replicas own their drainers
        rep["accepting"] = self._accepting
        rep["inflight"] = self.total_inflight()
        replicas = {}
        states = []  # live slots only: retired replicas can't 503 us
        for i, r in enumerate(self.replicas):
            h = r.health_report()
            h["retired"] = self._retired[i]
            replicas[str(i)] = h
            if not self._retired[i]:
                states.append(h["state"])
        rep["replicas"] = replicas
        if router_state == DEAD or not states \
                or all(s == DEAD for s in states):
            state = DEAD
        elif router_state == OK and all(s == OK for s in states):
            state = OK
        else:
            state = "degraded"
        rep["state"] = state
        # the fleet serves while ANY replica is routable: healthz 503s
        # only when all replicas are DEAD (or the router is beyond its
        # restart budget) — a degraded replica drains, it doesn't take
        # the fleet down
        rep["can_serve"] = state != DEAD
        # fleet-wide failure accounting (same keys as a single engine's
        # report, so bench.py / dashboards read either shape)
        rep["batch_failures"] = sum(r.batch_failures
                                    for r in self.replicas)
        rep["retry_executions"] = sum(r.retry_executions
                                      for r in self.replicas)
        rep["quarantined"] = sum(r.quarantined for r in self.replicas)
        rep["exec_timeouts"] = sum(r.exec_timeouts for r in self.replicas)
        rep["watchdog_restarts"] += sum(r.health.watchdog_restarts
                                        for r in self.replicas)
        rep["shed_shutdown"] = self.shed_shutdown
        ages = [a for r in replicas.values() if not r.get("retired")
                if (a := r.get("last_batch_age_s")) is not None]
        rep["last_batch_age_s"] = min(ages) if ages else None
        # same mesh-advertisement keys as a single engine's report so
        # the gateway probe reads either shape (replica views are
        # single-device: mesh_shape stays None unless the base model
        # was built for a mesh)
        rep["mesh_shape"] = self.model.mesh_shape() \
            if hasattr(self.model, "mesh_shape") else None
        rep["param_shard_bytes"] = self.model.param_bytes() \
            if hasattr(self.model, "param_bytes") else None
        rep["hbm_headroom_bytes"] = device_hbm_headroom()
        if self.faults.enabled:
            rep["faults"] = self.faults.stats()
        return rep

    @property
    def queue_depth(self) -> int:
        """Requests awaiting routing in the shared submit queue — the
        edge QoS pressure signal."""
        return self._queue.qsize()

    def occupancy(self) -> float:
        """Mean per-replica compute occupancy over live slots — the
        fleet's duty cycle for the batchy-SLO autoscaler (one busy
        replica among idle ones reads fractional, as capacity says it
        should).  Reads ``_retired`` without the lock, like the
        ``_free_replicas`` divisor (a stale slot flag skews one gauge
        sample, nothing more)."""
        occ = [r.occupancy() for i, r in enumerate(self.replicas)
               if not self._retired[i]]
        return round(sum(occ) / len(occ), 4) if occ else 0.0

    def stats(self) -> dict:
        merged = LatencyHistogram()
        per = []
        img_per_sec = 0.0
        for i, rep in enumerate(self.replicas):
            merged.merge(rep.latency.state_dict())
            ips = rep.throughput.images_per_sec
            img_per_sec += ips
            with self._lock:
                routed = self.routed_batches[i]
            per.append({
                "replica": i,
                "device": rep.model.placement_desc()
                if hasattr(rep.model, "placement_desc") else None,
                "state": rep.health.state,
                "retired": self._retired[i],
                "routed_batches": routed,
                "batches": rep.batches,
                "served": rep.served,
                "quarantined": rep.quarantined,
                "img_per_sec": round(ips, 2),
                "inflight": rep._inflight,
                "max_inflight": rep.max_inflight,
                "compiles": rep.compiles})
        with self._lock:
            out = {"model": self.model.name,
                   "version": getattr(self.model, "serve_version", None),
                   "submitted": self.submitted,
                   "served": sum(r.served for r in self.replicas),
                   "batches": sum(r.batches for r in self.replicas),
                   "compiles": sum(r.compiles for r in self.replicas),
                   "padded_images": sum(r.padded_images
                                        for r in self.replicas),
                   "quarantined": sum(r.quarantined
                                      for r in self.replicas),
                   "queue_depth": self._queue.qsize(),
                   "buckets": list(self.buckets),
                   "max_wait_ms": self.max_wait_s * 1e3,
                   "wire_dtype": str(self.wire_dtype),
                   "infer_dtype": getattr(self.model, "infer_dtype",
                                          "float32"),
                   # per-chip weight pricing, same keys as the single
                   # engine (each replica holds its own full copy —
                   # this is ONE replica's footprint, not the sum)
                   "weight_hbm_bytes": self.model.param_bytes()
                   if hasattr(self.model, "param_bytes") else None,
                   "param_shard_bytes": self.model.param_bytes()
                   if hasattr(self.model, "param_bytes") else None,
                   "param_global_bytes": self.model.param_global_bytes()
                   if hasattr(self.model, "param_global_bytes")
                   else None,
                   "mesh_shape": self.model.mesh_shape()
                   if hasattr(self.model, "mesh_shape") else None,
                   "routing": {
                       "policy": "least_outstanding_work",
                       "replicas": len(self.replicas),
                       "live_replicas": self.live_replicas(),
                       "free_replicas": self._free_replicas(),
                       "rescued_requests": self.rescued_requests,
                       "evacuations": self.evacuations,
                       "shed_all_dead": self.shed_all_dead,
                       "replicas_added": self.replicas_added,
                       "replicas_removed": self.replicas_removed}}
        out["replicas"] = per
        pooled: dict = {}
        h2d_by_bucket: dict = {}
        for r in self.replicas:
            for b, nbuf in r.staging.stats()["pooled"].items():
                pooled[b] = pooled.get(b, 0) + nbuf
            with r._lock:  # dvtlint: lock=serve.engine.BatchingEngine._lock
                for b, nb in r.h2d_bytes_by_bucket.items():
                    h2d_by_bucket[b] = h2d_by_bucket.get(b, 0) + nb
        out["pipeline"] = {
            "depth": self.pipeline_depth,
            "inflight": self.total_inflight(),
            "max_inflight": max(r.max_inflight for r in self.replicas),
            "bulk_transfers": sum(r.bulk_transfers
                                  for r in self.replicas),
            "bulk_transfer_bytes": sum(r.bulk_transfer_bytes
                                       for r in self.replicas),
            "h2d_transfers": sum(r.h2d_transfers for r in self.replicas),
            "h2d_bytes": sum(r.h2d_bytes for r in self.replicas),
            "h2d_bytes_by_bucket": h2d_by_bucket,
            # the single-engine host proxy doesn't compose across
            # replicas (their windows overlap in wall time)
            "device_idle_frac": None,
            "occupancy": self.occupancy(),
            "staging": {
                "allocated": sum(r.staging.allocated
                                 for r in self.replicas),
                "reused": sum(r.staging.reused for r in self.replicas),
                "dtype": str(self.wire_dtype),
                "pooled": pooled}}
        out["latency"] = merged.percentiles()
        out["latency_hist"] = merged.state_dict()
        out["img_per_sec"] = round(img_per_sec, 2)
        out["admission"] = self.admission.stats()
        out["health"] = self.health_report()
        out["mfu"] = MfuMeter.merged_report([r.mfu for r in self.replicas])
        out["trace"] = self.tracer.summary()
        return out
