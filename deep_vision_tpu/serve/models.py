"""Model control plane: versioned model table, HBM weight cache,
zero-downtime hot-reload, and canary rollout.

The serving tier below this module (engine/replicas/http/gateway)
drives exactly one frozen checkpoint per model name, loaded once at
boot.  This module layers the deployment half on top — the parts that
let one process serve the whole zoo and take new trainer checkpoints
without a restart:

  versioned table    每 model name owns an ordered list of
                     ``ModelVersion``s, each wrapping a ServingModel +
                     its own engine + checkpoint identity (step, params
                     digest, checkpoint-dir mtime, load time) and a
                     lifecycle state;
  weight cache       ``WeightCache`` — an LRU over device (HBM) bytes
                     with a configurable budget.  Evicted models spill
                     their params to host RAM and are ``device_put``
                     back on demand; per-model bucket AOT programs are
                     RETAINED across eviction (the engine's executable
                     dict survives, and registry.compile_bucket late-
                     binds its variables), so a cache re-admit costs
                     one H2D transfer, never a recompile;
  lifecycle          LOADING → SHADOW → CANARY(frac) → ACTIVE →
                     DRAINING → RETIRED per version.  ``reload()``
                     re-walks the workdir via core/restore.py in a
                     background thread, optionally shadows (a sampled
                     fraction of live requests is duplicated onto the
                     candidate, top-1 agreement + latency deltas are
                     recorded, outputs are DISCARDED), then routes a
                     ``canary_frac`` slice of real traffic to the
                     candidate and auto-promotes or auto-rolls-back on
                     the ``CanaryPolicy`` gates (error rate, p99 ratio,
                     shadow agreement);
  zero downtime      the old version serves until the new one is
                     ACTIVE; promote swaps the routing table first and
                     only then drains the old engine
                     (``stop(drain_deadline=)`` finishes admitted
                     work), so in-flight cohorts complete on the
                     version that admitted them.  A request that races
                     the swap (admitted-version engine stopped before
                     its cohort formed) is transparently resubmitted to
                     the new active — a reload under load loses zero
                     admitted requests.

Observability: ``stats()`` returns ``{"models": ..., "cache": ...,
"plane": ...}`` (serve/http.py renders ``dvt_serve_model_up`` and the
``dvt_serve_weight_cache_*`` series from it); every lock here is a
``sanitizer.new_lock`` so the chaos suite's lock-order sanitizer covers
the plane.  Lock order: plane._lock and cache._lock are LEAF locks —
never held across an engine call (engine submits, stops, and stats all
happen outside them).
"""

from __future__ import annotations

import threading
import time
from concurrent.futures import Future

import numpy as np

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.serve.admission import Shed
from deep_vision_tpu.serve.faults import Quarantined

_log = get_logger("dvt.serve.models")

# -- lifecycle states ------------------------------------------------------

LOADING = "loading"
SHADOW = "shadow"
CANARY = "canary"
ACTIVE = "active"
DRAINING = "draining"
RETIRED = "retired"
FAILED = "failed"  # load/warmup raised before the version could serve

#: states in which a version's engine receives live traffic
_ROUTABLE = (SHADOW, CANARY, ACTIVE)


class WeightCache:
    """LRU over device (HBM) bytes for registered serving models.

    A registered model's variables live in one of two places: resident
    on device, or spilled to a host-RAM numpy copy.  ``variables_for``
    is the single hot-path entry (called once per dispatched batch from
    the bucket program's late-binding closure, registry.py): a resident
    model is a hit (LRU touch); a spilled one is a miss that admits it
    — evicting least-recently-used residents until the byte budget
    holds — via one ``device_put`` of the host copy.  Eviction is safe
    against in-flight batches: a dispatched program holds Python refs
    to the variables it was called with, so evicted buffers die only
    after the last batch using them drains.

    A single model larger than the whole budget still serves: the
    admit proceeds over budget (counted in ``over_budget``) rather than
    failing — the budget shapes steady-state residency, it is not an
    allocation guarantee.  ``budget_bytes <= 0`` means unbounded
    (residency tracking + counters without eviction).
    """

    def __init__(self, budget_bytes: int = 0):
        self.budget_bytes = int(budget_bytes)
        # name → entry dict; insertion order IS recency order (oldest
        # first), maintained by _touch
        self._entries: dict[int, dict] = {}  # guarded-by: _lock
        self._lock = new_lock("serve.models.WeightCache._lock")
        self.hits = 0  # guarded-by: _lock
        self.misses = 0  # guarded-by: _lock
        self.evictions = 0  # guarded-by: _lock
        self.admits = 0  # guarded-by: _lock
        self.over_budget = 0  # guarded-by: _lock
        self.spilled_bytes_total = 0  # guarded-by: _lock

    def register(self, model) -> None:
        """Put ``model`` (a CheckpointServingModel) under residency
        management.  Its current variables count as resident; admitting
        them may evict others immediately when the budget is already
        full.

        The accounting unit is ``model.param_bytes()`` — PER-CHIP
        addressable shard bytes: a mesh view whose leaves are split
        over ``model`` charges the budget only what one chip actually
        holds (the budget is per-chip HBM), while unsharded models
        price at full size exactly as before.  Spill/re-admit round-
        trips the sharded layout: eviction ``device_get``s (gathering
        shards to full host values), re-admit ``device_put``s against
        the view's sharding pytree — bit-identical, zero recompiles.
        Minimal duck-typed models without ``param_bytes`` price at the
        raw leaf-bytes sum (necessarily unsharded)."""
        import jax

        if hasattr(model, "param_bytes"):
            nbytes = int(model.param_bytes())
        else:
            nbytes = int(sum(a.nbytes for a in
                             jax.tree_util.tree_leaves(model._variables)))
        with self._lock:
            self._entries[id(model)] = {
                "model": model, "nbytes": nbytes, "resident": True,
                "host_copy": None}
            self._evict_for_locked(id(model))
        model._cache = self
        event(_log, "cache_register", model=model.name,
              bytes=nbytes, budget=self.budget_bytes)

    def drop(self, model) -> None:
        """Retire ``model`` from management (version retired/rolled
        back): its entry — resident bytes included — leaves the table."""
        model._cache = None
        with self._lock:
            self._entries.pop(id(model), None)

    def variables_for(self, model):
        """Hot path: the variables ``model``'s bucket programs run with.
        None = not under management (caller falls back to its own)."""
        with self._lock:
            entry = self._entries.get(id(model))
            if entry is None:
                return None
            if entry["resident"]:
                self.hits += 1
                self._touch_locked(id(model))
                return entry["model"]._variables
            # miss: admit the spilled copy, evicting LRU residents
            # until the budget holds (device_put under the cache lock
            # is deliberate — two threads admitting the same model must
            # not both transfer; this lock is a leaf, nothing else is
            # ever acquired under it)
            self.misses += 1
            self._admit_locked(entry)
            self._touch_locked(id(model))
            return entry["model"]._variables

    # -- internals (all under _lock) ---------------------------------------

    def _touch_locked(self, key: int):
        entry = self._entries.pop(key)
        self._entries[key] = entry  # re-insert at the recent end

    def _resident_bytes_locked(self) -> int:
        return sum(e["nbytes"] for e in self._entries.values()
                   if e["resident"])

    def _admit_locked(self, entry: dict):
        import jax

        self.admits += 1
        self._evict_for_locked(id(entry["model"]), entry["nbytes"])
        model = entry["model"]
        model._variables = jax.device_put(entry["host_copy"],
                                          model._var_sharding)
        entry["resident"] = True
        event(_log, "cache_admit", model=model.name,
              bytes=entry["nbytes"],
              resident_bytes=self._resident_bytes_locked())

    def _evict_for_locked(self, keep_key: int, incoming: int = 0):
        """Evict LRU residents (never ``keep_key``) until the budget
        holds the resident set + ``incoming`` bytes."""
        if self.budget_bytes <= 0:
            return
        while self._resident_bytes_locked() + incoming \
                > self.budget_bytes:
            victim_key = next(
                (k for k, e in self._entries.items()
                 if e["resident"] and k != keep_key), None)
            if victim_key is None:
                # only the incoming/kept model remains: allow the
                # overrun (a model bigger than the budget still serves)
                self.over_budget += 1
                return
            self._evict_locked(victim_key)

    def _evict_locked(self, key: int):
        import jax

        entry = self._entries[key]
        model = entry["model"]
        if entry["host_copy"] is None:
            # first eviction pays the D2H spill; the host copy is kept
            # afterwards so later evictions are pure ref-drops
            entry["host_copy"] = jax.tree_util.tree_map(
                np.asarray, jax.device_get(model._variables))
            self.spilled_bytes_total += entry["nbytes"]
        # swap the model onto its host copy: the device buffers die as
        # soon as in-flight batches holding them drain
        model._variables = entry["host_copy"]
        entry["resident"] = False
        self.evictions += 1
        event(_log, "cache_evict", model=model.name,
              bytes=entry["nbytes"])

    # -- observability -----------------------------------------------------

    def resident_models(self) -> list[str]:
        with self._lock:
            return [e["model"].name for e in self._entries.values()
                    if e["resident"]]

    def stats(self) -> dict:
        with self._lock:
            return {
                "budget_bytes": self.budget_bytes,
                "resident_bytes": self._resident_bytes_locked(),
                "hits": self.hits, "misses": self.misses,
                "evictions": self.evictions, "admits": self.admits,
                "over_budget": self.over_budget,
                "spilled_bytes_total": self.spilled_bytes_total,
                "models": {
                    e["model"].name: {
                        "bytes": e["nbytes"],
                        "resident": e["resident"],
                        "spilled": e["host_copy"] is not None}
                    for e in self._entries.values()}}


class CanaryPolicy:
    """Gates + pacing for the SHADOW/CANARY phases of a reload.

    ``canary_frac`` of live traffic routes to the candidate once it
    reaches CANARY; auto-promote requires ``min_requests`` canary
    answers with an error rate ≤ ``max_error_rate`` AND (when both
    sides have latency history) canary p99 ≤ active p99 ×
    ``max_p99_ratio``.  ``shadow_frac > 0`` first duplicates that
    fraction of live requests onto the candidate (outputs discarded)
    and requires ``min_agreement`` top-1 agreement over
    ``shadow_min_compared`` comparisons.  A phase that can't reach its
    quota within ``phase_timeout_s`` rolls back (timeouts are a
    failure, not a pass)."""

    def __init__(self, *, canary_frac: float = 0.1,
                 min_requests: int = 20,
                 max_error_rate: float = 0.0,
                 max_p99_ratio: float | None = 3.0,
                 shadow_frac: float = 0.0,
                 shadow_min_compared: int = 10,
                 min_agreement: float = 0.8,
                 phase_timeout_s: float = 30.0):
        if not 0.0 < canary_frac <= 1.0:
            raise ValueError(f"canary_frac {canary_frac}: need (0, 1]")
        if not 0.0 <= shadow_frac <= 1.0:
            raise ValueError(f"shadow_frac {shadow_frac}: need [0, 1]")
        self.canary_frac = canary_frac
        self.min_requests = int(min_requests)
        self.max_error_rate = float(max_error_rate)
        self.max_p99_ratio = max_p99_ratio
        self.shadow_frac = shadow_frac
        self.shadow_min_compared = int(shadow_min_compared)
        self.min_agreement = float(min_agreement)
        self.phase_timeout_s = float(phase_timeout_s)

    def describe(self) -> dict:
        return {"canary_frac": self.canary_frac,
                "min_requests": self.min_requests,
                "max_error_rate": self.max_error_rate,
                "max_p99_ratio": self.max_p99_ratio,
                "shadow_frac": self.shadow_frac,
                "shadow_min_compared": self.shadow_min_compared,
                "min_agreement": self.min_agreement,
                "phase_timeout_s": self.phase_timeout_s}


class ModelVersion:
    """One deployable version of one model: ServingModel + engine +
    checkpoint identity + lifecycle state.  Mutable fields are guarded
    by the owning plane's lock."""

    def __init__(self, version: int, model, engine, *,
                 workdir: str | None = None):
        self.version = version
        self.model = model
        self.engine = engine
        self.workdir = workdir
        self.state = LOADING
        self.loaded_at = time.monotonic()
        self.state_reason: str | None = None
        # ever held the default route?  revert() only targets versions
        # that actually served as ACTIVE (not rolled-back candidates)
        self.was_active = False
        # canary accounting (filled by the plane's done-callbacks)
        self.canary_requests = 0
        self.canary_errors = 0
        # shadow accounting
        self.shadow_compared = 0
        self.shadow_agreed = 0
        self.shadow_discarded = 0

    def describe(self) -> dict:
        d = {"version": self.version, "state": self.state,
             "state_reason": self.state_reason,
             "was_active": self.was_active,
             "step": self.model.restored_step,
             "digest": getattr(self.model, "params_digest", None),
             "mtime": getattr(self.model, "restored_mtime", None),
             "loaded_age_s": round(time.monotonic() - self.loaded_at, 3)}
        if self.canary_requests or self.canary_errors:
            d["canary"] = {"requests": self.canary_requests,
                           "errors": self.canary_errors}
        if self.shadow_compared or self.shadow_discarded:
            d["shadow"] = {"compared": self.shadow_compared,
                           "agreed": self.shadow_agreed,
                           "discarded": self.shadow_discarded}
        return d


class AgreementHistogram:
    """Tier-vs-big agreement per tier-confidence bucket — one cascade
    hop's calibration sample (serve/cascade.py).

    Fixed bins over [0, 1): sample i lands in
    ``floor(conf * bins)`` and records whether the cheap tier's answer
    matched the big tier's.  ``threshold()`` answers the calibration
    question: the smallest confidence at which routing everything
    at-or-above it to the cheap tier still clears the operator's
    agreement floor — computed from suffix sums, so it is exactly "the
    measured agreement of the traffic the cheap tier would answer".
    Deterministic for a given sample sequence (no RNG anywhere), which
    is what makes calibration testable with a seeded sample.

    ``per_class=True`` adds a per-CLASS axis: each sample ALSO lands in
    its predicted class's own (bins)-count row, and
    ``class_thresholds()`` derives an independent threshold per class
    from the classes whose own sample is thick enough — so a class the
    cheap tier is systematically wrong about escalates at confidences
    where the pooled histogram would have served it (skewed-class
    calibration, the ROADMAP follow-up).  Class rows are lazy (a dict
    keyed by class id), so no class count is needed up front."""

    def __init__(self, bins: int = 20, per_class: bool = False):
        self.bins = max(1, int(bins))
        self.per_class = bool(per_class)
        self._lock = new_lock("serve.models.AgreementHistogram._lock")
        self._total = [0] * self.bins  # guarded-by: _lock
        self._agree = [0] * self.bins  # guarded-by: _lock
        # class id -> per-bin counts, lazily created; guarded-by: _lock
        self._cls_total: dict = {}
        self._cls_agree: dict = {}

    def record(self, confidence: float, agreed: bool, cls=None):
        conf = min(max(float(confidence), 0.0), 1.0)
        i = min(int(conf * self.bins), self.bins - 1)
        with self._lock:
            self._total[i] += 1
            if agreed:
                self._agree[i] += 1
            if self.per_class and cls is not None:
                c = int(cls)
                t = self._cls_total.setdefault(c, [0] * self.bins)
                a = self._cls_agree.setdefault(c, [0] * self.bins)
                t[i] += 1
                if agreed:
                    a[i] += 1

    def reset(self):
        with self._lock:
            self._total = [0] * self.bins
            self._agree = [0] * self.bins
            self._cls_total = {}
            self._cls_agree = {}

    @staticmethod
    def _check_counts(bins: int, total, agree) -> tuple:
        total = [int(x) for x in total]
        agree = [int(x) for x in agree]
        if len(total) != bins or len(agree) != bins:
            raise ValueError(f"persisted bins {len(total)} != {bins}")
        if any(a > t or t < 0 or a < 0
               for t, a in zip(total, agree)):
            raise ValueError("persisted counts are inconsistent")
        return total, agree

    def restore(self, total, agree, per_class=None):
        """Adopt persisted per-bin counts — the cascade calibration
        ledger's boot replay (serve/cascade.py).  Shape and sanity are
        the caller's digest check's problem; this only enforces that
        the counts fit THIS histogram's binning.  ``per_class`` maps
        class id (JSON string keys fine) to {"total", "agree"} rows and
        is ignored unless this histogram tracks the class axis."""
        total, agree = self._check_counts(self.bins, total, agree)
        cls_total: dict = {}
        cls_agree: dict = {}
        if self.per_class and per_class:
            for key, row in per_class.items():
                c = int(key)
                t, a = self._check_counts(
                    self.bins, row["total"], row["agree"])
                cls_total[c] = t
                cls_agree[c] = a
        with self._lock:
            self._total = total
            self._agree = agree
            self._cls_total = cls_total
            self._cls_agree = cls_agree

    @staticmethod
    def _derive(bins: int, total, agree, min_agreement: float,
                min_sample: int) -> float | None:
        """The suffix-sum walk over ONE count row (the pooled histogram
        or a single class's) — see ``threshold`` for the contract."""
        if sum(total) < max(1, int(min_sample)):
            return None
        suf_t = suf_a = 0
        best = None
        # walk top bin down so each step extends the suffix by one bin;
        # the LAST qualifying populated edge is the smallest qualifying t
        for i in range(bins - 1, -1, -1):
            suf_t += total[i]
            suf_a += agree[i]
            if total[i] > 0 and suf_a / suf_t >= float(min_agreement):
                best = i / bins
        return best

    def threshold(self, min_agreement: float,
                  min_sample: int) -> float | None:
        """Smallest bin lower-edge t where the agreement of all samples
        with confidence >= t clears ``min_agreement`` — or None (fail
        closed: all traffic to the big tier) when the whole sample is
        thinner than ``min_sample`` or no suffix clears the floor.

        The edge must sit on a POPULATED bin: empty bins below the
        lowest qualifying sample never extend the threshold downward,
        so confidence levels the sample has not observed escalate
        instead of riding an extrapolated threshold (conservative in
        the cheap direction — an extra big-tier answer costs
        throughput, never correctness)."""
        with self._lock:
            total = list(self._total)
            agree = list(self._agree)
        return self._derive(self.bins, total, agree,
                            min_agreement, min_sample)

    def class_thresholds(self, min_agreement: float,
                         min_sample: int) -> dict:
        """Per-class thresholds for every class whose OWN sample clears
        ``min_sample``: the class's qualifying threshold, or ``None``
        when no confidence level clears the floor — a measured-bad
        class FAILS CLOSED (always escalates) instead of riding the
        pooled threshold it is known to violate.  Classes absent from
        the map (sample too thin) fall back to the pooled threshold."""
        with self._lock:
            rows = {c: (list(self._cls_total[c]),
                        list(self._cls_agree[c]))
                    for c in self._cls_total}
        out = {}
        for c, (total, agree) in sorted(rows.items()):
            if sum(total) < max(1, int(min_sample)):
                continue
            out[c] = self._derive(self.bins, total, agree,
                                  min_agreement, min_sample)
        return out

    def stats(self) -> dict:
        with self._lock:
            total = list(self._total)
            agree = list(self._agree)
            cls_n = {c: sum(t) for c, t in self._cls_total.items()}
        n = sum(total)
        out = {"bins": self.bins,
               "samples": n,
               "agreement": (sum(agree) / n) if n else None,
               "total": total,
               "agree": agree}
        if self.per_class:
            out["class_samples"] = {str(c): cls_n[c]
                                    for c in sorted(cls_n)}
        return out

    def class_counts(self) -> dict:
        """Per-class count rows for the persistence ledger — JSON-safe
        {class id as str: {"total": [...], "agree": [...]}}."""
        with self._lock:
            return {str(c): {"total": list(self._cls_total[c]),
                             "agree": list(self._cls_agree[c])}
                    for c in sorted(self._cls_total)}


class ModelControlPlane:
    """Versioned model table + reload/canary lifecycle over N engines.

    ``engine_factory(model)`` builds (and does NOT start) an engine for
    a ServingModel — cli.serve wires the production BatchingEngine /
    ReplicatedEngine construction through it, tests inject small ones.
    One ``AdmissionController`` per model NAME is shared across that
    model's versions (pass ``admission_factory`` to customize), so the
    per-bucket exec EWMAs — and the per-model queue accounting — carry
    over a reload instead of resetting with each new engine.

    Drop-in engine surface for ``cli.serve``'s boot prints and
    shutdown: ``buckets``/``pipeline_depth``/``faults`` proxy the first
    deployed engine; ``stop(drain_deadline=)`` drains every routable
    version.
    """

    def __init__(self, registry, engine_factory, *,
                 cache: WeightCache | None = None,
                 policy: CanaryPolicy | None = None,
                 admission_factory=None,
                 retain_retired: int = 5):
        self.registry = registry
        self.engine_factory = engine_factory
        self.cache = cache
        self.policy = policy or CanaryPolicy()
        self.admission_factory = admission_factory
        self.retain_retired = int(retain_retired)
        # name → ordered list of ModelVersions (oldest first); the
        # versioned model table
        self._table: dict[str, list[ModelVersion]] = {}  # guarded-by: _lock
        # name → the version currently answering the default route
        self._active: dict[str, ModelVersion] = {}  # guarded-by: _lock
        # name → (candidate, period) canary routing: every period-th
        # submit goes to the candidate (deterministic, not sampled — a
        # 10% canary is exactly every 10th request)
        self._canary: dict[str, tuple] = {}  # guarded-by: _lock
        # name → (candidate, period) shadow duplication
        self._shadow: dict[str, tuple] = {}  # guarded-by: _lock
        self._counter: dict[str, int] = {}  # guarded-by: _lock
        self._reloading: dict[str, threading.Thread] = {}  # guarded-by: _lock
        self._admissions: dict = {}  # name → controller; guarded-by: _lock
        # fns called as fn(name) after a version swap (deploy/promote/
        # rollback/revert) — the cascade recalibration hook; guarded-by:
        # _lock for mutation, snapshotted before firing
        self._version_listeners: list = []  # guarded-by: _lock
        self._lock = new_lock("serve.models.ModelControlPlane._lock")
        self._stopping = threading.Event()
        self.reloads = 0  # guarded-by: _lock
        self.promotions = 0  # guarded-by: _lock
        self.rollbacks = 0  # guarded-by: _lock
        self.reverts = 0  # guarded-by: _lock
        self.resubmitted = 0  # guarded-by: _lock
        # optional BrownoutController (serve/brownout.py): at L1+ the
        # shadow duplicate is optional work and pauses (the shadow
        # phase just compares more slowly); read racily, None = off
        self.brownout = None
        self.shadow_paused = 0  # guarded-by: _lock

    # -- deployment --------------------------------------------------------

    def admission_for(self, name: str):
        """The model's shared admission controller (created on first
        use via ``admission_factory``; None factory = the engine builds
        its own and per-model EWMA continuity is off)."""
        if self.admission_factory is None:
            return None
        with self._lock:
            adm = self._admissions.get(name)
            if adm is None:
                adm = self._admissions[name] = \
                    self.admission_factory(name)
            return adm

    def add_version_listener(self, fn):
        """Register ``fn(name)`` to fire after any version swap of
        ``name`` (deploy, promote — and through promote, revert).  The
        cascade router hooks this to drop its calibration the instant a
        tier's weights change: a new checkpoint shifts the confidence
        distribution, so the old threshold is invalid."""
        with self._lock:
            self._version_listeners.append(fn)

    def _fire_version_listeners(self, name: str):
        # snapshot then call OUTSIDE _lock: listeners may call back
        # into the plane (resolve, canary_active) and _lock is a leaf
        with self._lock:
            listeners = list(self._version_listeners)
        for fn in listeners:
            try:
                fn(name)
            except Exception as e:  # noqa: BLE001 — a listener must not break a deploy
                event(_log, "version_listener_error", model=name,
                      error=f"{type(e).__name__}: {e}")

    def deploy(self, model, *, workdir: str | None = None,
               start: bool = True) -> ModelVersion:
        """Install ``model`` as the next version of its name and make
        it ACTIVE immediately (the boot path; ``reload`` is the
        gradual-rollout path).  Builds + starts its engine, registers
        its weights with the cache, and publishes it in the registry."""
        engine = self.engine_factory(model)
        mv = ModelVersion(0, model, engine, workdir=workdir)
        # allocate the version number and publish the table entry in ONE
        # critical section — two concurrent deploys (or a deploy racing
        # a reload) must never mint the same number
        with self._lock:
            versions = self._table.setdefault(model.name, [])
            mv.version = (versions[-1].version + 1) if versions else 1
            model.serve_version = mv.version
            versions.append(mv)
        try:
            if self.cache is not None and \
                    hasattr(model, "_live_variables"):
                self.cache.register(model)
            if start:
                engine.start()
        except Exception:  # noqa: BLE001 — cleanup only; re-raised to the boot caller
            with self._lock:
                versions.remove(mv)  # failed boot leaves no table entry
            raise
        self.registry.add(model, version=mv.version)
        with self._lock:
            old = self._active.get(model.name)
            self._active[model.name] = mv
            mv.state = ACTIVE
            mv.was_active = True
        if old is not None:
            self._retire(old, reason="replaced by deploy")
        self._fire_version_listeners(model.name)
        event(_log, "deploy", model=model.name, version=mv.version,
              step=model.restored_step)
        return mv

    # -- request path ------------------------------------------------------

    def resolve(self, name: str | None):
        """Routing-table model lookup for the HTTP layer: the ACTIVE
        version's ServingModel (KeyError lists the served names, same
        contract as ``ModelRegistry.get``)."""
        with self._lock:
            names = sorted(self._active)
            if name is None:
                if len(self._active) != 1:
                    raise KeyError(f"model name required "
                                   f"(serving {names})")
                return next(iter(self._active.values())).model
            mv = self._active.get(name)
        if mv is None:
            raise KeyError(f"unknown model '{name}'; serving {names}")
        return mv.model

    def active_version(self, name: str) -> ModelVersion:
        """The ACTIVE ModelVersion for ``name`` (workdir + model +
        engine in one handle) — the deploy watcher's view."""
        with self._lock:
            mv = self._active.get(name)
            names = sorted(self._active)
        if mv is None:
            raise KeyError(f"unknown model '{name}'; serving {names}")
        return mv

    def load_candidate(self, name: str):
        """Load (but do NOT deploy) the newest checkpoint under
        ``name``'s workdir as a fresh ServingModel — the same restore
        path a reload takes.  The deploy watcher's accuracy gate
        evaluates this before anything enters the version table."""
        return self._load_model(self.active_version(name))

    def active_engine(self, name: str):
        with self._lock:
            mv = self._active.get(name)
        if mv is None:
            raise KeyError(f"unknown model '{name}'; "
                           f"serving {sorted(self._active)}")
        return mv.engine

    def active_engines(self) -> dict:
        """name → active engine snapshot (the healthz/metrics view)."""
        with self._lock:
            return {name: mv.engine
                    for name, mv in sorted(self._active.items())}

    def canary_active(self, name: str) -> bool:
        """True while a canary candidate takes a slice of ``name``'s
        traffic — the response cache must not INSERT during that window
        (a canary-served answer would be filed under the active
        version's digest), though lookups stay safe."""
        with self._lock:
            return name in self._canary

    def submit(self, name: str, image, deadline_ms: float | None = None,
               span=None) -> Future:
        """Route one request: the ACTIVE version, or — every canary
        period — the CANARY candidate; an optional SHADOW duplicate
        rides along with its output discarded.  The returned future
        resolves exactly like an engine's.  If the admitting version
        was drained out from under the request mid-reload (its engine
        answered ``Shed("shutdown")`` while a newer version is active),
        the request transparently resubmits to the current active —
        the zero-lost-requests half of zero-downtime."""
        fut: Future = Future()
        self._submit_once(name, image, deadline_ms, span, fut, retries=3)
        return fut

    def infer(self, name: str, image, deadline_ms: float | None = None,
              timeout: float | None = 30.0, span=None):
        return self.submit(name, image, deadline_ms,
                           span=span).result(timeout)

    def _submit_once(self, name, image, deadline_ms, span, fut: Future,
                     retries: int):
        with self._lock:
            mv = self._active.get(name)
            if mv is None:
                names = sorted(self._active)
                err: Exception = KeyError(
                    f"unknown model '{name}'; serving {names}")
                mv = None
            else:
                err = None
                self._counter[name] = self._counter.get(name, 0) + 1
                tick = self._counter[name]
                canary = self._canary.get(name)
                shadow = self._shadow.get(name)
                if canary is not None and tick % canary[1] == 0:
                    mv = canary[0]  # this request IS canary traffic
                    canary = None
        if err is not None:
            fut.set_exception(err)
            return
        is_canary = mv.state == CANARY
        inner = mv.engine.submit(image, deadline_ms, span=span)
        inner.add_done_callback(
            lambda f: self._request_done(f, name, mv, image,
                                         deadline_ms, span, fut,
                                         retries, is_canary))
        # shadow duplication: same image onto the candidate, result
        # compared against the primary then discarded — the candidate
        # never answers a client while shadowing
        if shadow is not None and tick % shadow[1] == 0:
            bo = self.brownout
            if bo is not None and bo.at_least(1):
                # brownout L1+: the duplicate is optional work — the
                # shadow phase compares more slowly, nothing breaks
                with self._lock:
                    self.shadow_paused += 1
            else:
                self._shadow_submit(shadow[0], image, inner)

    def _request_done(self, inner: Future, name, mv, image, deadline_ms,
                      span, fut: Future, retries: int, is_canary: bool):
        """Done-callback on the engine future: transfer the result out,
        count canary outcomes, and resubmit shutdown-shed requests that
        raced a version swap.  Runs on an engine worker thread — must
        never block."""
        try:
            result = inner.result()
        except Exception as e:  # noqa: BLE001 — the engine failed the future; propagate (after canary accounting)
            if is_canary:
                self._count_canary(mv, error=True)
            fut.set_exception(e)
            return
        if is_canary:
            self._count_canary(mv, error=self._is_bad(result))
        if isinstance(result, Shed) and result.reason == "shutdown" \
                and retries > 0 and not self._stopping.is_set():
            with self._lock:
                active = self._active.get(name)
            if active is not None and active is not mv:
                # the admitting version was drained mid-reload: the
                # new active owns this request now
                with self._lock:
                    self.resubmitted += 1
                self._submit_once(name, image, deadline_ms, span, fut,
                                  retries - 1)
                return
        fut.set_result(result)

    @staticmethod
    def _is_bad(result) -> bool:
        """Is this served result an error for canary gating?  Failed
        futures and Quarantined are; NaN float output is (a bad
        checkpoint's signature — serve/faults.py nan mode); sheds are
        capacity, not version quality."""
        if isinstance(result, Quarantined):
            return True
        if isinstance(result, Shed):
            return False
        import jax

        for leaf in jax.tree_util.tree_leaves(result):
            arr = np.asarray(leaf)
            if arr.dtype.kind == "f" and np.isnan(arr).any():
                return True
        return False

    def _count_canary(self, mv: ModelVersion, *, error: bool):
        with self._lock:
            mv.canary_requests += 1
            if error:
                mv.canary_errors += 1

    # -- shadow ------------------------------------------------------------

    def _shadow_submit(self, mv: ModelVersion, image, primary: Future):
        sfut = mv.engine.submit(image)
        holder: dict = {}

        def arrived(which, f):
            with self._lock:
                holder[which] = f
                ready = len(holder) == 2 and not holder.get("_done")
                if ready:
                    holder["_done"] = True
                p, s = holder.get("p"), holder.get("s")
            if ready:
                self._compare_shadow(mv, p, s)

        primary.add_done_callback(lambda f: arrived("p", f))
        sfut.add_done_callback(lambda f: arrived("s", f))

    def _compare_shadow(self, mv: ModelVersion, p: Future, s: Future):
        """Both sides answered: record per-workload agreement, then
        DISCARD the shadow output (it never reaches a client).  The
        workload adapter owns the metric (serve/workloads.py): top-1
        argmax for classify, PCK-style keypoint proximity for pose,
        output-digest equality for generate, greedy IoU≥0.5 class-
        matched pairing fraction (the mAP proxy) for detect;
        ``agree()`` returning None means "not comparable"
        (Shed/Quarantined rows, host-path detect pyramids) — discarded
        without entering the compared count, the same accounting shape
        as before workloads existed."""
        try:
            pr, sr = p.result(), s.result()
        except Exception:  # noqa: BLE001 — either side failed: nothing to compare
            with self._lock:
                mv.shadow_discarded += 1
            return
        wl = getattr(mv.model, "workload", None)
        verdict = None
        if wl is not None:
            try:
                verdict = wl.agree(pr, sr)
            except Exception:  # noqa: BLE001 — a row the metric can't digest
                verdict = None
        with self._lock:
            mv.shadow_discarded += 1
            if verdict is None:
                return
            mv.shadow_compared += 1
            if verdict:
                mv.shadow_agreed += 1

    # -- reload lifecycle --------------------------------------------------

    def reload(self, name: str, *, force: bool = False,
               wait: bool = False, _loader=None) -> dict:
        """Kick a background reload of ``name`` from its workdir: load
        the newest checkpoint, shadow/canary per the policy, then
        auto-promote or auto-roll-back.  Returns immediately with the
        accepted/refused verdict (``wait=True`` blocks until the
        lifecycle completes — the test/CLI convenience).  One reload
        per model at a time (a second request answers ``in_progress``).
        ``_loader()`` (test seam) overrides the checkpoint walk and
        must return a ready ServingModel."""
        with self._lock:
            mv = self._active.get(name)
            if mv is None:
                raise KeyError(f"unknown model '{name}'; "
                               f"serving {sorted(self._active)}")
            t = self._reloading.get(name)
            if t is not None and t.is_alive():
                return {"status": "in_progress", "model": name}
        if _loader is None and mv.workdir is None:
            return {"status": "refused", "model": name,
                    "reason": "no workdir to reload from"}
        if not force and _loader is None:
            from deep_vision_tpu.core.restore import \
                checkpoint_fingerprint

            fp = checkpoint_fingerprint(mv.workdir)
            if fp["step"] == mv.model.restored_step and \
                    fp["step"] is not None:
                return {"status": "no_new_step", "model": name,
                        "step": fp["step"]}
        worker = threading.Thread(
            target=self._reload_worker, args=(name, mv, _loader),
            name=f"reload-{name}", daemon=True)
        with self._lock:
            self._reloading[name] = worker
            self.reloads += 1
        worker.start()
        if wait:
            # wait=True's contract is "return only once the reload has
            # resolved" — compile time is unbounded, so no timeout
            worker.join()  # dvtlint: disable=DVT007
            with self._lock:
                versions = list(self._table.get(name, []))
            last = versions[-1].describe() if versions else None
            return {"status": "done", "model": name, "version": last}
        return {"status": "reloading", "model": name}

    def _load_model(self, mv: ModelVersion):
        """Default loader: same restore path as registry.load_checkpoint
        but into a FRESH ServingModel (the old version keeps serving its
        weights untouched)."""
        from deep_vision_tpu.core.restore import load_state
        from deep_vision_tpu.serve.registry import CheckpointServingModel

        old = mv.model
        cfg = old.cfg
        info: dict = {}
        model, state = load_state(cfg, mv.workdir, tag="reload",
                                  info=info)
        sm = CheckpointServingModel(
            old.name, cfg, model, state,
            wire_dtype=str(old.wire_dtype),
            infer_dtype=old.infer_dtype,
            # int8 reloads recalibrate the NEW weights with the same
            # provenance (batch count / held-out dir / ingest choice)
            calib_batches=getattr(old, "calib_batches", 2),
            calib_dir=getattr(old, "calib_dir", None),
            ingest=getattr(old, "ingest", "pallas"))
        # cascade front tiers keep their fused confidence epilogue
        # across reloads (workloads.ClassifyWorkload.make_epilogue
        # gates on this attribute at bucket-compile time)
        sm.cascade_topk = getattr(old, "cascade_topk", 0)
        # detect models keep their fused decode knobs across reloads
        # too (workloads.DetectWorkload.make_epilogue reads them at
        # bucket-compile time) — a reload must not silently flip a
        # host-pinned baseline to device decode or change K/thresholds
        sm.detect_decode = getattr(old, "detect_decode", "device")
        sm.detect_topk = getattr(old, "detect_topk", 100)
        sm.detect_score_threshold = getattr(
            old, "detect_score_threshold", 0.05)
        sm.detect_iou_threshold = getattr(
            old, "detect_iou_threshold", 0.5)
        sm.detect_soft_nms = getattr(old, "detect_soft_nms", "off")
        sm.detect_soft_sigma = getattr(old, "detect_soft_sigma", 0.5)
        sm.detect_max_per_class = getattr(
            old, "detect_max_per_class", 0)
        sm.restored_step = info.get("step")
        sm.restore_fallback = bool(info.get("fallback"))
        sm.restored_mtime = info.get("mtime")
        sm.params_digest = info.get("digest")
        return sm

    def _reload_worker(self, name: str, old_mv: ModelVersion, _loader):
        try:
            sm = _loader() if _loader is not None \
                else self._load_model(old_mv)
        except Exception as e:  # noqa: BLE001 — a bad checkpoint must not kill the plane
            event(_log, "reload_failed", model=name,
                  error=f"{type(e).__name__}: {e}")
            return
        engine = self.engine_factory(sm)
        mv = ModelVersion(0, sm, engine, workdir=old_mv.workdir)
        # same single-critical-section allocation as deploy(): the
        # version number and the table entry are minted atomically
        with self._lock:
            versions = self._table.setdefault(name, [])
            mv.version = (versions[-1].version + 1) if versions else 1
            sm.serve_version = mv.version
            versions.append(mv)
        v = mv.version
        try:
            if self.cache is not None and \
                    hasattr(sm, "_live_variables"):
                self.cache.register(sm)
            engine.start()
            # warm EVERY bucket before entering shadow/canary: a canary
            # request landing on a cold bucket would pay the compile,
            # inflating the candidate's p99 and tripping the
            # max_p99_ratio gate on a healthy version
            engine.warmup()
        except Exception as e:  # noqa: BLE001 — version never served; mark and bail
            with self._lock:
                mv.state = FAILED
                mv.state_reason = f"{type(e).__name__}: {e}"
            engine.stop()
            if self.cache is not None:
                self.cache.drop(sm)
            self._release_weights(mv)
            event(_log, "reload_failed", model=name, version=v,
                  error=mv.state_reason)
            return
        event(_log, "reload_loaded", model=name, version=v,
              step=sm.restored_step, digest=sm.params_digest)
        # each phase answers True (gates passed), False (gates failed),
        # or None (the operator promoted/rolled back the candidate out
        # from under the phase — the worker's verdict is moot and the
        # guarded transitions below would no-op anyway)
        if self.policy.shadow_frac > 0:
            ok = self._run_shadow(name, mv)
            if ok is None:
                return
            if not ok:
                self._rollback(name, mv, "shadow gate failed")
                return
        ok = self._run_canary(name, mv)
        if ok is None:
            return
        if not ok:
            self._rollback(name, mv, "canary gate failed")
            return
        self._promote(name, mv)

    def _phase_wait(self, done, timeout_s: float) -> bool:
        """Poll ``done()`` until true or the phase times out (timeouts
        fail the phase — an idle service can't validate a candidate)."""
        t_end = time.monotonic() + timeout_s
        while time.monotonic() < t_end:
            if done():
                return True
            if self._stopping.wait(0.005):
                return False
        return done()

    def _run_shadow(self, name: str, mv: ModelVersion) -> bool | None:
        period = max(1, round(1.0 / self.policy.shadow_frac))
        with self._lock:
            mv.state = SHADOW
            self._shadow[name] = (mv, period)
        event(_log, "shadow_start", model=name, version=mv.version,
              period=period)
        try:
            # an operator promote/rollback moves the candidate out of
            # SHADOW under the lock — that ends the phase immediately
            ok = self._phase_wait(
                lambda: mv.state != SHADOW
                or mv.shadow_compared
                >= self.policy.shadow_min_compared,
                self.policy.phase_timeout_s)
        finally:
            with self._lock:
                pair = self._shadow.get(name)
                if pair is not None and pair[0] is mv:
                    self._shadow.pop(name)
        with self._lock:
            if mv.state != SHADOW:
                return None  # operator decided mid-phase
            compared, agreed = mv.shadow_compared, mv.shadow_agreed
        if not ok:
            mv.state_reason = (f"shadow timeout: {compared}/"
                               f"{self.policy.shadow_min_compared} "
                               f"compared")
            return False
        agreement = agreed / compared if compared else 0.0
        event(_log, "shadow_done", model=name, version=mv.version,
              compared=compared, agreed=agreed,
              agreement=round(agreement, 4))
        if agreement < self.policy.min_agreement:
            mv.state_reason = (f"shadow agreement {agreement:.2f} < "
                               f"{self.policy.min_agreement}")
            return False
        return True

    def _run_canary(self, name: str, mv: ModelVersion) -> bool | None:
        period = max(1, round(1.0 / self.policy.canary_frac))
        with self._lock:
            mv.state = CANARY
            self._canary[name] = (mv, period)
        event(_log, "canary_start", model=name, version=mv.version,
              period=period)
        try:
            ok = self._phase_wait(
                lambda: mv.state != CANARY
                or mv.canary_requests >= self.policy.min_requests,
                self.policy.phase_timeout_s)
            with self._lock:
                if mv.state != CANARY:
                    return None  # operator decided mid-phase
                requests, errors = mv.canary_requests, mv.canary_errors
            if not ok:
                mv.state_reason = (f"canary timeout: {requests}/"
                                   f"{self.policy.min_requests} "
                                   f"requests")
                return False
            error_rate = errors / requests if requests else 1.0
            if error_rate > self.policy.max_error_rate:
                mv.state_reason = (f"canary error rate "
                                   f"{error_rate:.3f} > "
                                   f"{self.policy.max_error_rate}")
                return False
            # p99 regression gate: the candidate engine's own latency
            # distribution vs the active's (same histogram edges)
            if self.policy.max_p99_ratio is not None:
                with self._lock:
                    active = self._active.get(name)
                cp = mv.engine.stats()["latency"]
                ap = active.engine.stats()["latency"] \
                    if active is not None else {}
                if cp.get("count") and ap.get("count") and \
                        ap.get("p99_ms"):
                    ratio = cp["p99_ms"] / ap["p99_ms"]
                    if ratio > self.policy.max_p99_ratio:
                        mv.state_reason = (
                            f"canary p99 {cp['p99_ms']:.1f}ms is "
                            f"{ratio:.2f}x active "
                            f"{ap['p99_ms']:.1f}ms > "
                            f"{self.policy.max_p99_ratio}x")
                        return False
            event(_log, "canary_done", model=name, version=mv.version,
                  requests=requests, errors=errors)
            return True
        finally:
            with self._lock:
                pair = self._canary.get(name)
                if pair is not None and pair[0] is mv:
                    self._canary.pop(name)

    def _promote(self, name: str, mv: ModelVersion) -> bool:
        """Swap the routing table to ``mv`` FIRST, then drain the old
        version — no instant exists where neither serves.  The swap is
        a guarded transition: both the reload worker and the operator
        override land here, and only a candidate still in its rollout
        (LOADING/SHADOW/CANARY) can win — a candidate the other side
        already promoted or retired is left alone (returns False)."""
        with self._lock:
            if mv.state not in (LOADING, SHADOW, CANARY):
                return False
            old = self._active.get(name)
            self._active[name] = mv
            mv.state = ACTIVE
            mv.was_active = True
            self.promotions += 1
            # the candidate stops being canary/shadow traffic the same
            # instant it becomes the default route
            for routes in (self._canary, self._shadow):
                pair = routes.get(name)
                if pair is not None and pair[0] is mv:
                    routes.pop(name)
        self.registry.add(mv.model, version=mv.version)
        self._fire_version_listeners(name)
        event(_log, "promote", model=name, version=mv.version,
              step=mv.model.restored_step)
        if old is not None and old is not mv:
            self._retire(old, reason=f"superseded by v{mv.version}")
        return True

    @staticmethod
    def _release_weights(mv: ModelVersion):
        """Free a drained version's device weight copies: the model's
        own variables AND every replica view's (for_device copies own
        their device buffers — a ReplicatedEngine keeps one per chip)."""
        mv.model.release_device_weights()
        for rep in getattr(mv.engine, "replicas", None) or []:
            view = getattr(rep, "model", None)
            if view is not None and view is not mv.model:
                view.release_device_weights()

    def _rollback(self, name: str, mv: ModelVersion, why: str) -> bool:
        """Guarded like ``_promote``: only a candidate still in its
        rollout can be rolled back, so the worker's gate verdict can
        never retire a version the operator just made ACTIVE."""
        with self._lock:
            if mv.state not in (LOADING, SHADOW, CANARY):
                return False
            self.rollbacks += 1
            reason = mv.state_reason or why
            for routes in (self._canary, self._shadow):
                pair = routes.get(name)
                if pair is not None and pair[0] is mv:
                    routes.pop(name)
        event(_log, "rollback", model=name, version=mv.version,
              reason=reason)
        self._retire(mv, reason=reason or why, rolled_back=True)
        return True

    def _retire(self, mv: ModelVersion, *, reason: str,
                rolled_back: bool = False):
        """DRAINING → RETIRED: admitted work finishes on the version
        that admitted it, then the engine stops, the weights leave the
        cache, and the version's device weight copy is released (host
        spill) — a retained-for-observability retired version costs
        host RAM, never HBM."""
        with self._lock:
            if mv.state in (DRAINING, RETIRED, FAILED):
                return  # another thread is already retiring it
            mv.state = DRAINING
            if rolled_back or mv.state_reason is None:
                mv.state_reason = reason
        mv.engine.stop(drain_deadline=5.0)
        if self.cache is not None:
            self.cache.drop(mv.model)
        self._release_weights(mv)
        with self._lock:
            mv.state = RETIRED
            versions = self._table.get(mv.model.name, [])
            retired = [x for x in versions
                       if x.state in (RETIRED, FAILED)]
            for stale in retired[:-self.retain_retired] \
                    if self.retain_retired > 0 else []:
                versions.remove(stale)
                # the registry's version table must not outlive the
                # retain window, or its refs pin the pruned weights
                self.registry.remove_version(mv.model.name,
                                             stale.version)
        event(_log, "retired", model=mv.model.name, version=mv.version,
              reason=reason)

    def promote(self, name: str) -> dict:
        """Operator override: promote the in-flight CANARY/SHADOW
        candidate immediately, skipping the remaining gates.  Decided
        through the same guarded transition the reload worker uses, so
        whichever side moves first wins and the other's verdict is a
        no-op (the worker re-checks the candidate's state and bails)."""
        with self._lock:
            pair = self._canary.get(name) or self._shadow.get(name)
        if pair is None:
            return {"status": "refused", "model": name,
                    "reason": "no candidate in canary/shadow"}
        if not self._promote(name, pair[0]):
            return {"status": "refused", "model": name,
                    "reason": f"v{pair[0].version} already decided"}
        return {"status": "promoted", "model": name,
                "version": pair[0].version}

    def rollback(self, name: str) -> dict:
        """Operator override: retire the in-flight candidate now (same
        guarded transition as ``promote``)."""
        with self._lock:
            pair = self._canary.get(name) or self._shadow.get(name)
        if pair is None:
            return {"status": "refused", "model": name,
                    "reason": "no candidate in canary/shadow"}
        if not self._rollback(name, pair[0], "operator rollback"):
            return {"status": "refused", "model": name,
                    "reason": f"v{pair[0].version} already decided"}
        return {"status": "rolled_back", "model": name,
                "version": pair[0].version}

    def revert(self, name: str) -> dict:
        """One-command rollback to the previous promoted version: mint
        a NEW version wrapping the newest RETIRED model that actually
        held the default route (``was_active``), start + warm its fresh
        engine, then swap it ACTIVE through the same guarded
        ``_promote`` transition every other path uses — the current
        active drains afterwards, so no instant exists where neither
        serves and admitted work finishes where it was admitted.

        Busy-vs-failed semantics match the gateway fan-out: a lifecycle
        already in flight answers ``in_progress`` (HTTP 409) without
        touching anything; nothing to revert to answers ``refused``; a
        revert whose engine fails to boot answers ``failed`` (500) and
        leaves the current active untouched."""
        with self._lock:
            active = self._active.get(name)
            if active is None:
                raise KeyError(f"unknown model '{name}'; "
                               f"serving {sorted(self._active)}")
            t = self._reloading.get(name)
            if (t is not None and t.is_alive()) \
                    or name in self._canary or name in self._shadow:
                return {"status": "in_progress", "model": name,
                        "reason": "a reload lifecycle is in flight"}
            target = None
            for old in reversed(self._table.get(name, [])):
                if old.version < active.version \
                        and old.state == RETIRED and old.was_active:
                    target = old
                    break
        if target is None:
            return {"status": "refused", "model": name,
                    "reason": "no previous promoted version to "
                              "revert to"}
        sm = target.model
        engine = self.engine_factory(sm)
        mv = ModelVersion(0, sm, engine, workdir=target.workdir)
        # same single-critical-section allocation as deploy()/reload
        with self._lock:
            versions = self._table.setdefault(name, [])
            mv.version = (versions[-1].version + 1) if versions else 1
            sm.serve_version = mv.version
            versions.append(mv)
        try:
            if self.cache is not None and \
                    hasattr(sm, "_live_variables"):
                self.cache.register(sm)
            engine.start()
            engine.warmup()  # no canary phase: warm before the swap
        except Exception as e:  # noqa: BLE001 — failed revert must not take the active down
            with self._lock:
                mv.state = FAILED
                mv.state_reason = f"{type(e).__name__}: {e}"
            engine.stop()
            if self.cache is not None:
                self.cache.drop(sm)
            self._release_weights(mv)
            event(_log, "revert_failed", model=name, version=mv.version,
                  error=mv.state_reason)
            return {"status": "failed", "model": name,
                    "reason": mv.state_reason}
        if not self._promote(name, mv):
            self._retire(mv, reason="revert lost the promote race")
            return {"status": "refused", "model": name,
                    "reason": "another lifecycle decided first"}
        with self._lock:
            self.reverts += 1
        event(_log, "revert", model=name, version=mv.version,
              restores=target.version, from_version=active.version,
              step=sm.restored_step, digest=sm.params_digest)
        return {"status": "reverted", "model": name,
                "version": mv.version, "restores": target.version,
                "from_version": active.version}

    # -- lifecycle / engine-surface compatibility --------------------------

    @property
    def faults(self):
        with self._lock:
            mv = next(iter(self._active.values()), None)
        return mv.engine.faults if mv is not None else _NO_FAULTS

    @property
    def buckets(self):
        with self._lock:
            mv = next(iter(self._active.values()), None)
        return mv.engine.buckets if mv is not None else []

    @property
    def pipeline_depth(self):
        with self._lock:
            mv = next(iter(self._active.values()), None)
        return mv.engine.pipeline_depth if mv is not None else 1

    @property
    def model(self):
        with self._lock:
            mv = next(iter(self._active.values()), None)
        return mv.model if mv is not None else None

    def warmup(self, buckets=None):
        for eng in self.active_engines().values():
            eng.warmup(buckets)

    def stop(self, timeout: float = 5.0,
             drain_deadline: float | None = None):
        """Stop every version's engine (reload workers bail at the next
        phase poll)."""
        self._stopping.set()
        with self._lock:
            workers = list(self._reloading.values())
            versions = [mv for vs in self._table.values() for mv in vs]
        for w in workers:
            w.join(timeout)
        for mv in versions:
            if mv.state in _ROUTABLE or mv.state == LOADING:
                mv.engine.stop(timeout, drain_deadline=drain_deadline)

    # -- observability -----------------------------------------------------

    def models(self) -> dict:
        """The /v1/models listing: per name, the version table + which
        one is active + the gate policy."""
        with self._lock:
            names = {name: (list(vs), self._active.get(name))
                     for name, vs in self._table.items()}
        out = {}
        for name, (versions, active) in sorted(names.items()):
            out[name] = {
                "active_version": active.version
                if active is not None else None,
                "model": (active.model.describe()
                          if active is not None else None),
                "versions": [mv.describe() for mv in versions]}
        return out

    def stats(self) -> dict:
        """The plane-shaped /v1/stats body: ``models`` (per name: the
        active engine's full stats + the version table), ``cache``, and
        ``plane`` counters.  serve/http.py renders /metrics from it."""
        with self._lock:
            snapshot = {name: (self._active.get(name),
                               list(self._table.get(name, [])))
                        for name in self._table}
            plane = {"reloads": self.reloads,
                     "promotions": self.promotions,
                     "rollbacks": self.rollbacks,
                     "reverts": self.reverts,
                     "resubmitted": self.resubmitted,
                     "shadow_paused": self.shadow_paused,
                     "policy": self.policy.describe()}
        models = {}
        for name, (active, versions) in sorted(snapshot.items()):
            entry = {
                "active_version": active.version
                if active is not None else None,
                "versions": [mv.describe() for mv in versions]}
            if active is not None:
                entry["engine"] = active.engine.stats()
            # a routable non-active candidate's engine stats ride along
            # so canary latency/error progress is observable mid-rollout
            for mv in versions:
                if mv is not active and mv.state in _ROUTABLE:
                    entry["candidate_engine"] = mv.engine.stats()
            models[name] = entry
        out = {"models": models, "plane": plane}
        if self.cache is not None:
            out["cache"] = self.cache.stats()
        return out


class _NoFaults:
    enabled = False
    spec = ""
    seed = 0


_NO_FAULTS = _NoFaults()
