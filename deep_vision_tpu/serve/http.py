"""Stdlib HTTP front-end for the batching engine — zero new dependencies.

Routes (JSON in, JSON out):

    GET  /v1/healthz   DEEP health: per-engine thread liveness,
                       heartbeat ages, last-completed-batch age,
                       consecutive failures, and the OK → DEGRADED →
                       DEAD state machine — 503 when any engine can't
                       serve (single engine: DEGRADED/DEAD; replicated
                       engine: every replica DEAD) so load balancers
                       drain traffic, 200 again after recovery
    GET  /v1/stats     per-model engine stats (latency p50/p95/p99,
                       throughput, shed counts, compile/bucket state,
                       the pipelined executor's overlap block, the
                       ``health`` block: state, failures, retries,
                       quarantines, watchdog restarts — plus the
                       ``mfu`` and ``trace`` observability blocks)
    GET  /metrics      Prometheus text exposition (format 0.0.4) of the
                       same stats: dvt_serve_* counters/gauges, the
                       request-latency histogram as cumulative ``le``
                       buckets, and the ``dvt_serve_mfu`` gauge
                       (docs/OBSERVABILITY.md has the full name table)
    GET  /v1/traces    recent finished request traces from the bounded
                       in-memory ring (``?n=`` caps the count) plus the
                       tracer summary (per-stage time aggregates)
    POST /v1/classify  {"pixels": [[...]] | "image_b64": "...",
                        "model"?, "deadline_ms"?, "top_k"?}
    POST /v1/detect    same inputs + "score_threshold"?; detection
                       models (YOLO, CenterNet) — decode → threshold →
                       top-k → class-wise NMS run ON DEVICE in the
                       fused epilogue, so D2H ships K fixed-size boxes
                       per image, and the reply carries
                       {"num_detections", "detections": [{box, score,
                       class}]} with no padded/invalid rows
    POST /v1/pose      same image inputs; heatmap models (Stacked
                       Hourglass) — the traced on-device epilogue
                       decodes heatmaps to {"keypoints": [{x, y,
                       score}]} (serve/workloads.py)
    POST /v1/generate  generative models: latent-in (DCGAN) bodies
                       carry {"latent": [...]} or {"seed": int}
                       (deterministic host draw); image-in translation
                       (CycleGAN) takes the usual image inputs.  The
                       reply is {"image": {"b64", "shape", "dtype"}} —
                       raw uint8 bytes encoded ON DEVICE by the fused
                       epilogue, so the bulk D2H moves 1 byte/pixel
    POST /v1/models/{name}/classify | /detect | /pose | /generate
                       same bodies with the model named in the PATH —
                       the multi-model route (a body "model" key must
                       match the path or 400).  The verb set derives
                       from the workload registry (serve/workloads.py);
                       unknown verbs 404 with the supported list in
                       the body
    GET  /v1/models    the model table: per name the active version +
                       full version history (step/digest/state) — the
                       control-plane listing when ``cli.serve --models``
                       booted a plane, a flat describe() map otherwise
    POST /v1/models/{name}/reload | /promote | /rollback
                       lifecycle endpoints (control plane required, 503
                       otherwise): reload kicks the background
                       load → shadow → canary walk (body: {"force"?,
                       "wait"?}); promote/rollback override the gates on
                       the in-flight candidate (docs/SERVING.md runbook)
    GET  /v1/deploy/{name}/history
                       the append-only deployment ledger for one model
                       (deploy/history.py): every candidate sighting,
                       gate verdict, promote/rollback/revert — ``?n=``
                       caps the tail (deploy pipeline required, 503
                       otherwise)
    POST /v1/deploy/{name}/revert
                       one-command rollback to the last previously
                       promoted version, through the plane's gated
                       state machine: 200 reverted / 409 while a
                       lifecycle is in flight or nothing to revert to /
                       500 when the restored version fails to boot
                       (docs/DEPLOY.md runbook)
    POST /v1/jobs      offline batch tier (serve/jobs.py): submit a
                       manifest {"items": [<request bodies>], "model"?,
                       "shard_size"?} → 202 with a job handle; the
                       trough-filling scheduler (serve/batch_sched.py)
                       drains it through the engines strictly below
                       interactive traffic.  503 unless the tier is
                       wired (cli.serve --jobs-dir)
    GET  /v1/jobs      job listing (status views, FIFO order)
    GET  /v1/jobs/{id} one job's status: state, shards done, images
    GET  /v1/jobs/{id}/results
                       chunked ndjson stream of the job's completed
                       results — the contiguous shard prefix, one
                       {"index": i, ...} line per item plus a trailing
                       {"status": ...} line; re-issue after completion
                       for the full set (results are durable)
    POST /v1/drain     zero-downtime shutdown hook: healthz flips to
                       503 ``draining`` IMMEDIATELY (so a gateway or
                       load balancer stops routing here), new requests
                       shed 429, and every engine finishes its admitted
                       in-flight work via ``stop(drain_deadline=)``
                       (body: {"drain_deadline_s"?: float, default 10})
                       before the 200 reply — no admitted request fails

Request tracing: every POST carries a request id — the client's
``X-DVT-Request-Id`` header if present (the gateway forwards its own),
else generated here — echoed on the response and stamped on the
request's span.  ``?debug=1`` on classify/detect adds the span's
per-stage timing breakdown to the response body; the same traces land
in the in-memory ring behind ``GET /v1/traces``.

Image payloads: ``pixels`` is an (H, W, C) array in the model's WIRE
dtype — raw 0–255 integers on the uint8 wire (the ``cli.serve``
default; the server normalizes on device), a host-preprocessed float
array on the float32 wire (the machine-to-machine back-compat path).
Non-finite float payloads reject 400 at decode.  ``image_b64`` is a
base64-encoded image file decoded + resized server-side in integer
space; the float32 wire additionally normalizes exactly like
``cli.infer`` (requires PIL).  Shed requests answer 429 with the
shed reason (queue-full sheds add a ``Retry-After`` header) so clients
can retry against another replica; quarantined (poison) requests answer
500 with the isolation detail.  Bodies over ``max_body_bytes`` (default
32 MiB) are rejected 413 before any buffer is allocated.

Each connection carries a socket timeout (``socket_timeout_s``, default
30 s): a client that opens a socket and never sends a request line gets
the connection closed, and one that stalls mid-body gets 408 — either
way a slow-loris can't pin a handler thread forever.

The front-end itself is the selector event loop in ``serve/edge.py``
(HTTP/1.1 keep-alive, pipelining, bounded connections) by default;
``edge=False`` keeps the original thread-per-request
``ThreadingHTTPServer`` — the A/B baseline in docs/PERF.md.  Either
way the routes above run unchanged.  Two optional edge services hook
the inference POST path: a content-addressed response cache
(``serve/cache.py`` — a repeat payload against the same model version
answers without touching the engine) and per-tenant QoS
(``serve/admission.py TenantQoS`` — the ``X-DVT-Tenant`` header maps
to a priority class with a token-bucket quota, checked before the
cache, and a weighted-shedding knee on engine queue pressure, checked
on cache misses only).
"""

from __future__ import annotations

import base64
import io
import json
import math
import threading
import time

from deep_vision_tpu.analysis.sanitizer import new_lock
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from deep_vision_tpu.obs.trace import REQUEST_ID_HEADER, new_request_id
from deep_vision_tpu.serve.admission import TENANT_HEADER
from deep_vision_tpu.serve.cache import ResponseCache, payload_digest
from deep_vision_tpu.serve.cascade import base_tier as cascade_base_tier
from deep_vision_tpu.serve.cascade import is_degraded as cascade_degraded
from deep_vision_tpu.serve.edge import (
    _CHUNK_END,
    DEFAULT_MAX_CONNECTIONS,
    EdgeServer,
    _chunk_frame,
)
from deep_vision_tpu.serve.workloads import (
    LIFECYCLE_VERBS,
    WORKLOADS,
    infer_paths,
    infer_verbs,
)

DEFAULT_MAX_BODY_BYTES = 32 * 2**20

#: which cascade tier produced this answer ("front"/"big") — set on
#: every cascaded 200 so clients and the bench can split per-tier
#: latency without a debug span (serve/cascade.py)
TIER_HEADER = "X-DVT-Tier"

#: set ("1") on answers the brownout ladder degraded deliberately — a
#: forced front-tier cascade answer (L2) or a stale response-cache hit
#: (L2).  Clients that care about full quality can retry later; ones
#: that don't get a fast answer instead of a 429 (serve/brownout.py)
DEGRADED_HEADER = "X-DVT-Degraded"


class ServeError(Exception):
    def __init__(self, status: int, message: str,
                 headers: dict | None = None):
        super().__init__(message)
        self.status = status
        self.headers = headers


def _decode_pixels(body: dict, model):
    """Body → one (H, W, C) image in the model's WIRE dtype + layout.

    ``pixels`` lists decode STRAIGHT to the wire dtype (no float64
    detour copy: json gives Python scalars, one ``np.asarray`` lands
    them in uint8 or float32).  ``image_b64`` decodes + resizes in
    integer space; on a uint8 wire the pixels ship raw (the bucket
    program normalizes on device), on a float32 wire the host applies
    the model family's normalization exactly like ``cli.infer``.
    """
    import numpy as np

    wire = np.dtype(getattr(model, "wire_dtype", np.float32))
    if "pixels" in body:
        try:
            x = np.asarray(body["pixels"], wire)
        except (ValueError, TypeError, OverflowError) as e:
            # ragged lists, non-numeric entries, or NaN/Inf → integer
            raise ServeError(400, f"bad pixels payload: {e}") from e
        if x.ndim == 2 and model.input_shape[-1] == 1:
            x = x[..., None]
        if x.shape != model.input_shape:
            raise ServeError(
                400, f"pixels shape {list(x.shape)} != model input "
                     f"{list(model.input_shape)}")
        if wire.kind == "f" and not np.isfinite(x).all():
            # NaN/Inf would propagate through the whole padded batch's
            # outputs — reject at the door, not in the batcher
            raise ServeError(
                400, "pixels contain non-finite values (NaN/Inf)")
        return x
    if "image_b64" in body:
        try:
            from PIL import Image
        except ImportError as e:
            raise ServeError(501, "image_b64 needs PIL on the server; "
                                  "send preprocessed 'pixels'") from e
        raw = base64.b64decode(body["image_b64"])
        size = model.input_shape[0]
        img = Image.open(io.BytesIO(raw))
        if model.input_shape[-1] == 1:
            # grayscale models (LeNet): MNIST-style geometry — resize to
            # size-4 and pad 2px each side, all in uint8
            arr = np.asarray(img.convert("L").resize((size - 4, size - 4)))
            u8 = np.pad(arr, 2)[:size, :size, None]
            if wire.kind == "u":
                return u8  # device prologue scales + standardizes
            from deep_vision_tpu.data.mnist import preprocess

            return preprocess(arr[None])[0][:size, :size]
        arr = np.asarray(img.convert("RGB"))
        if model.task == "classification":
            from deep_vision_tpu.data.transforms import (
                eval_transform,
                eval_transform_u8,
                imagenet_resize_for,
            )

            if wire.kind == "u":
                # same rescale→center-crop geometry, kept uint8
                return np.ascontiguousarray(eval_transform_u8(
                    arr, size, imagenet_resize_for(size)))
            return eval_transform(arr, size, imagenet_resize_for(size))
        # detection/pose/GAN: plain resize, family-specific scaling
        from deep_vision_tpu.data.detection import resize_square

        u8 = resize_square(arr, size)
        if wire.kind == "u":
            return np.asarray(u8, np.uint8)
        if str(model.task).startswith("gan_"):
            # image-in translation (CycleGAN) trained on [-1,1] inputs
            # (make_gan_preprocess); the float wire ships them as-is
            return u8.astype(np.float32) / 127.5 - 1.0
        return u8.astype(np.float32) / 255.0
    raise ServeError(400, "body needs 'pixels' or 'image_b64'")


def render_serve_metrics(stats: dict) -> str:
    """Render serve stats as Prometheus text — both shapes.

    Legacy shape: {model_name: engine.stats()}.  Control-plane shape
    (serve/models.py ``ModelControlPlane.stats()``): {"models": {name:
    {"engine": ..., "versions": [...]}}, "cache": ..., "plane": ...} —
    the plane shape additionally emits ``dvt_serve_model_up`` per
    version and the ``dvt_serve_weight_cache_*`` series.

    No parallel metric registry: the stats dicts stay the single source
    of truth and this snapshots them through ``core.metrics.PromText``
    (docs/OBSERVABILITY.md tabulates every name emitted here).
    """
    from deep_vision_tpu.core.metrics import PromText

    p = PromText()
    _render_edge_metrics(p, stats)
    if isinstance(stats.get("batch"), dict):
        _render_batch_metrics(p, stats["batch"])
    if isinstance(stats.get("cascade"), dict):
        _render_cascade_metrics(p, stats["cascade"])
    if isinstance(stats.get("brownout"), dict):
        _render_brownout_metrics(p, stats["brownout"])
    if isinstance(stats.get("models"), dict):
        for name, entry in stats["models"].items():
            if isinstance(entry.get("engine"), dict):
                _render_engine_metrics(p, name, entry["engine"])
            for v in entry.get("versions", []):
                p.gauge("dvt_serve_model_up",
                        1 if v.get("state") in ("active", "canary",
                                                "shadow") else 0,
                        {"model": name,
                         "version": str(v.get("version")),
                         "state": str(v.get("state"))},
                        help="1 while this model version takes traffic")
        cache = stats.get("cache")
        if isinstance(cache, dict):
            p.gauge("dvt_serve_weight_cache_budget_bytes",
                    cache.get("budget_bytes"), {},
                    help="HBM byte budget (0 = unbounded)")
            p.gauge("dvt_serve_weight_cache_resident_bytes",
                    cache.get("resident_bytes"), {},
                    help="Bytes of model weights resident on device")
            p.counter("dvt_serve_weight_cache_hits_total",
                      cache.get("hits"), {},
                      help="Batch dispatches finding weights resident")
            p.counter("dvt_serve_weight_cache_misses_total",
                      cache.get("misses"), {},
                      help="Dispatches that had to re-admit weights")
            p.counter("dvt_serve_weight_cache_evictions_total",
                      cache.get("evictions"), {},
                      help="LRU evictions (weights spilled to host)")
            p.counter("dvt_serve_weight_cache_admits_total",
                      cache.get("admits"), {},
                      help="Host→device weight re-admissions")
            p.counter("dvt_serve_weight_cache_spilled_bytes_total",
                      cache.get("spilled_bytes_total"), {},
                      help="Bytes D2H-copied at first eviction")
            for mname, ent in (cache.get("models") or {}).items():
                p.gauge("dvt_serve_weight_cache_resident",
                        1 if ent.get("resident") else 0,
                        {"model": mname},
                        help="1 while this model's weights are on device")
        plane = stats.get("plane")
        if isinstance(plane, dict):
            p.counter("dvt_serve_reloads_total", plane.get("reloads"),
                      {}, help="Reload lifecycles started")
            p.counter("dvt_serve_promotions_total",
                      plane.get("promotions"), {},
                      help="Versions auto- or operator-promoted")
            p.counter("dvt_serve_rollbacks_total",
                      plane.get("rollbacks"), {},
                      help="Versions rolled back by gates or operator")
            p.counter("dvt_serve_reload_resubmitted_total",
                      plane.get("resubmitted"), {},
                      help="Requests transparently resubmitted across "
                           "a version swap")
            p.counter("dvt_serve_reverts_total", plane.get("reverts"),
                      {}, help="One-command reverts to a prior "
                               "promoted version")
        dep = stats.get("deploy")
        if isinstance(dep, dict):
            _render_deploy_metrics(p, dep)
        return p.render()
    for name, s in stats.items():
        if name in ("edge", "response_cache", "qos", "batch",
                    "cascade", "brownout"):
            continue  # front-end blocks, rendered above
        _render_engine_metrics(p, name, s)
    return p.render()


def _render_edge_metrics(p, stats: dict) -> None:
    """Emit the front-end tier's series: the selector edge's
    connection counters, the response cache, and per-tenant-class QoS
    (docs/OBSERVABILITY.md tabulates these)."""
    edge = stats.get("edge")
    if isinstance(edge, dict):
        p.gauge("dvt_serve_open_connections",
                edge.get("open_connections"), {},
                help="Sockets currently open on the serving edge")
        p.gauge("dvt_serve_max_connections",
                edge.get("max_connections"), {},
                help="Connection cap (--max-connections)")
        p.counter("dvt_serve_edge_accepted_total", edge.get("accepted"),
                  {}, help="Connections accepted")
        p.counter("dvt_serve_edge_requests_total", edge.get("requests"),
                  {}, help="Requests parsed off edge connections")
        p.counter("dvt_serve_edge_keepalive_reuses_total",
                  edge.get("keepalive_reuses"), {},
                  help="Requests after the first on one connection")
        p.counter("dvt_serve_edge_evicted_idle_total",
                  edge.get("evicted_idle"), {},
                  help="Idle connections evicted to admit new ones")
        p.counter("dvt_serve_edge_accept_pauses_total",
                  edge.get("accept_pauses"), {},
                  help="Times the listener paused at the connection cap")
        p.counter("dvt_serve_edge_timeouts_408_total",
                  edge.get("timeouts_408"), {},
                  help="Stalled-body connections answered 408")
        p.counter("dvt_serve_edge_closed_idle_total",
                  edge.get("closed_idle"), {},
                  help="Idle/slow-loris connections closed silently")
    rcache = stats.get("response_cache")
    if isinstance(rcache, dict):
        p.counter("dvt_serve_cache_hits_total", rcache.get("hits"), {},
                  help="Inference answers served from the response cache")
        p.counter("dvt_serve_cache_misses_total", rcache.get("misses"),
                  {}, help="Cacheable lookups that missed")
        p.counter("dvt_serve_cache_stale_hits_total",
                  rcache.get("stale_hits"), {},
                  help="Brownout-L2 answers served from a retired "
                       "params version (marked X-DVT-Degraded)")
        p.counter("dvt_serve_cache_evictions_total",
                  rcache.get("evictions"), {},
                  help="LRU evictions from the response cache")
        p.counter("dvt_serve_cache_insertions_total",
                  rcache.get("insertions"), {},
                  help="Responses inserted into the cache")
        for tier, n in sorted(
                (rcache.get("insertions_by_tier") or {}).items()):
            p.counter("dvt_serve_cache_tier_insertions_total", n,
                      {"tier": str(tier)},
                      help="Cache inserts by the cascade tier that "
                           "produced the answer (the key itself stays "
                           "tier-agnostic)")
        p.gauge("dvt_serve_cache_bytes", rcache.get("bytes"), {},
                help="Bytes of cached serialized responses")
        p.gauge("dvt_serve_cache_entries", rcache.get("entries"), {},
                help="Entries in the response cache")
    qos = stats.get("qos")
    if isinstance(qos, dict):
        for cls, q in qos.items():
            lab = {"class": cls}
            p.counter("dvt_serve_tenant_served_total", q.get("served"),
                      lab, help="Requests served per tenant class")
            p.counter("dvt_serve_tenant_shed_total", q.get("shed_quota"),
                      {**lab, "reason": "quota"},
                      help="Requests shed by tenant QoS")
            p.counter("dvt_serve_tenant_shed_total",
                      q.get("shed_priority"),
                      {**lab, "reason": "priority"})
            p.counter("dvt_serve_tenant_cache_hits_total",
                      q.get("cache_hits"), lab,
                      help="Cache hits per tenant class")
            lat = q.get("latency") or {}
            for k in ("p50_ms", "p95_ms", "p99_ms"):
                p.gauge("dvt_serve_tenant_latency_seconds",
                        (lat.get(k) or 0.0) / 1e3,
                        {**lab, "quantile": k[1:-3]},
                        help="Per-class request latency quantiles")


def _render_deploy_metrics(p, dep: dict) -> None:
    """Emit the dvt_deploy_* series from ``DeployPipeline.stats()``."""
    hist = dep.get("history") or {}
    p.counter("dvt_deploy_history_records_total", hist.get("records"),
              {}, help="Deployment-ledger records appended")
    p.counter("dvt_deploy_history_write_errors_total",
              hist.get("write_errors"), {},
              help="Ledger appends that failed to reach disk")
    w = dep.get("watcher")
    if isinstance(w, dict):
        p.counter("dvt_deploy_watcher_polls_total", w.get("polls"), {},
                  help="Checkpoint-fingerprint polls")
        p.counter("dvt_deploy_watcher_debounces_total",
                  w.get("debounces"), {},
                  help="Candidates held one interval for stability")
        p.counter("dvt_deploy_deploys_total", w.get("deploys"), {},
                  help="Watcher-initiated rollouts that promoted")
        p.counter("dvt_deploy_gate_failures_total",
                  w.get("gate_failures"), {},
                  help="Candidates refused by the accuracy gate")
    for mname, a in (dep.get("autoscale") or {}).items():
        lab = {"model": mname}
        p.counter("dvt_deploy_scale_ups_total", a.get("scale_ups"),
                  lab, help="Autoscaler replica additions")
        p.counter("dvt_deploy_scale_downs_total", a.get("scale_downs"),
                  lab, help="Autoscaler replica drains")
        p.counter("dvt_deploy_scale_errors_total",
                  a.get("scale_errors"), lab,
                  help="Scale actions that raised (cooldown consumed)")
        p.gauge("dvt_deploy_pressure_ms", a.get("pressure_ms"), lab,
                help="queue_depth × exec EWMA — the scale-up signal")
        if a.get("occupancy") is not None:
            p.gauge("dvt_deploy_occupancy", a.get("occupancy"), lab,
                    help="Engine compute occupancy — the batchy-SLO "
                         "scale-up signal (queue depth misses "
                         "throughput saturation)")


def _render_batch_metrics(p, batch: dict) -> None:
    """Emit the offline batch tier's dvt_batch_* series from the
    ``batch`` stats block (jobs store + trough-filling scheduler +
    occupancy-weighted MFU; docs/BATCH.md tabulates these)."""
    jobs = batch.get("jobs") or {}
    sched = batch.get("scheduler") or {}
    p.counter("dvt_batch_jobs_submitted_total", jobs.get("submitted"),
              {}, help="Bulk jobs accepted via POST /v1/jobs")
    p.counter("dvt_batch_images_total", jobs.get("images_done"), {},
              help="Images with durable batch results (end-to-end "
                   "goodput; replayed checkpoint shards count once)")
    p.counter("dvt_batch_jobs_resumed_total", jobs.get("resumed"), {},
              help="Unfinished jobs resumed from the JSONL checkpoint "
                   "at boot")
    p.counter("dvt_batch_checkpoint_write_errors_total",
              jobs.get("write_errors"), {},
              help="Job-ledger appends that failed to reach disk")
    for state, n in (jobs.get("states") or {}).items():
        p.gauge("dvt_batch_jobs", n, {"state": state},
                help="Jobs by lifecycle state")
    p.counter("dvt_batch_shards_total", sched.get("shards_done"), {},
              help="Shards drained to a durable record this process")
    p.counter("dvt_batch_shards_shed_total", sched.get("shards_shed"),
              {}, help="Whole-shard retries after an engine shed")
    p.counter("dvt_batch_deferred_total", sched.get("deferred"), {},
              help="Trough checks that parked batch work behind "
                   "interactive pressure")
    p.counter("dvt_batch_frozen_deferred_total",
              sched.get("frozen_deferred"), {},
              help="Cohort admissions frozen outright at brownout L1+")
    p.gauge("dvt_batch_occupancy", sched.get("occupancy"), {},
            help="Fraction of the trailing window batch shards kept "
                 "an engine busy (the trough-filling duty cycle)")
    for mname, v in (batch.get("mfu_occupancy_weighted") or {}).items():
        p.gauge("dvt_batch_mfu_weighted", v, {"model": mname},
                help="serving MFU x engine compute occupancy — the "
                     "sustained-throughput MFU a saturating bulk job "
                     "should drive toward the interactive peak")


def _render_cascade_metrics(p, cas: dict) -> None:
    """Emit the dvt_cascade_* series from the reserved ``cascade``
    stats block (serve/cascade.py ``CascadeRouter.stats()``;
    docs/OBSERVABILITY.md tabulates these)."""
    lab = {"front": str(cas.get("front")), "big": str(cas.get("big"))}
    p.counter("dvt_cascade_escalations_total", cas.get("escalations"),
              lab, help="Requests a cheap tier escalated down the "
                        "chain (low confidence, tier errors, and "
                        "deadline-exhausted escalations)")
    for tier, n in sorted((cas.get("served") or {}).items()):
        p.counter("dvt_cascade_requests_total", n,
                  {**lab, "tier": tier},
                  help="Cascade requests answered, by the tier that "
                       "produced the answer")
    p.gauge("dvt_cascade_escalation_rate", cas.get("escalation_rate"),
            lab, help="Of requests the cheap tiers judged, the "
                      "fraction escalated — the live "
                      "cascade-economics gauge")
    # per-HOP threshold/agreement/calibrated series: each hop
    # calibrates tier-i-vs-big independently, so one scalar cannot
    # describe an N-tier chain
    for hop in (cas.get("hops") or []):
        hlab = {**lab, "hop": str(hop.get("hop")),
                "tier": str(hop.get("tier"))}
        p.gauge("dvt_cascade_threshold", hop.get("threshold"), hlab,
                help="Calibrated confidence threshold per hop (absent "
                     "while uncalibrated — fail-closed, that hop "
                     "escalates through)")
        cls_thr = hop.get("class_thresholds") or {}
        # None entries are fail-closed classes (measured-bad) — they
        # have no threshold value to chart
        vals = sorted(v for v in cls_thr.values() if v is not None)
        if vals:
            mid = vals[len(vals) // 2]
            p.gauge("dvt_cascade_class_threshold_min", vals[0], hlab,
                    help="Smallest per-class calibrated threshold at "
                         "this hop (per-class axis active)")
            p.gauge("dvt_cascade_class_threshold_median", mid, hlab,
                    help="Median per-class calibrated threshold at "
                         "this hop")
            p.gauge("dvt_cascade_class_threshold_max", vals[-1], hlab,
                    help="Largest per-class calibrated threshold at "
                         "this hop")
            p.gauge("dvt_cascade_class_thresholds", len(vals), hlab,
                    help="Classes with their own calibrated threshold "
                         "at this hop")
        p.gauge("dvt_cascade_hop_agreement", hop.get("agreement"),
                hlab, help="Tier-vs-big agreement over this hop's "
                           "live calibration sample")
        p.counter("dvt_cascade_hop_escalations_total",
                  hop.get("escalations"), hlab,
                  help="Requests this hop escalated onward")
    p.gauge("dvt_cascade_calibrated",
            1 if cas.get("calibrated") else 0, lab,
            help="1 while hop 0 holds a calibrated threshold")
    p.gauge("dvt_cascade_agreement", cas.get("agreement"), lab,
            help="Hop-0 tier-vs-big agreement over the live "
                 "calibration sample")
    p.counter("dvt_cascade_calibration_samples_total",
              cas.get("samples"), lab,
              help="Dual-run calibration samples taken")
    p.counter("dvt_cascade_forced_big_total", cas.get("forced_big"),
              lab, help="Requests routed straight to the big tier for "
                        "always-big QoS tenants")
    p.counter("dvt_cascade_recalibrations_total", cas.get("resets"),
              lab, help="Calibration drops after a tier version swap")
    p.counter("dvt_cascade_samples_paused_total",
              cas.get("samples_paused"), lab,
              help="Dual-run calibration samples skipped at brownout "
                   "L1+ (optional work shed first)")
    p.counter("dvt_cascade_degraded_served_total",
              cas.get("degraded_served"), lab,
              help="Sub-threshold front answers forced at brownout L2 "
                   "(marked X-DVT-Degraded)")
    p.gauge("dvt_cascade_restored",
            1 if cas.get("restored") else 0, lab,
            help="1 when this boot's calibration was restored from "
                 "the persisted ledger")
    p.counter("dvt_cascade_ledger_write_errors_total",
              cas.get("ledger_write_errors"), lab,
              help="Calibration-ledger appends that failed to reach "
                   "disk")
    for tier, hist in (cas.get("latency_hist") or {}).items():
        if hist:
            p.histogram("dvt_cascade_latency_seconds", hist,
                        {**lab, "tier": tier},
                        help="End-to-end cascade request latency by "
                             "answering tier (escalations land in "
                             "'big' and include the front attempt)")


def _render_brownout_metrics(p, bo: dict) -> None:
    """Emit the dvt_brownout_* series from the reserved ``brownout``
    stats block (serve/brownout.py ``BrownoutController.stats()``;
    docs/OBSERVABILITY.md tabulates these)."""
    p.gauge("dvt_brownout_level", bo.get("level"), {},
            help="Degradation ladder level: 0 normal, 1 shed-optional, "
                 "2 degrade-quality, 3 hard-shed")
    p.gauge("dvt_brownout_forced",
            -1 if bo.get("forced") is None else bo.get("forced"), {},
            help="Operator-pinned level (-1 = signals in control)")
    p.counter("dvt_brownout_transitions_total",
              bo.get("transitions_up"), {"direction": "up"},
              help="Edge-triggered ladder level changes")
    p.counter("dvt_brownout_transitions_total",
              bo.get("transitions_down"), {"direction": "down"})
    for lvl, n in sorted((bo.get("level_entries") or {}).items()):
        p.counter("dvt_brownout_level_entries_total", n,
                  {"level": str(lvl)},
                  help="Times the ladder entered each level going up")
    sig = bo.get("signals") or {}
    p.gauge("dvt_brownout_pressure_ms", sig.get("pressure_ms"), {},
            help="Max queue_depth x bucket exec EWMA across engines — "
                 "the engage signal")
    p.gauge("dvt_brownout_occupancy", sig.get("occupancy"), {},
            help="Max engine compute duty cycle at the last tick")
    p.gauge("dvt_brownout_shed_rate", sig.get("shed_rate"), {},
            help="Admission sheds / offered over the last tick window")
    p.counter("dvt_brownout_ticks_total", bo.get("ticks"), {},
              help="Ladder decisions taken")
    p.counter("dvt_brownout_signal_errors_total",
              bo.get("signal_errors"), {},
              help="Engine signal reads that raised mid-teardown")


def _render_engine_metrics(p, name: str, s: dict) -> None:
    """Emit one engine's dvt_serve_* series (shared by both shapes)."""
    lab = {"model": name}
    if s.get("weight_hbm_bytes") is not None:
        p.gauge("dvt_serve_weight_hbm_bytes", s["weight_hbm_bytes"],
                lab, help="Byte footprint of the served weights "
                          "(int8 models report the quantized size)")
    if s.get("param_shard_bytes") is not None:
        p.gauge("dvt_serve_param_shard_bytes", s["param_shard_bytes"],
                lab, help="PER-CHIP addressable weight bytes (a mesh "
                          "view prices one chip's shard, not the "
                          "global logical size)")
    mesh = s.get("mesh_shape")
    if isinstance(mesh, dict):
        for axis, size in mesh.items():
            p.gauge("dvt_serve_mesh_shape", size,
                    {**lab, "axis": str(axis)},
                    help="Serving mesh axis sizes (data/model); "
                         "absent off-mesh")
    p.counter("dvt_serve_requests_submitted_total", s["submitted"],
              lab, help="Requests entering submit (incl. shed)")
    p.counter("dvt_serve_requests_served_total", s["served"], lab,
              help="Requests served a model output")
    p.counter("dvt_serve_batches_total", s["batches"], lab,
              help="Executed batches (incl. retry executions)")
    p.counter("dvt_serve_compiles_total", s["compiles"], lab,
              help="Bucket program compiles")
    p.counter("dvt_serve_padded_images_total", s["padded_images"],
              lab, help="Pad rows executed beyond live requests")
    p.gauge("dvt_serve_queue_depth", s["queue_depth"], lab,
            help="Requests queued awaiting batch formation")
    routing = s.get("routing")
    if isinstance(routing, dict):
        p.gauge("dvt_serve_replicas", routing.get("replicas"), lab,
                help="Replica slots ever provisioned (append-only)")
        p.gauge("dvt_serve_live_replicas", routing.get("live_replicas"),
                lab, help="Non-retired replicas (the elastic capacity)")
        p.counter("dvt_serve_replicas_added_total",
                  routing.get("replicas_added"), lab,
                  help="Scale-up replica additions")
        p.counter("dvt_serve_replicas_removed_total",
                  routing.get("replicas_removed"), lab,
                  help="Scale-down replica retirements")
    adm = s.get("admission", {})
    h = s.get("health", {})
    p.counter("dvt_serve_shed_total", adm.get("shed_queue_full"),
              {**lab, "reason": "queue_full"},
              help="Requests shed at admission or formation")
    p.counter("dvt_serve_shed_total", adm.get("shed_deadline"),
              {**lab, "reason": "deadline"})
    p.counter("dvt_serve_shed_total", h.get("shed_shutdown"),
              {**lab, "reason": "shutdown"})
    p.counter("dvt_serve_batch_failures_total",
              h.get("batch_failures"), lab,
              help="Dispatched/drained cohorts that raised")
    p.counter("dvt_serve_retry_executions_total",
              h.get("retry_executions"), lab,
              help="Bisect-retry sub-cohort executions")
    p.counter("dvt_serve_quarantined_total", h.get("quarantined"),
              lab, help="Requests isolated as poison")
    p.counter("dvt_serve_exec_timeouts_total",
              h.get("exec_timeouts"), lab,
              help="In-flight windows fast-failed by the watchdog")
    p.counter("dvt_serve_watchdog_restarts_total",
              h.get("watchdog_restarts"), lab,
              help="Worker-thread restarts by supervision")
    p.gauge("dvt_serve_up",
            1 if h.get("can_serve") else 0, lab,
            help="1 while this engine can serve (healthz 200)")
    pipe = s.get("pipeline", {})
    p.gauge("dvt_serve_inflight", pipe.get("inflight"), lab,
            help="Dispatched-but-undrained batches")
    p.gauge("dvt_serve_occupancy", pipe.get("occupancy"), lab,
            help="Compute duty cycle over the trailing window — the "
                 "throughput-workload pressure signal")
    p.counter("dvt_serve_h2d_transfers_total",
              pipe.get("h2d_transfers"), lab,
              help="Staged-batch host-to-device transfers")
    p.counter("dvt_serve_h2d_bytes_total", pipe.get("h2d_bytes"),
              lab, help="Wire-format bytes shipped to the device")
    wl = s.get("workload")
    p.counter("dvt_serve_d2h_bytes_total", pipe.get("d2h_bytes"),
              {**lab, "workload": wl} if wl else lab,
              help="Output bytes the bulk device_get moved back "
                   "(generate's fused uint8 epilogue shrinks this 4x); "
                   "sum by (workload) for the per-workload series")
    for b, ms in (adm.get("exec_ewma_ms_by_bucket") or {}).items():
        p.gauge("dvt_serve_exec_ewma_seconds", ms / 1e3,
                {**lab, "bucket": b},
                help="Per-bucket batch execution EWMA")
    p.gauge("dvt_serve_img_per_sec", s.get("img_per_sec"), lab,
            help="Served images per second (post-warmup)")
    if "latency_hist" in s:
        p.histogram("dvt_serve_request_latency_seconds",
                    s["latency_hist"], lab,
                    help="Submit-to-result latency")
    mfu = s.get("mfu") or {}
    p.gauge("dvt_serve_mfu", mfu.get("serving_mfu"), lab,
            help="Model FLOPs utilization of the compute stage "
                 "(analytic FLOPs / measured compute time / peak)")
    p.counter("dvt_serve_compute_seconds_total",
              mfu.get("compute_s"), lab,
              help="Measured device-occupancy seconds")
    p.counter("dvt_serve_flops_total", mfu.get("flops_total"), lab,
              help="Analytic FLOPs executed")
    tr = s.get("trace") or {}
    p.counter("dvt_serve_traces_started_total", tr.get("started"),
              lab, help="Spans started")
    p.counter("dvt_serve_traces_finished_total", tr.get("finished"),
              lab, help="Spans sealed into the ring")
    p.counter("dvt_serve_slow_traces_total", tr.get("slow_sampled"),
              lab, help="Traces over the slow-request threshold")
    p.counter("dvt_serve_slow_suppressed_total",
              tr.get("slow_suppressed"), lab,
              help="Slow-trace emissions dropped at brownout L1+ "
                   "(ring and stage sums still record)")
    for stage, secs in (tr.get("stage_s_total") or {}).items():
        p.counter("dvt_serve_stage_seconds_total", secs,
                  {**lab, "stage": stage},
                  help="Cumulative per-stage span time")


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    # per-request trace state (set at the top of do_POST)
    _rid = None
    _span = None
    _raw_body = None  # raw payload bytes — the cache's content address
    _tier = None  # cascade tier that answered ("front"/"big")
    _degraded = False  # True when brownout degraded this answer
    # chunked-response state: edge._handle sets _edge_stream on its
    # shim; _reply_stream parks the body generator on _stream for the
    # event loop to pump (serve/edge.py), or drains inline without it
    _edge_stream = False
    _stream = None

    # -- plumbing ----------------------------------------------------------

    def setup(self):
        # StreamRequestHandler applies self.timeout to the connection
        # socket; a timeout on the request line makes the stdlib
        # handle_one_request close the connection, a timeout mid-body
        # raises TimeoutError in do_POST (answered 408 below)
        self.timeout = getattr(self.server, "socket_timeout_s", None)
        super().setup()

    def log_message(self, fmt, *args):  # route access logs off stderr spam
        if self.server.verbose:  # type: ignore[attr-defined]
            super().log_message(fmt, *args)

    def _reply(self, status: int, payload: dict,
               headers: dict | None = None):
        blob = json.dumps(payload).encode()
        self._reply_raw(status, blob, "application/json", headers)

    def _reply_raw(self, status: int, blob: bytes, ctype: str,
                   headers: dict | None = None):
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(blob)))
        if self._rid is not None:
            self.send_header(REQUEST_ID_HEADER, self._rid)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        self.wfile.write(blob)

    def _reply_stream(self, status: int, chunks,
                      ctype: str = "application/x-ndjson",
                      headers: dict | None = None):
        """Chunked-transfer reply: ``chunks`` is an iterator of body
        byte pieces.  Under the selector edge the generator is handed
        to the event loop, which frames and flushes each piece as the
        worker produces it — a result set bigger than any buffer bound
        streams in O(1) memory.  Under the threaded baseline server the
        same frames drain inline to the real socket."""
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        if self._rid is not None:
            self.send_header(REQUEST_ID_HEADER, self._rid)
        for k, v in (headers or {}).items():
            self.send_header(k, str(v))
        self.end_headers()
        if getattr(self, "_edge_stream", False):
            self._stream = chunks
            return
        for piece in chunks:
            if piece:
                self.wfile.write(_chunk_frame(piece))
        self.wfile.write(_CHUNK_END)

    def _body(self) -> dict:
        length = int(self.headers.get("Content-Length") or 0)
        if length <= 0:
            raise ServeError(400, "empty body")
        cap = getattr(self.server, "max_body_bytes",
                      DEFAULT_MAX_BODY_BYTES)
        if length > cap:
            # reject BEFORE allocating an attacker-sized buffer; the
            # connection is closed (the unread body would desync keep-alive)
            self.close_connection = True
            raise ServeError(
                413, f"body of {length} bytes exceeds the {cap}-byte cap")
        raw = self._raw_body = self.rfile.read(length)
        try:
            return json.loads(raw)
        except json.JSONDecodeError as e:
            raise ServeError(400, f"bad JSON: {e}") from e

    def _engine(self, body: dict, path_model: str | None = None):
        """Resolve the target model: the PATH param wins (a body
        "model" key must agree or 400); the control plane's routing
        table answers when one is wired, the flat registry otherwise.
        KeyError text passes through as the 404 body — ``e.args[0]``,
        not ``str(e)``, because KeyError's str() wraps the message in
        repr quotes."""
        name = body.get("model")
        if path_model is not None:
            if name is not None and name != path_model:
                raise ServeError(
                    400, f"body model '{name}' contradicts path model "
                         f"'{path_model}'")
            name = path_model
        plane = getattr(self.server, "plane", None)
        try:
            if plane is not None:
                model = plane.resolve(name)
                return model, plane.active_engine(model.name)
            model = self.server.registry.get(name)
        except KeyError as e:
            raise ServeError(404, e.args[0]) from e
        return model, self.server.engines[model.name]

    def _infer_row(self, body: dict, path_model: str | None = None):
        """Shared inference request path: decode → engine → row.  The
        model's workload adapter decodes first (DCGAN reads latent/seed
        from the body); None defers to the generic image decode.  A
        client that omits ``deadline_ms`` gets the workload's SLO-class
        default (generate's is longer — output-dominated batches)."""
        model, engine = self._engine(body, path_model)
        wl = getattr(model, "workload", None)
        if engine.faults.enabled:
            engine.faults.inject("decode")
        x = None
        if wl is not None:
            try:
                x = wl.decode(body, model)
            except ValueError as e:
                raise ServeError(400, str(e)) from e
        if x is None:
            x = _decode_pixels(body, model)
        if self._span is not None:
            self._span.mark("decode")
        deadline_ms = body.get("deadline_ms")
        if deadline_ms is None and wl is not None:
            deadline_ms = wl.slo.deadline_ms
        plane = getattr(self.server, "plane", None)
        cascade = getattr(self.server, "cascade", None)
        if cascade is not None and plane is not None \
                and cascade.serves(model.name):
            # cascade routing: the front tier answers when confident,
            # escalation to the big tier keeps the ORIGINAL deadline
            # budget.  Always-big QoS tenants skip the front entirely.
            qos = getattr(self.server, "qos", None)
            force_big = False
            if qos is not None:
                tenant = self.headers.get(TENANT_HEADER) or ""
                force_big = bool(qos.class_of(tenant).always_big)
            self._tier, result = cascade.infer(
                x, deadline_ms=deadline_ms, span=self._span,
                force_big=force_big)
            if cascade_degraded(self._tier):
                # brownout L2 forced a sub-threshold answer at some
                # hop: the tier header names that tier (it DID answer),
                # the degraded marker carries the quality caveat
                self._tier = cascade_base_tier(self._tier)
                self._degraded = True
        elif plane is not None:
            # plane routing: canary/shadow splits + cross-version
            # resubmission happen behind this call, not per-engine
            result = plane.infer(model.name, x,
                                 deadline_ms=deadline_ms,
                                 span=self._span)
        else:
            result = engine.infer(x, deadline_ms=deadline_ms,
                                  span=self._span)
        from deep_vision_tpu.serve.admission import Shed
        from deep_vision_tpu.serve.faults import Quarantined

        if isinstance(result, Shed):
            headers = None
            if result.retry_after_s:
                headers = {"Retry-After":
                           max(1, math.ceil(result.retry_after_s))}
            raise ServeError(429, f"shed: {result.reason} {result.detail}",
                             headers=headers)
        if isinstance(result, Quarantined):
            raise ServeError(
                500, f"quarantined: {result.reason} {result.detail}")
        return model, result

    @staticmethod
    def _shed_429(shed) -> ServeError:
        headers = None
        if shed.retry_after_s:
            headers = {"Retry-After": max(1, math.ceil(shed.retry_after_s))}
        return ServeError(429, f"shed: {shed.reason} {shed.detail}",
                          headers=headers)

    def _infer_route(self, path: str, body: dict,
                     path_model: str | None, debug: bool) -> bytes:  # dvtlint: hot
        """The inference POST path (every workload verb) with the edge
        services hooked in — returns the serialized 200 body.  Order
        matters:

          1. tenant quota (token bucket) — BEFORE the cache, so a hot
             payload can't make quotas unenforceable;
          2. response cache lookup — a hit returns the byte-identical
             serialized answer, skipping decode + engine + QoS pressure
             (a hit consumes no engine capacity);
          3. weighted shedding on engine queue pressure — misses only;
          4. engine inference, then cache insert — 200s only: every
             shed/quarantine/error path raises BEFORE the put, so a
             transient verdict is never replayed from cache.

        Debug-trace requests bypass the cache both ways (the attached
        span is per-request), and models without a ``params_digest``
        are never cached (no version identity → no safe invalidation).
        """
        span = self._span
        qos = getattr(self.server, "qos", None)
        bo = getattr(self.server, "brownout", None)
        tenant = ""
        t0 = time.monotonic()
        if qos is not None:
            tenant = self.headers.get(TENANT_HEADER) or ""
            shed = qos.check_quota(tenant)
            if shed is not None:
                raise self._shed_429(shed)
        model, engine = self._engine(body, path_model)
        # the verb names the workload; the model's task must serve it —
        # checked BEFORE cache and engine so a mis-verbed request never
        # costs a batch slot (or a poisoned cache entry)
        wl = WORKLOADS[path.rsplit("/", 1)[-1]]
        model_wl = getattr(model, "workload", None)
        if model_wl is not None and model_wl.verb != wl.verb:
            raise ServeError(400, f"'{model.name}' is a {model.task} "
                                  f"model; use /v1/{model_wl.verb}")
        cache = getattr(self.server, "response_cache", None)
        cascade = getattr(self.server, "cascade", None)
        if cascade is not None and not cascade.serves(model.name):
            cascade = None
        key = None
        if cache is not None and not debug \
                and self._raw_body is not None:
            # cascaded models key on the COMBINED front+big digest: a
            # hit is tier-agnostic (either tier's answer satisfies the
            # contract), and a reload of either tier invalidates
            digest = cascade.params_digest() if cascade is not None \
                else getattr(model, "params_digest", None)
            if digest is not None:
                key = ResponseCache.key(
                    path, model.name, digest,
                    str(getattr(model, "wire_dtype", "")),
                    str(getattr(model, "infer_dtype", "")),
                    payload_digest(self._raw_body))
                blob = cache.get(key)
                if blob is None and bo is not None and bo.at_least(2):
                    # brownout L2: an exact miss may still have an
                    # answer under a PRIOR params version — stale but
                    # well-formed beats a 429 when the engine is
                    # saturated; the response carries X-DVT-Degraded
                    blob = cache.get_stale(key)
                    if blob is not None:
                        self._degraded = True
                if blob is not None:
                    self._cache_hit = True
                    if span is not None:
                        span.mark("cache_hit")
                        span.mark("respond")
                    if qos is not None:
                        qos.record_served(
                            tenant, time.monotonic() - t0,
                            cache_hit=True)
                    return blob
        if qos is not None:
            adm = getattr(engine, "admission", None)
            shed = qos.check_pressure(
                tenant, getattr(engine, "queue_depth", 0),
                adm.max_queue if adm is not None else 0,
                floor=bo.qos_pressure_floor() if bo is not None
                else 0.0)
            if shed is not None:
                raise self._shed_429(shed)
        _, row = self._infer_row(body, path_model)
        payload = wl.respond(model, body, row)
        if span is not None:
            span.mark("respond")
            if debug:
                payload["trace"] = span.to_dict()
        blob = json.dumps(payload).encode()
        if key is not None and wl.cacheable(len(blob)):
            # during a canary window plane.infer may have routed this
            # request to the CANDIDATE — filing that answer under the
            # active version's digest would poison the cache, so
            # inserts pause until the canary resolves (for a cascade:
            # a canary on EITHER tier)
            plane = getattr(self.server, "plane", None)
            paused = cascade.canary_active() if cascade is not None \
                else (plane is not None
                      and plane.canary_active(model.name))
            if not paused:
                cache.put(key, blob, tier=self._tier)
        if qos is not None:
            qos.record_served(tenant, time.monotonic() - t0)
        return blob

    # -- routes ------------------------------------------------------------

    def _edge_blocks(self) -> dict:
        """The front-end's own stats blocks ("edge", "response_cache",
        "qos") — present only when the selector edge / cache / QoS are
        wired, so the legacy flat shape stays byte-identical without
        them.  Keys are reserved: no model may be named after them."""
        out = {}
        srv = self.server
        edge_stats = getattr(srv, "stats", None)
        if callable(edge_stats):
            out["edge"] = edge_stats()
        rcache = getattr(srv, "response_cache", None)
        if rcache is not None:
            out["response_cache"] = rcache.stats()
        qos = getattr(srv, "qos", None)
        if qos is not None:
            out["qos"] = qos.stats()
        bo = getattr(srv, "brownout", None)
        if bo is not None:
            out["brownout"] = bo.stats()
        return out

    def _add_batch_block(self, stats: dict) -> None:
        """Attach the offline batch tier's ``batch`` stats block (jobs
        store + scheduler + occupancy-weighted MFU) when the tier is
        wired.  Like "edge", the key is reserved: no model may be named
        "batch".  The weighted MFU multiplies each engine's serving MFU
        (compute-stage efficiency) by its rolling occupancy (how much
        of the wall clock that compute actually filled) — the
        sustained-throughput figure a saturating bulk job should push
        toward the interactive MFU."""
        store = getattr(self.server, "jobs", None)
        if store is None:
            return
        sched = getattr(self.server, "batch_sched", None)
        block = {"jobs": store.stats(),
                 "scheduler": sched.stats() if sched is not None
                 else None}
        models = stats.get("models")
        if isinstance(models, dict):
            eng_stats = {n: e.get("engine") for n, e in models.items()}
        else:
            eng_stats = {n: s for n, s in stats.items()
                         if isinstance(s, dict) and "pipeline" in s}
        from deep_vision_tpu.obs.mfu import round_mfu

        weighted = {}
        for name, s in eng_stats.items():
            if not isinstance(s, dict):
                continue
            mfu = (s.get("mfu") or {}).get("serving_mfu")
            occ = (s.get("pipeline") or {}).get("occupancy")
            if mfu is not None and occ is not None:
                weighted[name] = round_mfu(mfu * occ)
        block["mfu_occupancy_weighted"] = weighted
        stats["batch"] = block

    def _add_cascade_block(self, stats: dict) -> None:
        """Attach the cascade router's reserved ``cascade`` stats block
        (escalation counters, live threshold/agreement, per-tier
        latency) when one is wired.  Like "edge"/"batch", the key is
        reserved: no model may be named "cascade"."""
        cascade = getattr(self.server, "cascade", None)
        if cascade is not None:
            stats["cascade"] = cascade.stats()

    def _models_with_cascade(self, models: dict) -> dict:
        """Annotate /v1/models entries for chain members with the
        router's ``cascade`` block (chain, hop role, threshold source)
        — models outside the chain pass through untouched."""
        cascade = getattr(self.server, "cascade", None)
        if cascade is None:
            return models
        for name, entry in models.items():
            if not isinstance(entry, dict):
                continue
            block = cascade.describe_member(name)
            if block is not None:
                entry["cascade"] = block
        return models

    def _job_results_ndjson(self, job_id: str):
        """The results stream body: one JSON line per completed item
        (contiguous shard prefix, manifest order) and a trailing
        ``{"status": ...}`` line clients use to tell "all results
        delivered" from "drained so far"."""
        store = self.server.jobs
        for idx, item in store.results_items(job_id):
            yield json.dumps({"index": idx, **item}).encode() + b"\n"
        yield json.dumps({"status": store.status(job_id)}).encode() \
            + b"\n"

    def _jobs_get(self, path: str) -> None:
        store = getattr(self.server, "jobs", None)
        if store is None:
            self._reply(503, {"error": "batch jobs are not enabled "
                                       "(cli.serve --jobs-dir ...)"})
            return
        parts = path.split("/")
        if len(parts) == 3:  # /v1/jobs
            self._reply(200, {"jobs": store.jobs()})
            return
        try:
            status = store.status(parts[3])
        except KeyError:
            self._reply(404, {"error": f"no job '{parts[3]}'"})
            return
        if len(parts) == 4:  # /v1/jobs/<id>
            self._reply(200, status)
        elif len(parts) == 5 and parts[4] == "results":
            self._reply_stream(200, self._job_results_ndjson(parts[3]))
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def _jobs_post(self) -> tuple:
        """POST /v1/jobs → (status, payload): validate the manifest,
        resolve the target model, persist the job, kick the scheduler.
        202: the reply is a job HANDLE — results arrive via the
        trough-filling drain, not this request."""
        store = getattr(self.server, "jobs", None)
        if store is None:
            return 503, {"error": "batch jobs are not enabled "
                                  "(cli.serve --jobs-dir ...)"}
        body = self._body()
        items = body.get("items")
        if not isinstance(items, list) or not items:
            raise ServeError(
                400, "manifest 'items' must be a non-empty list of "
                     "request bodies")
        shard_size = body.get("shard_size")
        if shard_size is not None:
            try:
                shard_size = int(shard_size)
            except (TypeError, ValueError) as e:
                raise ServeError(
                    400, f"bad shard_size: {body['shard_size']!r}") from e
            if shard_size <= 0:
                raise ServeError(400, "shard_size must be >= 1")
        model, _ = self._engine(body)
        wl = getattr(model, "workload", None)
        verb = wl.verb if wl is not None else "classify"
        view = store.submit(model.name, verb, items, shard_size)
        sched = getattr(self.server, "batch_sched", None)
        if sched is not None:
            sched.kick()
        return 202, view

    def _live_engines(self) -> dict:
        """name → the engine taking that model's traffic right now:
        the plane's ACTIVE versions when one is wired (a mid-reload
        candidate never answers healthz), the static dict otherwise."""
        plane = getattr(self.server, "plane", None)
        if plane is not None:
            return plane.active_engines()
        return self.server.engines

    def do_GET(self):
        path, _, query = self.path.partition("?")
        plane = getattr(self.server, "plane", None)
        if path == "/v1/healthz":
            engines = self._live_engines()
            if getattr(self.server, "draining", False):
                # draining outranks engine health: traffic must move
                # away BEFORE the engines finish their in-flight work
                self._reply(503, {"status": "draining",
                                  "models": self.server.registry.names()})
                return
            reports = {name: eng.health_report()
                       for name, eng in engines.items()}
            # each engine decides its own serve-ability: a single
            # engine only while fully OK, a ReplicatedEngine while ANY
            # replica is routable (per-replica states are in its report)
            healthy = all(r.get("can_serve", r["state"] == "ok")
                          for r in reports.values())
            self._reply(200 if healthy else 503,
                        {"status": "ok" if healthy else "unhealthy",
                         "models": self.server.registry.names(),
                         "engines": reports})
        elif path == "/v1/stats":
            deploy = getattr(self.server, "deploy", None)
            if plane is not None:
                stats = plane.stats()
                if deploy is not None:
                    stats["deploy"] = deploy.stats()
                stats.update(self._edge_blocks())
                self._add_batch_block(stats)
                self._add_cascade_block(stats)
                self._reply(200, stats)
                return
            stats = {name: eng.stats()
                     for name, eng in self.server.engines.items()}
            stats.update(self._edge_blocks())
            self._add_batch_block(stats)
            self._add_cascade_block(stats)
            self._reply(200, stats)
        elif path == "/v1/models":
            if plane is not None:
                self._reply(200, {"models": self._models_with_cascade(
                    plane.models())})
                return
            self._reply(200, {"models": self._models_with_cascade({
                name: {"model": self.server.registry.get(name).describe()}
                for name in self.server.registry.names()})})
        elif path == "/metrics":
            if plane is not None:
                stats = plane.stats()
                deploy = getattr(self.server, "deploy", None)
                if deploy is not None:
                    stats["deploy"] = deploy.stats()
            else:
                stats = {name: eng.stats()
                         for name, eng in self.server.engines.items()}
            stats.update(self._edge_blocks())
            self._add_batch_block(stats)
            self._add_cascade_block(stats)
            text = render_serve_metrics(stats)
            self._reply_raw(
                200, text.encode(),
                "text/plain; version=0.0.4; charset=utf-8")
        elif path == "/v1/jobs" or path.startswith("/v1/jobs/"):
            self._jobs_get(path)
        elif path == "/v1/brownout":
            bo = getattr(self.server, "brownout", None)
            if bo is None:
                self._reply(503, {"error": "brownout controller is not "
                                           "enabled (cli.serve "
                                           "--brownout)"})
                return
            self._reply(200, bo.stats())
        elif path == "/v1/traces":
            params = parse_qs(query)
            n = int(params.get("n", ["32"])[0])
            tracer = getattr(self.server, "tracer", None)
            self._reply(200, {
                "traces": tracer.recent(n) if tracer is not None else [],
                "summary": tracer.summary() if tracer is not None
                else None})
        else:
            parts = path.split("/")
            # /v1/deploy/<name>/history: the deployment ledger
            if len(parts) == 5 and parts[1] == "v1" \
                    and parts[2] == "deploy" and parts[4] == "history":
                self._reply(*self._deploy_history(
                    parts[3], parse_qs(query)))
                return
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        path, _, query = self.path.partition("?")
        debug = parse_qs(query).get("debug", ["0"])[0] not in ("", "0")
        # request id: the edge's header wins (a gateway hop forwards its
        # own, keeping one id across the whole path); else minted here
        self._rid = self.headers.get(REQUEST_ID_HEADER) \
            or new_request_id()
        tracer = getattr(self.server, "tracer", None)
        span = self._span = tracer.start(self._rid, origin="recv") \
            if tracer is not None else None
        try:
            if path == "/v1/drain":
                self._reply(200, self._drain())
                return
            if path == "/v1/jobs":
                self._reply(*self._jobs_post())
                return
            if path == "/v1/brownout":
                self._reply(*self._brownout_post())
                return
            path_model = None
            parts = path.split("/")
            # /v1/models/<name>/<verb>: the multi-model and lifecycle
            # routes (the name segment never contains "/")
            if len(parts) == 5 and parts[1] == "v1" \
                    and parts[2] == "models":
                path_model, verb = parts[3], parts[4]
                if verb in LIFECYCLE_VERBS:
                    self._reply(*self._lifecycle(path_model, verb))
                    return
                if verb in infer_verbs():
                    path = f"/v1/{verb}"
            if len(parts) == 5 and parts[1] == "v1" \
                    and parts[2] == "deploy" and parts[4] == "revert":
                self._reply(*self._deploy_revert(parts[3]))
                return
            if path not in infer_paths():
                self._body()  # consistent 400 on empty/oversized bodies
                self._reply(404, {
                    "error": f"no route {self.path}",
                    "supported_verbs": sorted(
                        infer_verbs() + LIFECYCLE_VERBS)})
                return
            body = self._body()
            self._cache_hit = False
            self._tier = None
            self._degraded = False
            blob = self._infer_route(path, body, path_model, debug)
            # X-DVT-Cache lets clients (and the trace bench) split
            # hit/miss latency without a debug span per request;
            # X-DVT-Tier reports which cascade tier answered;
            # X-DVT-Degraded marks brownout-degraded answers
            headers = {}
            if self._cache_hit:
                headers["X-DVT-Cache"] = "hit"
            if self._tier is not None:
                headers[TIER_HEADER] = self._tier
            if self._degraded:
                headers[DEGRADED_HEADER] = "1"
            self._reply_raw(200, blob, "application/json",
                            headers=headers or None)
        except ServeError as e:
            self._reply(e.status, {"error": str(e)}, headers=e.headers)
        except TimeoutError:
            # client stalled mid-body: answer 408 and drop the
            # connection instead of pinning this handler thread
            self.close_connection = True
            self._reply(408, {"error": "timed out reading request body"})
        except Exception as e:  # noqa: BLE001 — surface, don't kill worker
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
        finally:
            # this handler created the span, so it seals it — error
            # paths included (finish is idempotent and never raises)
            if tracer is not None:
                tracer.finish(span)
            self._span = None
            self._rid = None

    def _drain(self) -> dict:
        """Flip healthz to draining, then finish admitted work.

        The flag flips BEFORE any engine stops so probes see 503 while
        in-flight requests are still completing; draining twice is a
        no-op reply.  An empty body is fine — the route predates the
        body parse precisely so `curl -XPOST .../v1/drain` works."""
        length = int(self.headers.get("Content-Length") or 0)
        body = self._body() if length > 0 else {}
        deadline = float(body.get("drain_deadline_s", 10.0))
        srv = self.server
        with srv.drain_lock:  # type: ignore[attr-defined]  # dvtlint: lock=serve.http.Server.drain_lock
            already = getattr(srv, "draining", False)
            srv.draining = True
            if not already:
                plane = getattr(srv, "plane", None)
                if plane is not None:
                    # the plane drains every version (and joins any
                    # in-flight reload worker) — not just the actives
                    plane.stop(drain_deadline=deadline)
                else:
                    for eng in srv.engines.values():
                        eng.stop(drain_deadline=deadline)
        return {"status": "draining", "already_draining": already,
                "drain_deadline_s": deadline}

    def _brownout_post(self) -> tuple:
        """POST /v1/brownout → (status, payload): the operator
        override.  Body {"force": 0..3} pins the ladder at a level
        (pre-shedding load before a known spike, or testing the
        degraded path in prod); {"force": null} returns control to the
        signals.  The reply is the controller's live stats so the
        operator sees the resulting state in the same exchange."""
        bo = getattr(self.server, "brownout", None)
        if bo is None:
            return 503, {"error": "brownout controller is not enabled "
                                  "(cli.serve --brownout)"}
        body = self._body()
        if "force" not in body:
            raise ServeError(400, "body needs 'force': 0..3 to pin the "
                                  "ladder, null to release")
        force = body["force"]
        if force is not None:
            try:
                force = int(force)
            except (TypeError, ValueError) as e:
                raise ServeError(
                    400, f"bad force level: {body['force']!r}") from e
        bo.force(force)
        return 200, bo.stats()

    def _lifecycle(self, name: str, verb: str) -> tuple:
        """POST /v1/models/<name>/reload|promote|rollback → (status,
        payload).  Control-plane-only routes: a plain engine dict has
        no version table to act on."""
        plane = getattr(self.server, "plane", None)
        if plane is None:
            return 503, {"error": f"/v1/models/{name}/{verb} needs the "
                                  f"model control plane (cli.serve "
                                  f"--models ...)"}
        length = int(self.headers.get("Content-Length") or 0)
        body = self._body() if length > 0 else {}
        try:
            if verb == "reload":
                out = plane.reload(name,
                                   force=bool(body.get("force", False)),
                                   wait=bool(body.get("wait", False)))
            elif verb == "promote":
                out = plane.promote(name)
            else:
                out = plane.rollback(name)
        except KeyError as e:
            return 404, {"error": e.args[0]}
        return (409 if out.get("status") in ("refused", "in_progress")
                else 200), out

    def _deploy_history(self, name: str, params: dict) -> tuple:
        """GET /v1/deploy/<name>/history → (status, payload): the
        ledger tail for one model, 503 without a deploy pipeline."""
        deploy = getattr(self.server, "deploy", None)
        if deploy is None:
            return 503, {"error": f"/v1/deploy/{name}/history needs the "
                                  f"deploy pipeline (cli.serve --watch "
                                  f"or --max-replicas)"}
        n = int(params.get("n", ["0"])[0]) or None
        try:
            entries = deploy.entries(name, n)
        except KeyError as e:
            return 404, {"error": e.args[0]}
        return 200, {"model": name, "entries": entries}

    def _deploy_revert(self, name: str) -> tuple:
        """POST /v1/deploy/<name>/revert → (status, payload): the
        pipeline's status-map contract — reverted 200, a lifecycle in
        flight or nothing to revert to 409, boot failure 500."""
        deploy = getattr(self.server, "deploy", None)
        if deploy is None:
            return 503, {"error": f"/v1/deploy/{name}/revert needs the "
                                  f"deploy pipeline (cli.serve --watch "
                                  f"or --max-replicas)"}
        if int(self.headers.get("Content-Length") or 0) > 0:
            self._body()  # drain: revert takes no parameters
        try:
            out = deploy.revert(name)
        except KeyError as e:
            return 404, {"error": e.args[0]}
        status = out.get("status")
        if status in ("refused", "in_progress"):
            return 409, out
        return (500 if status == "failed" else 200), out

    # response building lives on the workload adapters now
    # (serve/workloads.py respond()) — the old _classify/_detect bodies
    # moved there verbatim when the verb set became registry-driven


class ServeServer:
    """HTTP front-end wired to a registry + one engine per model.

    ``edge=True`` (default) runs the selector event loop from
    ``serve/edge.py`` — keep-alive, pipelining, bounded connections;
    ``edge=False`` keeps the original thread-per-request
    ``ThreadingHTTPServer`` (the A/B baseline in docs/PERF.md).  Both
    carry the same context attributes, so ``self.httpd`` stays the
    single handle tests and the CLI reach through."""

    def __init__(self, registry, engines: dict, host: str = "127.0.0.1",
                 port: int = 0, verbose: bool = False,
                 max_body_bytes: int = DEFAULT_MAX_BODY_BYTES,
                 socket_timeout_s: float | None = 30.0,
                 tracer=None, plane=None, deploy=None, edge: bool = True,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 http_workers: int = 8, response_cache=None, qos=None,
                 jobs=None, batch_sched=None, cascade=None,
                 brownout=None):
        if edge:
            self.httpd = EdgeServer((host, port), _Handler,
                                    max_connections=max_connections,
                                    workers=http_workers, name="serve")
        else:
            self.httpd = ThreadingHTTPServer((host, port), _Handler)
        self.httpd.registry = registry
        self.httpd.engines = engines
        # model control plane (serve/models.py): when wired, routing /
        # stats / lifecycle endpoints go through it; None keeps the
        # original single-version behaviour byte-for-byte
        self.httpd.plane = plane
        # deploy pipeline (deploy/__init__.py): ledger + watcher +
        # autoscalers behind /v1/deploy/... and the dvt_deploy_* series
        self.httpd.deploy = deploy
        self.httpd.verbose = verbose
        self.httpd.max_body_bytes = max_body_bytes
        self.httpd.socket_timeout_s = socket_timeout_s
        self.httpd.draining = False
        self.httpd.drain_lock = new_lock("serve.http.Server.drain_lock")
        # optional edge services (None = off): the content-addressed
        # response cache and per-tenant QoS, hooked into _infer_route
        self.httpd.response_cache = response_cache
        self.httpd.qos = qos
        # offline batch tier (None = off): the job store behind
        # /v1/jobs and the trough-filling scheduler it kicks
        self.httpd.jobs = jobs
        self.httpd.batch_sched = batch_sched
        # confidence-routed cascade (serve/cascade.py, None = off):
        # requests naming its big model route front-first with
        # calibrated escalation; needs the plane (both tiers live there)
        self.httpd.cascade = cascade
        # brownout ladder (serve/brownout.py, None = off): the request
        # path probes it for the L2 stale-cache/degraded answers and
        # the L3 QoS pressure floor; /v1/brownout exposes force/stats
        self.httpd.brownout = brownout
        if tracer is None:
            # share the first engine's tracer so handler-created spans
            # land in the same ring /v1/traces reads
            for eng in engines.values():
                tracer = getattr(eng, "tracer", None)
                if tracer is not None:
                    break
        self.httpd.tracer = tracer
        self._thread: threading.Thread | None = None

    @property
    def host(self) -> str:
        return self.httpd.server_address[0]

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_forever(self):
        self.httpd.serve_forever()

    def start_background(self) -> "ServeServer":
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="serve-http", daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
