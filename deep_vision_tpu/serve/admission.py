"""Admission control: shed doomed work at the door, not after the queue.

Two bounds, both checked at submit time (and deadlines re-checked at
batch-formation time, so a request that expired while queued is dropped
rather than executed late):

  * queue depth — beyond ``max_queue`` the engine is over capacity and
    every additional request only adds latency for everyone; reject
    immediately so the client can retry against another replica.
  * deadline feasibility — if ``now + estimated_service_time`` already
    exceeds the request's deadline, executing it wastes a batch slot on
    an answer nobody will read.  The estimate is the batcher's drain
    window plus PER-BUCKET EWMAs of recent batch execution time — a
    request that will pad into the 32-bucket is judged by the
    32-bucket's history, not by a global average dragged down by
    1-image batches — scaled by the pipelined engine's current
    in-flight depth (each outstanding batch adds roughly one more
    execution before this request's batch reaches the device).
    Pessimistic before any batch has run: only already-expired
    deadlines are shed.
"""

from __future__ import annotations

import dataclasses
import threading

from deep_vision_tpu.analysis.sanitizer import new_lock
import time

from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.serve.admission")


@dataclasses.dataclass
class Shed:
    """Result delivered to a request the engine refused to execute.

    ``retry_after_s`` is a hint for the client (surfaced as the HTTP
    ``Retry-After`` header on 429s): for ``queue_full`` it is the
    current estimated service time — when the backlog should have
    drained enough to admit a retry.  Deadline sheds carry no hint (a
    retry can't make a deadline the first attempt already missed), nor
    do shutdown sheds (this replica is going away)."""

    reason: str          # "queue_full" | "deadline" | "shutdown"
    detail: str = ""
    retry_after_s: float | None = None

    def __bool__(self):  # `if result:` reads as "was served"
        return False


class AdmissionController:
    """``name`` tags the controller with the model it accounts for: the
    control plane (serve/models.py) shares ONE controller across every
    version of one model name, so the per-bucket EWMAs — and the
    admitted/shed counters — survive a hot reload instead of resetting
    with the new version's engine."""

    def __init__(self, max_queue: int = 256, max_wait_ms: float = 5.0,
                 ewma_alpha: float = 0.2, name: str | None = None):
        self.name = name
        self.max_queue = max_queue
        self._max_wait_s = max_wait_ms / 1e3
        self._alpha = ewma_alpha
        self._exec_ewma_s: float | None = None      # all-bucket fallback
        self._bucket_ewma_s: dict[int, float] = {}  # bucket → EWMA
        # replicas able to absorb work right now: an int, or a zero-arg
        # callable the ReplicatedEngine wires to its routing mask (DEAD
        # replicas drop out of the divisor as they drop out of routing;
        # replicas added/removed at runtime move it the same way)
        self._free_replicas = 1
        # provisioned replicas (DEAD included) — the /v1/stats capacity
        # gauge; None falls back to the free-replica divisor
        self._live_replicas = None
        self._lock = new_lock("serve.admission.AdmissionController._lock")
        self.shed_queue_full = 0  # guarded-by: _lock
        self.shed_deadline = 0  # guarded-by: _lock
        self.admitted = 0  # guarded-by: _lock
        # edge-triggered overload logging: one line when queue_full
        # shedding STARTS, one when an admit clears it — never a line
        # per shed request (a saturated engine must not also saturate
        # its own log)
        self._overloaded = False  # guarded-by: _lock

    def observe_exec(self, seconds: float, bucket: int | None = None):
        """Feed one batch's execution time into the EWMAs (global + the
        bucket it actually ran in)."""
        with self._lock:
            if self._exec_ewma_s is None:
                self._exec_ewma_s = seconds
            else:
                self._exec_ewma_s += self._alpha * (seconds -
                                                    self._exec_ewma_s)
            if bucket is not None:
                prev = self._bucket_ewma_s.get(bucket)
                self._bucket_ewma_s[bucket] = seconds if prev is None \
                    else prev + self._alpha * (seconds - prev)

    def set_free_replicas(self, provider):
        """Wire the replica divisor: an int, or a zero-arg callable
        returning the count of replicas currently routable (≥ 1 is
        enforced at read time so a fully-DEAD set stays finite)."""
        self._free_replicas = provider

    def _replica_divisor(self) -> int:
        # resolved OUTSIDE self._lock: the callable may read engine state
        n = self._free_replicas() if callable(self._free_replicas) \
            else self._free_replicas
        return max(1, int(n))

    def set_live_replicas(self, provider):
        """Wire the provisioned-replica gauge (int or zero-arg
        callable): how many replicas exist right now, DEAD included —
        what the autoscaler changes.  Unset, it mirrors the free-replica
        divisor (a single engine is one replica either way)."""
        self._live_replicas = provider

    def _live_count(self) -> int:
        p = self._live_replicas
        if p is None:
            return self._replica_divisor()
        n = p() if callable(p) else p
        return max(0, int(n))

    def estimated_service_s(self, bucket: int | None = None,
                            inflight: int = 0) -> float:
        """Worst-case time-to-result for a request admitted right now: a
        full drain window, one execution of the bucket it will likely
        run in (global EWMA until that bucket has history), plus one
        more execution per batch already in the pipeline ahead of it.
        With N free replicas the outstanding executions drain N-wide,
        so the exec term divides by N (the drain window doesn't — batch
        formation is one shared queue either way)."""
        n = self._replica_divisor()
        with self._lock:
            e = self._bucket_ewma_s.get(bucket) if bucket is not None \
                else None
            if e is None:
                e = self._exec_ewma_s or 0.0
            return self._max_wait_s + ((1 + max(0, inflight)) * e) / n

    def bucket_ewma_s(self, bucket: int | None = None) -> float | None:
        """Raw exec EWMA for ``bucket`` (global fallback, None before
        any batch has run) — the watchdog's exec-timeout base."""
        with self._lock:
            e = self._bucket_ewma_s.get(bucket) if bucket is not None \
                else None
            return e if e is not None else self._exec_ewma_s

    def admit(self, queue_depth: int, deadline: float | None,
              now: float | None = None, bucket: int | None = None,
              inflight: int = 0) -> Shed | None:
        """None = admitted; a ``Shed`` = rejected (reason inside)."""
        if queue_depth >= self.max_queue:
            with self._lock:
                self.shed_queue_full += 1
                entered = not self._overloaded
                self._overloaded = True
            if entered:
                event(_log, "overload_shed_start",
                      queue_depth=queue_depth, max_queue=self.max_queue,
                      inflight=inflight)
            return Shed("queue_full",
                        f"queue depth {queue_depth} >= {self.max_queue}",
                        retry_after_s=self.estimated_service_s(
                            bucket, inflight))
        with self._lock:
            cleared = self._overloaded
            self._overloaded = False
        if cleared:
            event(_log, "overload_cleared", queue_depth=queue_depth,
                  shed_queue_full=self.shed_queue_full)
        if deadline is not None:
            now = time.monotonic() if now is None else now
            est = self.estimated_service_s(bucket, inflight)
            if now + est > deadline:
                with self._lock:
                    self.shed_deadline += 1
                return Shed("deadline",
                            f"needs ~{est * 1e3:.1f}ms, "
                            f"deadline in {(deadline - now) * 1e3:.1f}ms")
        return None

    def record_admit(self):
        """Count one admitted request (called by the engine AFTER a None
        verdict from ``admit`` — the controller can't count it itself
        because ``admit`` doesn't know whether the caller enqueued).
        Per-model queue accounting for the control plane: admitted −
        served across every version of a name = requests the plane owes
        an answer."""
        with self._lock:
            self.admitted += 1

    def expired(self, deadline: float | None,
                now: float | None = None) -> Shed | None:
        """Batch-formation-time re-check: queued past its deadline?"""
        if deadline is None:
            return None
        now = time.monotonic() if now is None else now
        if now > deadline:
            with self._lock:
                self.shed_deadline += 1
            return Shed("deadline",
                        f"expired {(now - deadline) * 1e3:.1f}ms ago in "
                        f"queue")
        return None

    def stats(self) -> dict:
        n = self._replica_divisor()  # outside the lock, see above
        live = self._live_count()
        with self._lock:
            out = {"shed_queue_full": self.shed_queue_full,
                   "shed_deadline": self.shed_deadline,
                   "admitted": self.admitted,
                   "exec_ewma_ms": (self._exec_ewma_s or 0.0) * 1e3,
                   "exec_ewma_ms_by_bucket": {
                       str(b): round(v * 1e3, 3)
                       for b, v in sorted(self._bucket_ewma_s.items())},
                   "free_replicas": n,
                   "live_replicas": live,
                   "max_queue": self.max_queue}
        if self.name is not None:
            out["name"] = self.name
        return out
