"""Admission control: shed doomed work at the door, not after the queue.

Two bounds, both checked at submit time (and deadlines re-checked at
batch-formation time, so a request that expired while queued is dropped
rather than executed late):

  * queue depth — beyond ``max_queue`` the engine is over capacity and
    every additional request only adds latency for everyone; reject
    immediately so the client can retry against another replica.
  * deadline feasibility — if ``now + estimated_service_time`` already
    exceeds the request's deadline, executing it wastes a batch slot on
    an answer nobody will read.  The estimate is the batcher's drain
    window plus PER-BUCKET EWMAs of recent batch execution time — a
    request that will pad into the 32-bucket is judged by the
    32-bucket's history, not by a global average dragged down by
    1-image batches — scaled by the pipelined engine's current
    in-flight depth (each outstanding batch adds roughly one more
    execution before this request's batch reaches the device).
    Pessimistic before any batch has run: only already-expired
    deadlines are shed.
"""

from __future__ import annotations

import dataclasses
import threading

from deep_vision_tpu.analysis.sanitizer import new_lock
import time

from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.serve.admission")


@dataclasses.dataclass
class Shed:
    """Result delivered to a request the engine refused to execute.

    ``retry_after_s`` is a hint for the client (surfaced as the HTTP
    ``Retry-After`` header on 429s): for ``queue_full`` it is the
    current estimated service time — when the backlog should have
    drained enough to admit a retry.  Deadline sheds carry the same
    bucket-EWMA estimate: the first attempt's deadline is dead either
    way, but the estimate is when a FRESH deadline stops being doomed
    on arrival, so clients back off instead of immediately re-offering
    work the estimator will shed again.  Shutdown sheds carry no hint
    (this replica is going away)."""

    reason: str   # "queue_full" | "deadline" | "shutdown" | "quota" | "priority"
    detail: str = ""
    retry_after_s: float | None = None

    def __bool__(self):  # `if result:` reads as "was served"
        return False


class AdmissionController:
    """``name`` tags the controller with the model it accounts for: the
    control plane (serve/models.py) shares ONE controller across every
    version of one model name, so the per-bucket EWMAs — and the
    admitted/shed counters — survive a hot reload instead of resetting
    with the new version's engine."""

    def __init__(self, max_queue: int = 256, max_wait_ms: float = 5.0,
                 ewma_alpha: float = 0.2, name: str | None = None):
        self.name = name
        self.max_queue = max_queue
        self._max_wait_s = max_wait_ms / 1e3
        self._alpha = ewma_alpha
        self._exec_ewma_s: float | None = None      # all-bucket fallback
        self._bucket_ewma_s: dict[int, float] = {}  # bucket → EWMA
        # replicas able to absorb work right now: an int, or a zero-arg
        # callable the ReplicatedEngine wires to its routing mask (DEAD
        # replicas drop out of the divisor as they drop out of routing;
        # replicas added/removed at runtime move it the same way)
        self._free_replicas = 1
        # provisioned replicas (DEAD included) — the /v1/stats capacity
        # gauge; None falls back to the free-replica divisor
        self._live_replicas = None
        self._lock = new_lock("serve.admission.AdmissionController._lock")
        self.shed_queue_full = 0  # guarded-by: _lock
        self.shed_deadline = 0  # guarded-by: _lock
        self.admitted = 0  # guarded-by: _lock
        # edge-triggered overload logging: one line when queue_full
        # shedding STARTS, one when an admit clears it — never a line
        # per shed request (a saturated engine must not also saturate
        # its own log)
        self._overloaded = False  # guarded-by: _lock

    def observe_exec(self, seconds: float, bucket: int | None = None):
        """Feed one batch's execution time into the EWMAs (global + the
        bucket it actually ran in)."""
        with self._lock:
            if self._exec_ewma_s is None:
                self._exec_ewma_s = seconds
            else:
                self._exec_ewma_s += self._alpha * (seconds -
                                                    self._exec_ewma_s)
            if bucket is not None:
                prev = self._bucket_ewma_s.get(bucket)
                self._bucket_ewma_s[bucket] = seconds if prev is None \
                    else prev + self._alpha * (seconds - prev)

    def set_free_replicas(self, provider):
        """Wire the replica divisor: an int, or a zero-arg callable
        returning the count of replicas currently routable (≥ 1 is
        enforced at read time so a fully-DEAD set stays finite)."""
        self._free_replicas = provider

    def _replica_divisor(self) -> int:
        # resolved OUTSIDE self._lock: the callable may read engine state
        n = self._free_replicas() if callable(self._free_replicas) \
            else self._free_replicas
        return max(1, int(n))

    def set_live_replicas(self, provider):
        """Wire the provisioned-replica gauge (int or zero-arg
        callable): how many replicas exist right now, DEAD included —
        what the autoscaler changes.  Unset, it mirrors the free-replica
        divisor (a single engine is one replica either way)."""
        self._live_replicas = provider

    def _live_count(self) -> int:
        p = self._live_replicas
        if p is None:
            return self._replica_divisor()
        n = p() if callable(p) else p
        return max(0, int(n))

    def estimated_service_s(self, bucket: int | None = None,
                            inflight: int = 0) -> float:
        """Worst-case time-to-result for a request admitted right now: a
        full drain window, one execution of the bucket it will likely
        run in (global EWMA until that bucket has history), plus one
        more execution per batch already in the pipeline ahead of it.
        With N free replicas the outstanding executions drain N-wide,
        so the exec term divides by N (the drain window doesn't — batch
        formation is one shared queue either way)."""
        n = self._replica_divisor()
        with self._lock:
            e = self._bucket_ewma_s.get(bucket) if bucket is not None \
                else None
            if e is None:
                e = self._exec_ewma_s or 0.0
            return self._max_wait_s + ((1 + max(0, inflight)) * e) / n

    def bucket_ewma_s(self, bucket: int | None = None) -> float | None:
        """Raw exec EWMA for ``bucket`` (global fallback, None before
        any batch has run) — the watchdog's exec-timeout base."""
        with self._lock:
            e = self._bucket_ewma_s.get(bucket) if bucket is not None \
                else None
            return e if e is not None else self._exec_ewma_s

    def admit(self, queue_depth: int, deadline: float | None,
              now: float | None = None, bucket: int | None = None,
              inflight: int = 0) -> Shed | None:
        """None = admitted; a ``Shed`` = rejected (reason inside)."""
        if queue_depth >= self.max_queue:
            with self._lock:
                self.shed_queue_full += 1
                entered = not self._overloaded
                self._overloaded = True
            if entered:
                event(_log, "overload_shed_start",
                      queue_depth=queue_depth, max_queue=self.max_queue,
                      inflight=inflight)
            return Shed("queue_full",
                        f"queue depth {queue_depth} >= {self.max_queue}",
                        retry_after_s=self.estimated_service_s(
                            bucket, inflight))
        with self._lock:
            cleared = self._overloaded
            self._overloaded = False
        if cleared:
            event(_log, "overload_cleared", queue_depth=queue_depth,
                  shed_queue_full=self.shed_queue_full)
        if deadline is not None:
            now = time.monotonic() if now is None else now
            est = self.estimated_service_s(bucket, inflight)
            if now + est > deadline:
                with self._lock:
                    self.shed_deadline += 1
                return Shed("deadline",
                            f"needs ~{est * 1e3:.1f}ms, "
                            f"deadline in {(deadline - now) * 1e3:.1f}ms",
                            retry_after_s=est)
        return None

    def record_admit(self):
        """Count one admitted request (called by the engine AFTER a None
        verdict from ``admit`` — the controller can't count it itself
        because ``admit`` doesn't know whether the caller enqueued).
        Per-model queue accounting for the control plane: admitted −
        served across every version of a name = requests the plane owes
        an answer."""
        with self._lock:
            self.admitted += 1

    def expired(self, deadline: float | None,
                now: float | None = None) -> Shed | None:
        """Batch-formation-time re-check: queued past its deadline?"""
        if deadline is None:
            return None
        now = time.monotonic() if now is None else now
        if now > deadline:
            with self._lock:
                self.shed_deadline += 1
            return Shed("deadline",
                        f"expired {(now - deadline) * 1e3:.1f}ms ago in "
                        f"queue",
                        retry_after_s=self.estimated_service_s())
        return None

    def stats(self) -> dict:
        n = self._replica_divisor()  # outside the lock, see above
        live = self._live_count()
        with self._lock:
            out = {"shed_queue_full": self.shed_queue_full,
                   "shed_deadline": self.shed_deadline,
                   "admitted": self.admitted,
                   "exec_ewma_ms": (self._exec_ewma_s or 0.0) * 1e3,
                   "exec_ewma_ms_by_bucket": {
                       str(b): round(v * 1e3, 3)
                       for b, v in sorted(self._bucket_ewma_s.items())},
                   "free_replicas": n,
                   "live_replicas": live,
                   "max_queue": self.max_queue}
        if self.name is not None:
            out["name"] = self.name
        return out


# ---------------------------------------------------------------------------
# Per-tenant QoS: priority classes, token-bucket quotas, weighted shedding
# ---------------------------------------------------------------------------

TENANT_HEADER = "X-DVT-Tenant"

DEFAULT_QOS_SPEC = ("premium:rate=0,shed_at=1.0;"
                    "standard:rate=200,burst=50,shed_at=0.8;"
                    "best_effort:rate=50,burst=10,shed_at=0.5;"
                    "default=standard")


@dataclasses.dataclass
class QoSClass:
    """One priority class.

    ``rate``/``burst`` parameterize each member tenant's token bucket
    (requests/second sustained, requests of headroom); ``rate=0`` means
    unmetered.  ``shed_at`` is the weighted-shedding knee: the fraction
    of engine queue capacity beyond which this class's cache-missing
    requests are shed pre-engine, so under pressure best-effort
    (shed_at 0.5) absorbs the 429s half a queue before premium
    (shed_at 1.0) loses anything.  ``always_big`` is the cascade
    premium knob (serve/cascade.py): members of the class bypass the
    cheap front tier entirely — every request goes straight to the big
    tier, pricing guaranteed-big-model answers as a QoS class."""

    name: str
    rate: float = 0.0
    burst: float = 1.0
    shed_at: float = 1.0
    tenants: tuple = ()
    always_big: bool = False


class TenantQoS:
    """Maps the ``X-DVT-Tenant`` header to a priority class and applies
    two independent controls at the edge:

      quota     a per-tenant token bucket (class rate/burst), checked
                BEFORE the response cache — a tenant over quota is 429'd
                even for cached answers, otherwise a hot payload would
                make quotas unenforceable.
      priority  deterministic weighted shedding on engine queue
                pressure, checked only on a cache MISS just before the
                engine — pressure = queue_depth / max_queue, and a class
                is shed when pressure ≥ its ``shed_at``.  Cache hits
                bypass this (they cost no engine capacity).

    Spec grammar (``--qos``):
        ``premium:rate=0,shed_at=1.0,tenants=acme|bigco;``
        ``best_effort:rate=20,burst=5,shed_at=0.5;default=best_effort``
    ``tenants=`` pins named tenants to a class; everything else lands in
    the ``default=`` class (first class declared if omitted);
    ``always_big=1`` marks the class as cascade-premium (its tenants
    bypass the front tier — serve/cascade.py)."""

    def __init__(self, classes: list, default: str):
        if not classes:
            raise ValueError("QoS spec declares no classes")
        self.classes = {c.name: c for c in classes}
        if default not in self.classes:
            raise ValueError(f"QoS default class {default!r} not declared")
        self.default = default
        self._tenant_class = {t: c.name for c in classes
                              for t in c.tenants}
        self._lock = new_lock("serve.admission.TenantQoS._lock")
        # tenant → [tokens, last_refill_monotonic]  guarded-by: _lock
        self._buckets: dict[str, list] = {}
        # class → counters/histogram  guarded-by: _lock
        self._served = {c.name: 0 for c in classes}
        self._shed_quota = {c.name: 0 for c in classes}
        self._shed_priority = {c.name: 0 for c in classes}
        self._cache_hits = {c.name: 0 for c in classes}
        from deep_vision_tpu.core.metrics import LatencyHistogram
        self._latency = {c.name: LatencyHistogram() for c in classes}

    @classmethod
    def parse(cls, spec: str) -> "TenantQoS":
        classes, default = [], None
        for part in filter(None, (p.strip() for p in spec.split(";"))):
            if part.startswith("default="):
                default = part[len("default="):].strip()
                continue
            name, _, opts = part.partition(":")
            kw: dict = {"name": name.strip()}
            for opt in filter(None, (o.strip() for o in opts.split(","))):
                k, _, v = opt.partition("=")
                k = k.strip()
                if k == "tenants":
                    kw["tenants"] = tuple(
                        t for t in v.strip().split("|") if t)
                elif k in ("rate", "burst", "shed_at"):
                    kw[k] = float(v)
                elif k == "always_big":
                    kw["always_big"] = v.strip().lower() \
                        not in ("", "0", "false", "no")
                else:
                    raise ValueError(f"unknown QoS option {k!r} in "
                                     f"{part!r}")
            classes.append(QoSClass(**kw))
        return cls(classes, default or (classes[0].name if classes
                                        else ""))

    def class_of(self, tenant: str) -> QoSClass:
        return self.classes[self._tenant_class.get(tenant, self.default)]

    def check_quota(self, tenant: str,
                    now: float | None = None) -> Shed | None:
        """Token-bucket admission for one request from ``tenant``.
        None = within quota (one token consumed)."""
        cls = self.class_of(tenant)
        if cls.rate <= 0:
            return None  # unmetered class
        now = time.monotonic() if now is None else now
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = [cls.burst, now]
                self._buckets[tenant] = bucket
            tokens = min(cls.burst,
                         bucket[0] + cls.rate * (now - bucket[1]))
            bucket[1] = now
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                return None
            bucket[0] = tokens
            self._shed_quota[cls.name] += 1
            wait_s = (1.0 - tokens) / cls.rate
        return Shed("quota",
                    f"tenant {tenant!r} ({cls.name}) over "
                    f"{cls.rate:g} req/s quota",
                    retry_after_s=wait_s)

    def check_pressure(self, tenant: str, queue_depth: int,
                       max_queue: int,
                       floor: float = 0.0) -> Shed | None:
        """Weighted shedding on a cache miss: shed this class once
        engine queue pressure crosses its knee.  ``floor`` is a lower
        bound on the pressure the knees see — the brownout L3 hook
        (serve/brownout.py) passes a floor just below 1.0 so every
        class but premium (shed_at=1.0) sheds regardless of the actual
        queue, premium last by construction."""
        cls = self.class_of(tenant)
        pressure = queue_depth / max_queue if max_queue > 0 else 0.0
        pressure = max(pressure, float(floor))
        if pressure < cls.shed_at:
            return None
        with self._lock:
            self._shed_priority[cls.name] += 1
        return Shed("priority",
                    f"{cls.name} sheds at {cls.shed_at:g} queue "
                    f"pressure (now {pressure:.2f})",
                    retry_after_s=1.0)

    def record_served(self, tenant: str, seconds: float,
                      cache_hit: bool = False):
        cls = self.class_of(tenant)
        with self._lock:
            self._served[cls.name] += 1
            if cache_hit:
                self._cache_hits[cls.name] += 1
            self._latency[cls.name].record(seconds)

    def stats(self) -> dict:
        with self._lock:
            return {name: {
                        "rate": c.rate, "burst": c.burst,
                        "shed_at": c.shed_at,
                        "always_big": c.always_big,
                        "served": self._served[name],
                        "shed_quota": self._shed_quota[name],
                        "shed_priority": self._shed_priority[name],
                        "cache_hits": self._cache_hits[name],
                        "latency": self._latency[name].percentiles(),
                        "default": name == self.default}
                    for name, c in self.classes.items()}
