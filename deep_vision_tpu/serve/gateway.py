"""Cross-host serving gateway: health-routed failover across backends.

``--serve-devices`` scales one process across its local chips; the next
scale axis is *processes and hosts*.  The gateway is a thin HTTP front
tier that proxies every workload inference verb (``/v1/classify``,
``/v1/detect``, ``/v1/pose``, ``/v1/generate`` — the route table
derives from ``serve/workloads.py``) across a table of
backend serve processes (each a full PR 1–5 stack: batcher, pipeline,
fault plane, deep health) so N backends look like one endpoint that
survives any single backend dying:

  state machine   per-backend OK → DEGRADED → DEAD, driven by BOTH
                  active ``/v1/healthz`` probes (a prober thread, every
                  ``probe_interval_s``) and passive request outcomes —
                  connect errors, timeouts, and 5xx count as failures;
                  any 2xx/4xx response or a 200 probe resets to OK.  A
                  503 probe means *alive but can't serve* (draining, or
                  the backend's own health machine flipped): the
                  backend leaves routing with NO breaker penalty and
                  rejoins on the next 200 probe.
  routing         least outstanding work over routable backends —
                  outstanding requests × the backend's latency EWMA,
                  scanned from a rotating offset with strict less-than
                  (ties round-robin), mirroring the in-process replica
                  router (serve/replicas.py).
  circuit breaker per backend: CLOSED → OPEN after ``breaker_threshold``
                  consecutive failures (probe or request) → HALF_OPEN
                  once ``breaker_cooldown_s`` elapses, admitting one
                  trial (the next probe or one live request); success
                  closes, failure re-opens with a fresh cooldown.  An
                  OPEN breaker takes the backend out of routing within
                  one probe interval of it dying — no traffic required.
  retries         inference requests are idempotent, so a connect
                  error / timeout / 5xx is retried with jittered
                  exponential backoff, bounded by ``retry_budget``
                  attempts per request, FAILING OVER to a different
                  backend when one is routable — killing one of two
                  backends mid-load loses zero admitted requests from
                  the client's view.
  retry budget    the per-request attempt cap bounds one request; it
                  does NOT bound the fleet-level retry *ratio* — under
                  a total backend outage every request still burns its
                  full attempt allowance, and the retry storm is load
                  the dying backends must also absorb.  So each retry
                  additionally draws one token from the TARGET
                  backend's bucket, refilled ``retry_budget_ratio``
                  per successful response (capped at
                  ``retry_budget_burst``): sustained retries are
                  bounded to a fixed fraction of sustained successes,
                  the classic success-refilled retry budget (Finagle,
                  "The Site Reliability Workbook" ch. 21).  A dry
                  bucket denies the retry; the request answers with
                  what it has (last 429/502) instead of amplifying.
                  Remaining tokens ride the ``X-DVT-Retry-Budget``
                  response header so a cooperating client (bench.py's
                  closed loop) suppresses ITS retries too — gateway
                  and client never jointly exceed the budget.
  429s            a shed (429) is failed over once to a less-loaded
                  backend when one exists; otherwise it propagates to
                  the client unchanged, ``Retry-After`` header included,
                  so client backoff semantics survive the extra hop.
  tail hedging    optional: if the primary hasn't answered after a
                  p99-based delay (``hedge_after_ms``, or the gateway's
                  own measured p99 once it has history), the request is
                  duplicated to a second backend — first answer wins,
                  the loser's response is discarded.

``GET /v1/stats`` aggregates every backend's own stats under the
gateway's counters (retries, failovers, hedges, breaker transitions),
plus the fleet-level latency DISTRIBUTION (per-backend histogram
states merged bin-wise — a true fleet p99, not an average of p99s) and
the aggregate serving MFU; ``GET /metrics`` renders the same as
Prometheus text; ``GET /v1/traces`` exposes the gateway's trace ring.
Every proxied request carries an ``X-DVT-Request-Id`` header to the
backend (client-provided or minted here) so one id names the whole
gateway→backend→engine path — ``?debug=1`` responses carry both the
backend's ``trace`` and the gateway-side ``gateway_trace`` breakdown.
``GET /v1/healthz`` answers 200 while ANY backend is routable.  Entry
point: ``python -m deep_vision_tpu.cli.gateway``; chaos suite:
``tests/test_gateway.py`` (marker ``gateway``); end-to-end smoke with a
real SIGKILL mid-load: ``make gateway-smoke``.  Zero new dependencies:
stdlib ``http.client`` out, the ``serve/edge.py`` selector loop in
(``ThreadingHTTPServer`` behind ``edge=False``).

Forwarding rides per-backend keep-alive connection POOLS with
retry-on-stale (an error on a reused socket drops the pool and retries
once fresh; an error on a fresh socket is a real backend failure), and
``affinity=True`` switches routing to rendezvous hashing on the
payload digest so repeats of one payload land where the backend's
response cache already holds the answer.
"""

from __future__ import annotations

import hashlib
import json
import math
import random
import threading
import time
from concurrent.futures import FIRST_COMPLETED, ThreadPoolExecutor, wait
from http.client import HTTPConnection, HTTPException
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.core.metrics import LatencyHistogram
from deep_vision_tpu.obs.log import event, get_logger
from deep_vision_tpu.obs.mfu import round_mfu
from deep_vision_tpu.obs.trace import (
    REQUEST_ID_HEADER,
    Tracer,
    new_request_id,
)
from deep_vision_tpu.serve.edge import DEFAULT_MAX_CONNECTIONS, EdgeServer
from deep_vision_tpu.serve.faults import InjectedFault
from deep_vision_tpu.serve.health import DEAD, DEGRADED, OK

_log = get_logger("dvt.serve.gateway")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"

# retry-able HTTP verdicts vs. final ones: anything below 500 except a
# 429 means the backend is alive and answered THIS request definitively
_PROXY_HEADERS = ("Content-Type", "Retry-After", "X-DVT-Cache",
                  "X-DVT-Tier", "X-DVT-Degraded")

#: response header carrying the answering backend's remaining retry
#: tokens — a value below 1.0 tells a cooperating client that retrying
#: now would exceed the budget the gateway itself is held to
RETRY_BUDGET_HEADER = "X-DVT-Retry-Budget"


class Backend:
    """One backend serve process: address + breaker + health + load.

    All mutation goes through ``record_*``/``begin``/``done_*`` under
    one lock; the router reads ``routable()`` and the outstanding/EWMA
    score.  The breaker is the ROUTING gate; the OK/DEGRADED/DEAD state
    is the observability verdict — both are driven by the same
    consecutive-failure count so they can't disagree about a dead
    backend.
    """

    def __init__(self, url: str, *, breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 degraded_after: int = 1, dead_after: int = 5,
                 ewma_alpha: float = 0.2,
                 retry_ratio: float = 0.1,
                 retry_burst: float = 10.0):
        addr = url.removeprefix("http://").rstrip("/")
        host, _, port = addr.rpartition(":")
        if not host or not port.isdigit():
            raise ValueError(f"backend '{url}': expected host:port")
        self.host, self.port = host, int(port)
        self.name = f"{self.host}:{self.port}"
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_cooldown_s = breaker_cooldown_s
        self.degraded_after = max(1, int(degraded_after))
        self.dead_after = max(self.degraded_after, int(dead_after))
        self._alpha = ewma_alpha
        self._lock = new_lock("serve.gateway.Backend._lock")
        self.state = OK  # guarded-by: _lock
        self.breaker = CLOSED  # guarded-by: _lock
        self.opened_at: float | None = None  # guarded-by: _lock
        self._trial_inflight = False  # guarded-by: _lock
        # a 503 healthz: alive but can't serve (reason from its body)
        self.unavailable: str | None = None  # guarded-by: _lock
        self.outstanding = 0  # guarded-by: _lock
        self.ewma_s: float | None = None  # guarded-by: _lock
        self.consecutive_failures = 0  # guarded-by: _lock
        self.failures = 0  # guarded-by: _lock
        self.successes = 0  # guarded-by: _lock
        self.sheds = 0  # guarded-by: _lock
        self.probes = 0  # guarded-by: _lock
        self.breaker_opens = 0  # guarded-by: _lock
        self.breaker_closes = 0  # guarded-by: _lock
        self.half_open_trials = 0  # guarded-by: _lock
        # success-refilled retry budget: each retry routed HERE spends
        # one token; each successful response refills ``retry_ratio``
        # (capped at ``retry_burst``).  The bucket starts full so a
        # cold gateway can still fail over, but sustained retries are
        # bounded to ratio × sustained successes — a retry RATIO, not
        # a per-request count.
        self.retry_ratio = max(0.0, float(retry_ratio))
        self.retry_burst = max(1.0, float(retry_burst))
        self.retry_tokens = self.retry_burst  # guarded-by: _lock
        self.retries_granted = 0  # guarded-by: _lock
        self.retries_denied = 0  # guarded-by: _lock
        self.last_probe_at: float | None = None  # guarded-by: _lock
        self.last_error: str | None = None  # guarded-by: _lock
        # model names this backend reports serving (from its healthz
        # payload); empty until the first 200 probe — an empty list
        # routes everything, so a pre-probe gateway still forwards
        self.models: list[str] = []  # guarded-by: _lock
        # per-engine mesh advertisement from the healthz payload —
        # {engine: {mesh_shape, param_shard_bytes, hbm_headroom_bytes}}
        # — the gateway's capacity view of this backend's chips
        self.mesh: dict = {}  # guarded-by: _lock
        # keep-alive connection pool for forwarding: connections check
        # out per exchange and return unless the response closed them.
        # Its own leaf lock — pool operations never nest under _lock.
        self._conn_lock = new_lock("serve.gateway.Backend._conn_lock")
        self._conns: list[HTTPConnection] = []  # guarded-by: _conn_lock
        self.conns_created = 0  # guarded-by: _conn_lock
        self.conns_reused = 0  # guarded-by: _conn_lock

    # -- keep-alive connection pool ----------------------------------------

    def acquire_conn(self, timeout: float,
                     fresh: bool = False) -> tuple[HTTPConnection, bool]:
        """Check out a connection: ``(conn, reused)``.  ``fresh=True``
        bypasses the pool — the retry-on-stale second attempt must not
        draw another possibly-stale keep-alive socket."""
        conn = None
        if not fresh:
            with self._conn_lock:
                if self._conns:
                    conn = self._conns.pop()
                    self.conns_reused += 1
        if conn is None:
            conn = HTTPConnection(self.host, self.port, timeout=timeout)
            with self._conn_lock:
                self.conns_created += 1
            return conn, False
        if conn.sock is not None:
            # per-use deadline: probes (1 s) and requests (30 s) share
            # the pool, so the timeout rides the checkout, not the conn
            conn.sock.settimeout(timeout)
        return conn, True

    def release_conn(self, conn: HTTPConnection):
        with self._conn_lock:
            if len(self._conns) < 8:
                self._conns.append(conn)
                return
        conn.close()

    def discard_conn(self, conn: HTTPConnection):
        try:
            conn.close()
        except OSError:
            pass

    def close_conns(self):
        """Drop every pooled connection — on gateway stop, and when a
        stale keep-alive surfaces (a restarted backend invalidates the
        WHOLE pool, not just the socket that noticed)."""
        with self._conn_lock:
            conns, self._conns = self._conns, []
        for conn in conns:
            try:
                conn.close()
            except OSError:
                pass

    # -- routing gate ------------------------------------------------------

    def serves(self, model: str | None) -> bool:
        """Does this backend serve ``model``?  None (no path param) and
        an un-probed backend (empty list) both route — the backend
        itself 404s a truly unknown model."""
        if model is None:
            return True
        with self._lock:
            return not self.models or model in self.models

    def routable(self, now: float | None = None) -> bool:
        """May the router send this backend a request right now?  OPEN →
        HALF_OPEN happens here (time-based), so the first caller after
        the cooldown sees the trial slot."""
        now = time.monotonic() if now is None else now
        with self._lock:
            if self.unavailable is not None:
                return False
            if self.breaker == CLOSED:
                return True
            if self.breaker == OPEN:
                if now - (self.opened_at or now) < self.breaker_cooldown_s:
                    return False
                self.breaker = HALF_OPEN
                self._trial_inflight = False
            return not self._trial_inflight

    def begin(self):
        """A request was routed here (claims the half-open trial slot)."""
        with self._lock:
            self.outstanding += 1
            if self.breaker == HALF_OPEN and not self._trial_inflight:
                self._trial_inflight = True
                self.half_open_trials += 1

    # -- outcome recording -------------------------------------------------

    def _failure_locked(self, err: str, now: float):
        self.consecutive_failures += 1
        self.failures += 1
        self.last_error = err
        opened = False
        if self.breaker == HALF_OPEN:
            # the trial failed: re-open with a fresh cooldown
            self.breaker = OPEN
            self.opened_at = now
            self.breaker_opens += 1
            opened = True
        elif self.breaker == CLOSED and \
                self.consecutive_failures >= self.breaker_threshold:
            self.breaker = OPEN
            self.opened_at = now
            self.breaker_opens += 1
            opened = True
        if opened:
            event(_log, "breaker_open", backend=self.name, error=err,
                  consecutive_failures=self.consecutive_failures)
        if self.consecutive_failures >= self.dead_after:
            self.state = DEAD
        elif self.consecutive_failures >= self.degraded_after:
            self.state = DEGRADED

    def _success_locked(self):
        self.consecutive_failures = 0
        if self.breaker != CLOSED:
            self.breaker = CLOSED
            self.breaker_closes += 1
            event(_log, "breaker_close", backend=self.name)
        self._trial_inflight = False
        self.state = OK

    def done_success(self, elapsed_s: float):
        with self._lock:
            self.outstanding -= 1
            self.successes += 1
            self.ewma_s = elapsed_s if self.ewma_s is None else \
                self.ewma_s + self._alpha * (elapsed_s - self.ewma_s)
            # only REAL successes refill the retry budget — sheds and
            # probes don't, so a 100%-shedding backend's bucket stays
            # dry and retries against it stop at the burst allowance
            self.retry_tokens = min(self.retry_burst,
                                    self.retry_tokens + self.retry_ratio)
            self._success_locked()

    def done_shed(self):
        """A 429: the backend is healthy, just out of capacity — resets
        the breaker, but sheds don't feed the service-latency EWMA."""
        with self._lock:
            self.outstanding -= 1
            self.sheds += 1
            self._success_locked()

    def done_failure(self, err: str, now: float | None = None):
        with self._lock:
            self.outstanding -= 1
            self._trial_inflight = False
            self._failure_locked(err, time.monotonic()
                                 if now is None else now)

    # -- retry budget ------------------------------------------------------

    def try_retry(self) -> bool:
        """Spend one retry token against this backend.  False means the
        budget is dry: the caller must NOT retry here — under a
        sustained outage nothing refills the bucket and the retry storm
        dies at the burst allowance instead of amplifying the load."""
        with self._lock:
            if self.retry_tokens >= 1.0:
                self.retry_tokens -= 1.0
                self.retries_granted += 1
                return True
            self.retries_denied += 1
            return False

    def retry_tokens_left(self) -> float:
        with self._lock:
            return self.retry_tokens

    def probe_ok(self, now: float, models: list[str] | None = None,
                 mesh: dict | None = None):
        with self._lock:
            self.probes += 1
            self.last_probe_at = now
            self.unavailable = None
            if models is not None:
                self.models = list(models)
            if mesh is not None:
                self.mesh = dict(mesh)
            self.consecutive_failures = 0
            if self.breaker == CLOSED:
                self.state = OK
            elif now - (self.opened_at or now) >= self.breaker_cooldown_s:
                # the probe IS the half-open trial: close on success
                self.half_open_trials += 1
                self._success_locked()

    def probe_unavailable(self, reason: str, now: float):
        """healthz answered 503: out of routing, no breaker penalty."""
        with self._lock:
            self.probes += 1
            self.last_probe_at = now
            self.unavailable = reason

    def probe_failure(self, err: str, now: float):
        with self._lock:
            self.probes += 1
            self.last_probe_at = now
            self._failure_locked(err, now)

    # -- observability -----------------------------------------------------

    def score(self) -> float:
        """Least-outstanding-work routing score (lower = preferred)."""
        return self.outstanding * (self.ewma_s or 1.0)

    def report(self, now: float | None = None) -> dict:
        now = time.monotonic() if now is None else now
        with self._conn_lock:
            conns = {"pooled": len(self._conns),
                     "created": self.conns_created,
                     "reused": self.conns_reused}
        with self._lock:
            return {
                "conns": conns,
                "url": f"http://{self.name}",
                "state": self.state,
                "breaker": self.breaker,
                "unavailable": self.unavailable,
                "outstanding": self.outstanding,
                "ewma_ms": round(self.ewma_s * 1e3, 3)
                if self.ewma_s is not None else None,
                "consecutive_failures": self.consecutive_failures,
                "failures": self.failures,
                "successes": self.successes,
                "sheds": self.sheds,
                "probes": self.probes,
                "breaker_opens": self.breaker_opens,
                "breaker_closes": self.breaker_closes,
                "half_open_trials": self.half_open_trials,
                "retry_budget": {
                    "tokens": round(self.retry_tokens, 3),
                    "burst": self.retry_burst,
                    "ratio": self.retry_ratio,
                    "granted": self.retries_granted,
                    "denied": self.retries_denied},
                "last_probe_age_s": round(now - self.last_probe_at, 4)
                if self.last_probe_at is not None else None,
                "last_error": self.last_error,
                "models": list(self.models),
                "mesh": dict(self.mesh)}


class _Outcome:
    """One attempt's verdict: ``ok`` (2xx / non-429 4xx — final),
    ``shed`` (429), or ``fail`` (connect error / timeout / 5xx)."""

    __slots__ = ("kind", "status", "headers", "payload", "backend",
                 "error", "hedge_backend")

    def __init__(self, kind, status, headers, payload, backend,
                 error=None):
        self.kind = kind
        self.status = status
        self.headers = headers
        self.payload = payload
        self.backend = backend
        self.error = error
        self.hedge_backend = None  # a hedge that ALSO failed


class Gateway:
    """Health-routed failover proxy over N backend serve processes."""

    def __init__(self, backends: list[str], *,
                 probe_interval_s: float = 0.25,
                 probe_timeout_s: float = 1.0,
                 request_timeout_s: float = 30.0,
                 retry_budget: int = 3,
                 retry_budget_ratio: float = 0.1,
                 retry_budget_burst: float = 10.0,
                 backoff_ms: float = 10.0,
                 backoff_max_ms: float = 250.0,
                 breaker_threshold: int = 3,
                 breaker_cooldown_s: float = 1.0,
                 degraded_after: int = 1, dead_after: int = 5,
                 hedge: bool = False,
                 hedge_after_ms: float | None = None,
                 hedge_min_history: int = 32,
                 affinity: bool = False,
                 tracer: Tracer | None = None,
                 faults=None):
        if not backends:
            raise ValueError("gateway needs at least one backend")
        self.backends = [Backend(u, breaker_threshold=breaker_threshold,
                                 breaker_cooldown_s=breaker_cooldown_s,
                                 degraded_after=degraded_after,
                                 dead_after=dead_after,
                                 retry_ratio=retry_budget_ratio,
                                 retry_burst=retry_budget_burst)
                         for u in backends]
        names = [b.name for b in self.backends]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate backends in {names}")
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.request_timeout_s = request_timeout_s
        self.retry_budget = max(0, int(retry_budget))
        self.backoff_ms = backoff_ms
        self.backoff_max_ms = backoff_max_ms
        self.hedge = hedge
        self.hedge_after_ms = hedge_after_ms
        self.hedge_min_history = hedge_min_history
        # payload-digest consistent hashing (rendezvous): repeats of
        # one payload land on one backend so ITS response cache hits,
        # instead of spreading a hot image's repeats across N cold
        # caches.  Opt-in: load-based routing stays the default.
        self.affinity = affinity
        self.tracer = tracer or Tracer()
        self.retry_budget_ratio = retry_budget_ratio
        self.retry_budget_burst = retry_budget_burst
        # optional FaultPlane (serve/faults.py): the "gateway" stage
        # fires per backend attempt, modeling the NETWORK between the
        # gateway and its backends (conn_reset / slow_drip / blackhole)
        self.faults = faults
        self.latency = LatencyHistogram()
        self._lock = new_lock("serve.gateway.Gateway._lock")
        self._stop = threading.Event()
        self._prober: threading.Thread | None = None
        self._pool: ThreadPoolExecutor | None = None  # guarded-by: _lock
        self._rr = 0  # rotating scan offset: idle ties round-robin; guarded-by: _lock
        self.proxied = 0  # guarded-by: _lock
        self.retries = 0  # guarded-by: _lock
        self.failovers = 0  # guarded-by: _lock
        self.hedges = 0  # guarded-by: _lock
        self.hedge_wins = 0  # guarded-by: _lock
        self.exhausted = 0  # guarded-by: _lock
        self.no_backend = 0  # guarded-by: _lock
        self.retry_budget_denied = 0  # guarded-by: _lock

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> "Gateway":
        if self._prober is None:
            self._stop.clear()
            self._probe_all()  # know the fleet before the first request
            self._prober = threading.Thread(target=self._probe_loop,
                                            name="gateway-prober",
                                            daemon=True)
            self._prober.start()
        return self

    def stop(self, timeout: float = 5.0):
        self._stop.set()
        if self._prober is not None:
            self._prober.join(timeout)
            self._prober = None
        with self._lock:
            pool, self._pool = self._pool, None
        if pool is not None:
            pool.shutdown(wait=False)
        for b in self.backends:
            b.close_conns()

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()

    # -- probing (active health) -------------------------------------------

    def _probe_loop(self):
        while not self._stop.wait(self.probe_interval_s):
            self._probe_all()

    def _probe_all(self):
        for b in self.backends:
            if self._stop.is_set():
                return
            now = time.monotonic()
            try:
                status, _, payload = self._call(
                    b, "GET", "/v1/healthz", None, self.probe_timeout_s,
                    pooled=False)
            except (OSError, HTTPException) as e:
                # the listener is gone: every pooled keep-alive socket
                # to it is now a liability — drop them so requests
                # can't ride a half-dead backend past its breaker
                b.close_conns()
                b.probe_failure(f"probe: {type(e).__name__}: {e}", now)
                continue
            if status == 200:
                models = None
                mesh = None
                try:
                    doc = json.loads(payload)
                    if isinstance(doc.get("models"), list):
                        models = [str(m) for m in doc["models"]]
                    # mesh advertisement: each engine's health report
                    # carries its weight layout + per-chip headroom —
                    # the fleet capacity table in gateway /v1/stats
                    engines = doc.get("engines")
                    if isinstance(engines, dict):
                        mesh = {
                            str(en): {
                                "mesh_shape": rep.get("mesh_shape"),
                                "param_shard_bytes":
                                    rep.get("param_shard_bytes"),
                                "hbm_headroom_bytes":
                                    rep.get("hbm_headroom_bytes")}
                            for en, rep in engines.items()
                            if isinstance(rep, dict)}
                except (ValueError, AttributeError):
                    pass
                b.probe_ok(now, models=models, mesh=mesh)
            else:
                reason = "unavailable"
                try:
                    reason = json.loads(payload).get("status", reason)
                except (ValueError, AttributeError):
                    pass
                b.probe_unavailable(reason, now)

    # -- request path ------------------------------------------------------

    def forward(self, path: str, body: bytes,
                request_id: str | None = None
                ) -> tuple[int, dict, bytes]:
        """Proxy one inference request: route, retry, fail over, hedge.
        Returns ``(status, headers, payload)`` for the client.  The
        request id (client-provided or minted here) rides the
        ``X-DVT-Request-Id`` header to the backend and back, so one id
        names the whole gateway→backend→engine path; ``?debug=1``
        responses additionally carry the gateway-side span as
        ``gateway_trace`` next to the backend's ``trace``."""
        rid = request_id or new_request_id()
        span = self.tracer.start(rid, origin="recv")
        try:
            status, headers, payload = self._forward(path, body, rid,
                                                     span)
            if span is not None:
                span.mark("respond")
                if status == 200 and self._debug_requested(path):
                    payload = self._attach_gateway_trace(payload, span)
            headers = dict(headers)
            headers[REQUEST_ID_HEADER] = rid
            return status, headers, payload
        finally:
            self.tracer.finish(span)

    @staticmethod
    def _debug_requested(path: str) -> bool:
        q = path.partition("?")[2]
        return parse_qs(q).get("debug", ["0"])[0] not in ("", "0")

    @staticmethod
    def _attach_gateway_trace(payload: bytes, span) -> bytes:
        try:
            doc = json.loads(payload)
            doc["gateway_trace"] = span.to_dict()
            return json.dumps(doc).encode()
        except (ValueError, TypeError):
            return payload  # not JSON: leave the body alone

    @staticmethod
    def _path_model(path: str) -> str | None:
        """The model name a /v1/models/<name>/<verb> path routes on
        (None for the classic un-named routes)."""
        parts = path.partition("?")[0].split("/")
        if len(parts) == 5 and parts[1] == "v1" and parts[2] == "models":
            return parts[3]
        return None

    # dvtlint: hot
    def _forward(self, path: str, body: bytes, rid: str, span
                 ) -> tuple[int, dict, bytes]:
        t0 = time.monotonic()
        model = self._path_model(path)
        # rendezvous affinity key: the payload digest, hashed once per
        # request (retries reuse it — failover is just the next-highest
        # backend in the same hash ranking)
        akey = hashlib.blake2b(body, digest_size=8).digest() \
            if self.affinity and body else None
        with self._lock:
            self.proxied += 1
        tried: list[Backend] = []
        last_shed: _Outcome | None = None
        last_fail: _Outcome | None = None
        prev: Backend | None = None
        for attempt in range(1 + self.retry_budget):
            b = self._pick(tried, model, akey)
            if b is None and tried:
                # every routable backend failed this request once —
                # clear the exclusions so the backoff'd retry may
                # revisit (a transient blip shouldn't 502 the client)
                tried = []
                b = self._pick(tried, model, akey)
            if b is None:
                break
            if attempt > 0:
                if not b.try_retry():
                    # the target's retry budget is dry: retrying would
                    # push the storm past the configured ratio.  Skip
                    # this backend (another may have tokens); when all
                    # are dry the loop runs out and the request answers
                    # with the last verdict it holds.
                    with self._lock:
                        self.retry_budget_denied += 1
                    if span is not None:
                        span.note("retry_budget_denied", b.name)
                    tried.append(b)
                    continue
                with self._lock:
                    self.retries += 1
                    if prev is not None and b is not prev:
                        self.failovers += 1
                if span is not None:
                    span.note("failover" if b is not prev else "retry",
                              b.name)
                if last_shed is None or b is prev:
                    # backoff applies to failures and same-backend
                    # retries; failing a 429 over to a DIFFERENT
                    # backend goes immediately
                    self._backoff(attempt)
            prev = b
            if span is not None:
                span.note("attempt", b.name)
            out = self._attempt(b, path, body, allow_hedge=attempt == 0,
                                rid=rid, span=span)
            if span is not None:
                # one backend_hop segment per attempt (accumulates):
                # the span's proxy-side time is attempts + respond
                span.mark("backend_hop")
            if out.kind == "ok":
                with self._lock:  # histogram increments aren't atomic
                    self.latency.record(time.monotonic() - t0)
                return out.status, self._client_headers(out), out.payload
            tried.append(out.backend)
            if out.hedge_backend is not None:
                tried.append(out.hedge_backend)
            if out.kind == "shed":
                last_shed = out
                if span is not None:
                    span.note("shed", out.backend.name)
                if self._pick(tried, model, akey) is None:
                    break  # nobody with headroom: propagate the 429
            else:
                last_fail = out
        with self._lock:
            if last_shed is None and last_fail is None:
                self.no_backend += 1
            else:
                self.exhausted += 1
        if last_shed is not None:
            # propagate the shed verbatim, Retry-After included
            return (last_shed.status, self._client_headers(last_shed),
                    last_shed.payload)
        if last_fail is not None:
            detail = last_fail.error or f"HTTP {last_fail.status}"
            return 502, {
                "Content-Type": "application/json",
                RETRY_BUDGET_HEADER:
                    f"{last_fail.backend.retry_tokens_left():.2f}",
            }, json.dumps(
                {"error": f"all backends failed after "
                          f"{1 + self.retry_budget} attempt(s): "
                          f"{detail}"}).encode()
        return 503, {"Content-Type": "application/json",
                     RETRY_BUDGET_HEADER: "0.00",
                     "Retry-After": max(1, math.ceil(
                         self.probe_interval_s))}, json.dumps(
            {"error": "no routable backend (all DEAD, draining, or "
                      "breaker-open)"}).encode()

    @staticmethod
    def _client_headers(out: _Outcome) -> dict:
        h = {k: out.headers[k] for k in _PROXY_HEADERS
             if k in out.headers}
        # budget state rides every proxied answer: a client deciding
        # whether to retry a 429/5xx sees the same bucket the gateway
        # spends from, so the two can't jointly exceed the ratio
        h[RETRY_BUDGET_HEADER] = \
            f"{out.backend.retry_tokens_left():.2f}"
        return h

    def _pick(self, exclude: list, model: str | None = None,
              affinity_key: bytes | None = None
              ) -> Backend | None:  # dvtlint: hot
        """Least outstanding work (outstanding × latency EWMA) over
        routable backends, scanning from a rotating offset with strict
        less-than — an idle fleet round-robins instead of piling onto
        backend 0 (same policy as serve/replicas.py).  ``model``
        (from a /v1/models/<name>/... path) filters to backends whose
        probed model list serves it.

        With an ``affinity_key`` (the payload digest, when
        ``affinity=True``), routing switches to rendezvous hashing:
        every candidate scores ``blake2b(key | backend-name)`` and the
        highest wins — repeats of one payload deterministically land on
        one backend (its response cache hits), a dead/excluded backend
        just drops out of the candidate set (only ITS keys move), and
        failover falls through to the next-highest hash."""
        now = time.monotonic()
        n = len(self.backends)
        with self._lock:
            start = self._rr % n
            self._rr += 1
        best = best_score = None
        for k in range(n):
            b = self.backends[(start + k) % n]
            if b in exclude or not b.routable(now) \
                    or not b.serves(model):
                continue
            if affinity_key is not None:
                # highest-random-weight: bigger hash wins
                score = -int.from_bytes(hashlib.blake2b(
                    affinity_key + b.name.encode(),
                    digest_size=8).digest(), "big")
            else:
                score = b.score()
            if best_score is None or score < best_score:
                best, best_score = b, score
        return best

    def _backoff(self, attempt: int):
        base = min(self.backoff_max_ms,
                   self.backoff_ms * (2 ** (attempt - 1)))
        # full jitter in [0.5, 1.5)×base: retries from a burst of
        # failovers must not re-converge on the survivor in lockstep
        time.sleep(base * (0.5 + random.random()) / 1e3)

    # -- single attempt + hedging ------------------------------------------

    def _attempt(self, b: Backend, path: str, body: bytes,
                 allow_hedge: bool, rid: str | None = None,
                 span=None) -> _Outcome:
        delay_s = self._hedge_delay_s() if allow_hedge else None
        if delay_s is None:
            return self._single(b, path, body, rid)
        pool = self._hedge_pool()
        primary = pool.submit(self._single, b, path, body, rid)
        done, _ = wait([primary], timeout=delay_s)
        if done:
            return primary.result()
        b2 = self._pick([b], self._path_model(path))
        if b2 is None:
            return primary.result()  # nobody to hedge to: just wait
        with self._lock:
            self.hedges += 1
        if span is not None:
            # noted from the forwarding thread only — the pool workers
            # never touch the span (single-writer ownership rule)
            span.note("hedge", b2.name)
        hedge = pool.submit(self._single, b2, path, body, rid)
        pending = {primary, hedge}
        while pending:
            done, pending = wait(pending, return_when=FIRST_COMPLETED)
            for f in done:
                out = f.result()
                if out.kind == "ok":
                    # first answer wins; the loser keeps running in the
                    # pool and its (counted) result is discarded
                    if f is hedge:
                        with self._lock:
                            self.hedge_wins += 1
                        if span is not None:
                            span.note("hedge_win", b2.name)
                    return out
        out = primary.result()
        if out.kind == "ok":  # pending-set raced: prefer any success
            return out
        out.hedge_backend = hedge.result().backend
        return out

    def _hedge_delay_s(self) -> float | None:
        if not self.hedge or len(self.backends) < 2:
            return None
        if self.hedge_after_ms is not None:
            return self.hedge_after_ms / 1e3
        # p99-based: hedge only the tail, and only once the gateway has
        # enough of its own history to know where the tail is
        p = self.latency.percentiles()
        if p["count"] < self.hedge_min_history:
            return None
        return p["p99_ms"] / 1e3

    def _hedge_pool(self) -> ThreadPoolExecutor:
        with self._lock:
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=2 * len(self.backends) + 2,
                    thread_name_prefix="gateway-hedge")
            return self._pool

    def _single(self, b: Backend, path: str, body: bytes,
                rid: str | None = None) -> _Outcome:
        b.begin()
        t0 = time.monotonic()
        try:
            if self.faults is not None and self.faults.enabled:
                # the injected NETWORK between gateway and backend:
                # conn_reset raises ConnectionResetError and blackhole
                # raises TimeoutError — both OSError subclasses, so
                # they ride the real failure path below untouched
                self.faults.inject("gateway", stop=self._stop)
            status, headers, payload = self._call(
                b, "POST", path, body, self.request_timeout_s,
                extra_headers={REQUEST_ID_HEADER: rid} if rid else None)
        except (OSError, HTTPException, InjectedFault) as e:
            err = f"{b.name}: {type(e).__name__}: {e}"
            b.done_failure(err)
            return _Outcome("fail", 0, {}, b"", b, error=err)
        if status >= 500:
            b.done_failure(f"{b.name}: HTTP {status}")
            return _Outcome("fail", status, headers, payload, b,
                            error=f"{b.name}: HTTP {status}")
        if status == 429:
            b.done_shed()
            return _Outcome("shed", status, headers, payload, b)
        b.done_success(time.monotonic() - t0)
        return _Outcome("ok", status, headers, payload, b)

    @staticmethod
    def _call(b: Backend, method: str, path: str, body: bytes | None,
              timeout: float, extra_headers: dict | None = None,
              pooled: bool = True) -> tuple[int, dict, bytes]:  # dvtlint: hot
        """One HTTP exchange over the backend's keep-alive pool.

        A REUSED connection can die for a reason that says nothing
        about the backend — it closed the idle socket between our
        requests — so an error on a reused connection discards the
        whole pool (a restarted backend invalidates every pooled
        socket) and retries ONCE on a fresh connection.  An error on a
        FRESH connection is the real thing (SIGKILL'd process, TCP
        reset) and propagates — failure detection stays exactly as
        sharp as the old connection-per-call scheme.  Retrying the
        exchange is safe even for POSTs: a stale keep-alive fails at
        send time, before the backend saw the request.

        ``pooled=False`` forces a fresh dial-and-close exchange —
        health probes use it, because a probe's whole job is proving
        the backend still ACCEPTS connections; probing over a pooled
        socket would let an established keep-alive mask a backend
        whose listener is gone."""
        headers = {"Content-Type": "application/json"} if body else {}
        if extra_headers:
            headers.update(extra_headers)
        if not pooled:
            conn = HTTPConnection(b.host, b.port, timeout=timeout)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                return resp.status, dict(resp.getheaders()), resp.read()
            finally:
                conn.close()
        for attempt in (0, 1):
            conn, reused = b.acquire_conn(timeout, fresh=attempt > 0)
            try:
                conn.request(method, path, body=body, headers=headers)
                resp = conn.getresponse()
                payload = resp.read()
            except (OSError, HTTPException):
                b.discard_conn(conn)
                if reused:
                    b.close_conns()
                    continue  # stale keep-alive: one fresh retry
                raise
            if resp.will_close:
                b.discard_conn(conn)
            else:
                b.release_conn(conn)
            return resp.status, dict(resp.getheaders()), payload
        raise HTTPException(f"{b.name}: unreachable retry state")

    # -- observability -----------------------------------------------------

    def routable_backends(self, now: float | None = None) -> list[str]:
        now = time.monotonic() if now is None else now
        return [b.name for b in self.backends if b.routable(now)]

    def counters(self) -> dict:
        with self._lock:
            return {"proxied": self.proxied, "retries": self.retries,
                    "failovers": self.failovers, "hedges": self.hedges,
                    "hedge_wins": self.hedge_wins,
                    "exhausted": self.exhausted,
                    "no_backend": self.no_backend,
                    "retry_budget_denied": self.retry_budget_denied,
                    "retry_budget_ratio": self.retry_budget_ratio,
                    "retry_budget_burst": self.retry_budget_burst,
                    "breaker_opens": sum(b.breaker_opens
                                         for b in self.backends),
                    "breaker_closes": sum(b.breaker_closes
                                          for b in self.backends)}

    def healthz(self) -> tuple[bool, dict]:
        now = time.monotonic()
        routable = self.routable_backends(now)
        ok = bool(routable)
        return ok, {"status": "ok" if ok else "unhealthy",
                    "routable": routable,
                    "backends": {b.name: b.report(now)
                                 for b in self.backends},
                    "gateway": self.counters()}

    def stats(self, include_backend_stats: bool = True) -> dict:
        now = time.monotonic()
        with self._lock:
            gw_latency = self.latency.percentiles()
            gw_hist = self.latency.state_dict()
        out = {"gateway": {**self.counters(),
                           "latency": gw_latency,
                           "latency_hist": gw_hist,
                           "trace": self.tracer.summary(),
                           "backends": {b.name: b.report(now)
                                        for b in self.backends}}}
        if self.faults is not None and self.faults.enabled:
            out["gateway"]["faults"] = self.faults.stats()
        if include_backend_stats:
            agg: dict = {}
            for b in self.backends:
                try:
                    status, _, payload = self._call(
                        b, "GET", "/v1/stats", None,
                        self.probe_timeout_s)
                    agg[b.name] = json.loads(payload) if status == 200 \
                        else {"error": f"HTTP {status}"}
                except (OSError, HTTPException, ValueError) as e:
                    agg[b.name] = {"error": f"{type(e).__name__}: {e}"}
            out["backends"] = agg
            merged, mfu, per_model = self._aggregate_backends(agg)
            # fleet-level latency DISTRIBUTION: per-backend histogram
            # states sum bin-wise (identical fixed edges), so the p99
            # here is the true fleet p99 — not an average of per-backend
            # p99s, which has no meaning
            out["gateway"]["backend_latency"] = \
                merged.percentiles() if merged is not None else None
            out["gateway"]["backend_latency_hist"] = \
                merged.state_dict() if merged is not None else None
            out["gateway"]["mfu"] = mfu
            out["gateway"]["models"] = per_model
            cas = self._aggregate_cascade(agg)
            if cas is not None:
                out["gateway"]["cascade"] = cas
        return out

    @staticmethod
    def _aggregate_cascade(agg: dict):
        """Fold each backend's reserved ``cascade`` stats block into
        one fleet view: summed tier/escalation/sample counters, a
        fleet-wide escalation rate, per-HOP escalation/sample/
        agreement-sample folds keyed by (hop, tier) across the chain,
        and per-tier latency percentiles from bin-wise-merged
        histograms (true fleet quantiles, same construction as the
        backend-latency merge above).  None when no backend runs a
        cascade."""
        served: dict = {}
        esc = esc_low = esc_shed = samples = forced = 0
        backends = []
        hists: dict = {}
        hops: dict = {}  # hop index -> folded per-hop block
        for bname, bstats in agg.items():
            cas = bstats.get("cascade") \
                if isinstance(bstats, dict) else None
            if not isinstance(cas, dict):
                continue
            backends.append(bname)
            for tier, n in (cas.get("served") or {}).items():
                served[tier] = served.get(tier, 0) + int(n or 0)
            esc += int(cas.get("escalations") or 0)
            esc_low += int(cas.get("escalated_lowconf") or 0)
            esc_shed += int(cas.get("escalated_shed") or 0)
            samples += int(cas.get("samples") or 0)
            forced += int(cas.get("forced_big") or 0)
            for hop in (cas.get("hops") or []):
                if not isinstance(hop, dict):
                    continue
                i = hop.get("hop")
                agg_hop = hops.setdefault(
                    i, {"hop": i, "tier": hop.get("tier"),
                        "token": hop.get("token"),
                        "escalations": 0, "samples": 0,
                        "sample_size": 0, "calibrated_backends": 0})
                agg_hop["escalations"] += int(
                    hop.get("escalations") or 0)
                agg_hop["samples"] += int(hop.get("samples") or 0)
                agg_hop["sample_size"] += int(
                    hop.get("sample_size") or 0)
                if hop.get("calibrated"):
                    agg_hop["calibrated_backends"] += 1
            for tier, h in (cas.get("latency_hist") or {}).items():
                if not h:
                    continue
                try:
                    mh = hists.get(tier)
                    if mh is None:
                        mh = hists[tier] = LatencyHistogram()
                        mh.load_state_dict(h)
                    else:
                        mh.merge(h)
                except (KeyError, ValueError, TypeError):
                    pass  # malformed or mismatched bins: skip
        if not backends:
            return None
        # everything a non-final tier answered was "judged" by the
        # chain; escalations that ended big-served or shed complete the
        # denominator (the 2-tier formula, generalized)
        routed = sum(n for t, n in served.items() if t != "big") \
            + esc_low + esc_shed
        return {"backends": backends,
                "served": served,
                "escalations": esc,
                "escalation_rate": ((esc_low + esc_shed) / routed)
                if routed else None,
                "samples": samples,
                "forced_big": forced,
                "hops": [hops[i] for i in sorted(hops)],
                "latency": {t: h.percentiles()
                            for t, h in hists.items()}}

    @staticmethod
    def _iter_engine_stats(bstats: dict):
        """Yield (model_name, engine_stats) from one backend's /v1/stats
        body — BOTH shapes: the legacy flat {name: engine.stats()} dict
        and the control-plane shape {"models": {name: {"engine": ...}},
        "cache": ..., "plane": ...}."""
        containers = bstats.get("models") \
            if isinstance(bstats.get("models"), dict) else bstats
        for name, mstats in containers.items():
            if not isinstance(mstats, dict):
                continue
            es = mstats.get("engine") \
                if isinstance(mstats.get("engine"), dict) else mstats
            if isinstance(es, dict) and "latency_hist" in es:
                yield name, es

    @staticmethod
    def _aggregate_backends(agg: dict):
        """Fold fetched backend /v1/stats into fleet-level views: one
        merged ``LatencyHistogram``, one MFU report (FLOPs and compute
        seconds sum across backends, MFU recomputes from the sums — a
        throughput-weighted aggregate by construction), and a per-model
        cross-backend table (served counts, merged-latency percentiles,
        which backends serve it)."""
        merged: LatencyHistogram | None = None
        flops = secs = 0.0
        batches = images = 0
        peak = None
        source = None
        per_model: dict = {}
        model_hists: dict = {}
        for bname, bstats in agg.items():
            if not isinstance(bstats, dict) or "error" in bstats:
                continue
            for name, mstats in Gateway._iter_engine_stats(bstats):
                hist = mstats.get("latency_hist")
                if hist:
                    try:
                        if merged is None:
                            merged = LatencyHistogram()
                            merged.load_state_dict(hist)
                        else:
                            merged.merge(hist)
                        mh = model_hists.get(name)
                        if mh is None:
                            mh = model_hists[name] = LatencyHistogram()
                            mh.load_state_dict(hist)
                        else:
                            mh.merge(hist)
                    except (KeyError, ValueError, TypeError):
                        pass  # malformed or mismatched bins: skip
                ent = per_model.setdefault(
                    name, {"served": 0, "submitted": 0, "backends": [],
                           "mesh": {}})
                ent["served"] += int(mstats.get("served") or 0)
                ent["submitted"] += int(mstats.get("submitted") or 0)
                ent["backends"].append(bname)
                # per-backend weight layout: the fleet capacity table —
                # which cells shard (per-chip bytes < global) and which
                # replicate, straight from each engine's stats
                ent["mesh"][bname] = {
                    "mesh_shape": mstats.get("mesh_shape"),
                    "param_shard_bytes": mstats.get("param_shard_bytes"),
                    "param_global_bytes":
                        mstats.get("param_global_bytes")}
                m = mstats.get("mfu") or {}
                flops += float(m.get("flops_total") or 0.0)
                secs += float(m.get("compute_s") or 0.0)
                batches += int(m.get("batches") or 0)
                images += int(m.get("images") or 0)
                if peak is None:
                    peak = m.get("peak_flops_per_s")
                if source is None:
                    source = m.get("flops_source")
        for name, mh in model_hists.items():
            per_model[name]["latency"] = mh.percentiles()
        mfu_val = flops / secs / peak \
            if secs > 0 and flops > 0 and peak else None
        mfu = {"serving_mfu": round_mfu(mfu_val),
               "flops_total": flops, "compute_s": round(secs, 6),
               "batches": batches, "images": images,
               "peak_flops_per_s": peak, "flops_source": source}
        return merged, mfu, per_model


def render_gateway_metrics(gw: Gateway, edge: dict | None = None) -> str:
    """Prometheus text for ``GET /metrics`` on the gateway: its own
    counters + per-backend breaker/load gauges + its request-latency
    histogram, plus the fleet aggregates (merged backend latency
    distribution and ``dvt_gateway_serving_mfu``) fetched from backend
    /v1/stats — one scrape sees the whole serving tier.  ``edge`` (the
    front-end EdgeServer's ``stats()``) adds the connection gauges."""
    from deep_vision_tpu.core.metrics import PromText

    s = gw.stats()
    g = s["gateway"]
    p = PromText()
    if isinstance(edge, dict):
        p.gauge("dvt_gateway_open_connections",
                edge.get("open_connections"),
                help="Client sockets open on the gateway edge")
        p.counter("dvt_gateway_edge_keepalive_reuses_total",
                  edge.get("keepalive_reuses"),
                  help="Client requests after the first per connection")
        p.counter("dvt_gateway_edge_accepted_total",
                  edge.get("accepted"),
                  help="Client connections accepted")
    p.counter("dvt_gateway_proxied_total", g["proxied"],
              help="Inference requests entering forward()")
    p.counter("dvt_gateway_retries_total", g["retries"],
              help="Attempts beyond each request's first")
    p.counter("dvt_gateway_failovers_total", g["failovers"],
              help="Retries that moved to a different backend")
    p.counter("dvt_gateway_hedges_total", g["hedges"],
              help="Tail-hedge duplicates issued")
    p.counter("dvt_gateway_hedge_wins_total", g["hedge_wins"],
              help="Hedged duplicates that answered first")
    p.counter("dvt_gateway_exhausted_total", g["exhausted"],
              help="Requests that failed every attempt")
    p.counter("dvt_gateway_no_backend_total", g["no_backend"],
              help="Requests with no routable backend at all")
    p.counter("dvt_gateway_retry_budget_denied_total",
              g["retry_budget_denied"],
              help="Retries refused because the target backend's "
                   "success-refilled token bucket was dry")
    p.gauge("dvt_gateway_retry_budget_ratio", g["retry_budget_ratio"],
            help="Tokens refilled per successful backend response")
    p.gauge("dvt_gateway_routable_backends",
            len(gw.routable_backends()),
            help="Backends currently accepting routed traffic")
    for b in gw.backends:
        r = b.report()
        lab = {"backend": b.name}
        p.gauge("dvt_gateway_backend_up",
                1 if r["breaker"] == CLOSED and not r["unavailable"]
                else 0, lab,
                help="1 while breaker-closed and not draining")
        p.gauge("dvt_gateway_backend_breaker_state",
                {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}[r["breaker"]], lab,
                help="0 closed, 1 half-open, 2 open")
        p.counter("dvt_gateway_backend_successes_total",
                  r["successes"], lab)
        p.counter("dvt_gateway_backend_failures_total",
                  r["failures"], lab)
        p.counter("dvt_gateway_backend_sheds_total", r["sheds"], lab)
        p.counter("dvt_gateway_backend_breaker_opens_total",
                  r["breaker_opens"], lab)
        p.gauge("dvt_gateway_backend_outstanding", r["outstanding"],
                lab, help="Requests in flight to this backend")
        p.gauge("dvt_gateway_backend_ewma_seconds",
                r["ewma_ms"] / 1e3 if r["ewma_ms"] is not None
                else None, lab, help="Per-backend latency EWMA")
        rb = r.get("retry_budget") or {}
        p.gauge("dvt_gateway_backend_retry_tokens", rb.get("tokens"),
                lab, help="Retry-budget tokens available (refilled "
                          "by successes, spent by retries)")
        p.counter("dvt_gateway_backend_retries_granted_total",
                  rb.get("granted"), lab)
        p.counter("dvt_gateway_backend_retries_denied_total",
                  rb.get("denied"), lab)
        conns = r.get("conns") or {}
        p.counter("dvt_gateway_backend_conns_created_total",
                  conns.get("created"), lab,
                  help="Backend connections dialed")
        p.counter("dvt_gateway_backend_conns_reused_total",
                  conns.get("reused"), lab,
                  help="Keep-alive checkouts from the backend pool")
    p.histogram("dvt_gateway_request_latency_seconds",
                g["latency_hist"],
                help="Gateway-side forward() latency (incl. retries)")
    if g.get("backend_latency_hist"):
        p.histogram("dvt_gateway_backend_latency_seconds",
                    g["backend_latency_hist"],
                    help="Backend engine latency merged fleet-wide")
    mfu = g.get("mfu") or {}
    p.gauge("dvt_gateway_serving_mfu", mfu.get("serving_mfu"),
            help="Fleet serving MFU (summed FLOPs / summed compute "
                 "seconds / peak)")
    cas = g.get("cascade")
    if isinstance(cas, dict):
        p.counter("dvt_gateway_cascade_escalations_total",
                  cas.get("escalations"),
                  help="Cascade escalations summed across backends")
        p.gauge("dvt_gateway_cascade_escalation_rate",
                cas.get("escalation_rate"),
                help="Fleet-wide fraction of cheap-tier-judged "
                     "requests escalated down the chain")
        for tier, n in sorted((cas.get("served") or {}).items()):
            p.counter("dvt_gateway_cascade_requests_total", n,
                      {"tier": str(tier)},
                      help="Cascade answers fleet-wide by answering "
                           "tier")
        for hop in (cas.get("hops") or []):
            hlab = {"hop": str(hop.get("hop")),
                    "tier": str(hop.get("tier"))}
            p.counter("dvt_gateway_cascade_hop_escalations_total",
                      hop.get("escalations"), hlab,
                      help="Requests this hop escalated onward, "
                           "summed across backends")
            p.gauge("dvt_gateway_cascade_hop_calibrated_backends",
                    hop.get("calibrated_backends"), hlab,
                    help="Backends where this hop currently holds a "
                         "calibrated threshold")
    tr = g.get("trace") or {}
    p.counter("dvt_gateway_traces_finished_total", tr.get("finished"),
              help="Gateway spans sealed into the ring")
    p.counter("dvt_gateway_slow_traces_total", tr.get("slow_sampled"),
              help="Gateway traces over the slow threshold")
    for stage, secs in (tr.get("stage_s_total") or {}).items():
        p.counter("dvt_gateway_stage_seconds_total", secs,
                  {"stage": stage},
                  help="Cumulative gateway span stage time")
    return p.render()


class _GatewayHandler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    _rid = None

    def setup(self):
        # per-connection socket timeout (StreamRequestHandler applies
        # self.timeout): a stalled client can't pin a handler thread
        self.timeout = self.server.socket_timeout_s  # type: ignore
        super().setup()

    def log_message(self, fmt, *args):
        if self.server.verbose:  # type: ignore[attr-defined]
            super().log_message(fmt, *args)

    def _reply(self, status: int, payload: dict,
               headers: dict | None = None):
        blob = json.dumps(payload).encode()
        self._reply_raw(status, blob, headers)

    def _reply_raw(self, status: int, blob: bytes,
                   headers: dict | None = None):
        self.send_response(status)
        headers = dict(headers or {})
        headers.setdefault("Content-Type", "application/json")
        if self._rid is not None:
            headers.setdefault(REQUEST_ID_HEADER, self._rid)
        for k, v in headers.items():
            self.send_header(k, str(v))
        self.send_header("Content-Length", str(len(blob)))
        self.end_headers()
        self.wfile.write(blob)

    def do_GET(self):
        gw: Gateway = self.server.gateway  # type: ignore[attr-defined]
        path, _, query = self.path.partition("?")
        if path == "/v1/healthz":
            ok, payload = gw.healthz()
            self._reply(200 if ok else 503, payload)
        elif path == "/v1/stats":
            stats = gw.stats()
            edge_stats = getattr(self.server, "stats", None)
            if callable(edge_stats):
                stats["edge"] = edge_stats()
            self._reply(200, stats)
        elif path == "/metrics":
            edge_stats = getattr(self.server, "stats", None)
            text = render_gateway_metrics(
                gw, edge=edge_stats() if callable(edge_stats) else None)
            self._reply_raw(
                200, text.encode(),
                {"Content-Type":
                 "text/plain; version=0.0.4; charset=utf-8"})
        elif path == "/v1/traces":
            n = int(parse_qs(query).get("n", ["32"])[0])
            self._reply(200, {"traces": gw.tracer.recent(n),
                              "summary": gw.tracer.summary()})
        else:
            self._reply(404, {"error": f"no route {self.path}"})

    def do_POST(self):
        gw: Gateway = self.server.gateway  # type: ignore[attr-defined]
        path = self.path.partition("?")[0]
        # one id for the whole path: reuse the client's if it sent one,
        # mint otherwise; forward() sends it to the backend and its
        # reply echo lands on our response via _reply_raw
        self._rid = self.headers.get(REQUEST_ID_HEADER) \
            or new_request_id()
        try:
            # /v1/models/<name>/<verb> routes on the path's model (the
            # gateway filters to backends probing that name); lifecycle
            # verbs forward to EVERY backend serving it — a reload must
            # reach the whole fleet, not one member.  The inference
            # verb set derives from the workload registry
            # (serve/workloads.py) — same source as the backends, so
            # the gateway never 404s a verb a backend would serve
            from deep_vision_tpu.serve.workloads import (
                LIFECYCLE_VERBS,
                infer_paths,
                infer_verbs,
            )

            parts = path.split("/")
            model_route = (len(parts) == 5 and parts[1] == "v1"
                           and parts[2] == "models")
            if model_route and parts[4] in LIFECYCLE_VERBS:
                self._lifecycle_fanout(gw, parts[3], parts[4])
                return
            if path not in infer_paths() and not (
                    model_route and parts[4] in infer_verbs()):
                self._reply(404, {
                    "error": f"no route {self.path}",
                    "supported_verbs": sorted(
                        infer_verbs() + LIFECYCLE_VERBS)})
                return
            length = int(self.headers.get("Content-Length") or 0)
            if length <= 0:
                self._reply(400, {"error": "empty body"})
                return
            cap = self.server.max_body_bytes  # type: ignore
            if length > cap:
                self.close_connection = True
                self._reply(413, {"error": f"body of {length} bytes "
                                           f"exceeds the {cap}-byte cap"})
                return
            body = self.rfile.read(length)
            status, headers, payload = gw.forward(self.path, body,
                                                  request_id=self._rid)
            self._reply_raw(status, payload, headers)
        except TimeoutError:
            # client stalled mid-body: answer 408 and drop the
            # connection instead of pinning this thread
            self.close_connection = True
            self._reply(408, {"error": "timed out reading request body"})
        except Exception as e:  # noqa: BLE001 — surface, don't kill worker
            self._reply(500, {"error": f"{type(e).__name__}: {e}"})
        finally:
            self._rid = None

    def _lifecycle_fanout(self, gw: Gateway, name: str, verb: str):
        """POST /v1/models/<name>/<verb> to every routable backend that
        serves ``name``; the per-backend verdicts come back keyed by
        backend.  200 when at least one backend accepted; 409 when none
        accepted but at least one answered 409 (reload already in
        progress / nothing to promote — the fleet is busy, not broken);
        502 only when every backend actually failed the call."""
        length = int(self.headers.get("Content-Length") or 0)
        body = self.rfile.read(length) if length > 0 else b"{}"
        now = time.monotonic()
        results: dict = {}
        any_ok = any_busy = False
        for b in gw.backends:
            if not b.routable(now) or not b.serves(name):
                continue
            try:
                status, _, payload = gw._call(
                    b, "POST", f"/v1/models/{name}/{verb}", body,
                    gw.request_timeout_s)
                try:
                    doc = json.loads(payload)
                except ValueError:
                    doc = {"raw": payload.decode(errors="replace")}
                # the HTTP code gets its own key: the backend's body
                # carries a "status" verdict string (reloading/refused/
                # in_progress) that must not mask it
                results[b.name] = {"http_status": status, **(
                    doc if isinstance(doc, dict) else {"body": doc})}
                any_ok = any_ok or status == 200
                any_busy = any_busy or status == 409
            except (OSError, HTTPException) as e:
                results[b.name] = {"http_status": None,
                                   "error": f"{type(e).__name__}: {e}"}
        if not results:
            self._reply(503, {"error": f"no routable backend serves "
                                       f"'{name}'"})
            return
        self._reply(200 if any_ok else (409 if any_busy else 502),
                    {"model": name, "verb": verb, "backends": results})


class GatewayServer:
    """HTTP front for a ``Gateway`` (mirrors ``serve.http.ServeServer``):
    the selector edge by default, ``edge=False`` for the
    thread-per-request baseline."""

    def __init__(self, gateway: Gateway, host: str = "127.0.0.1",
                 port: int = 0, verbose: bool = False,
                 max_body_bytes: int = 32 * 2**20,
                 socket_timeout_s: float | None = 30.0,
                 edge: bool = True,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 http_workers: int = 8):
        self.gateway = gateway
        if edge:
            self.httpd = EdgeServer((host, port), _GatewayHandler,
                                    max_connections=max_connections,
                                    workers=http_workers,
                                    name="gateway")
        else:
            self.httpd = ThreadingHTTPServer((host, port),
                                             _GatewayHandler)
        self.httpd.gateway = gateway
        self.httpd.verbose = verbose
        self.httpd.max_body_bytes = max_body_bytes
        self.httpd.socket_timeout_s = socket_timeout_s
        self._thread: threading.Thread | None = None

    @property
    def host(self) -> str:
        return self.httpd.server_address[0]

    @property
    def port(self) -> int:
        return self.httpd.server_address[1]

    def serve_forever(self):
        self.httpd.serve_forever()

    def start_background(self) -> "GatewayServer":
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="gateway-http", daemon=True)
        self._thread.start()
        return self

    def shutdown(self):
        self.httpd.shutdown()
        self.httpd.server_close()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
