"""Selector-based async HTTP edge shared by both serving tiers.

The stdlib ``ThreadingHTTPServer`` front-ends spent one OS thread per
CONNECTION: a keep-alive client pinned a thread while idle, a thousand
open sockets meant a thousand stacks, and connection churn (accept +
thread spawn + teardown per request) capped offered load well below
what the engines behind them sustain.  ``EdgeServer`` replaces that
with one event-loop thread over ``selectors.DefaultSelector`` and
non-blocking sockets, plus a small worker pool that only ever holds a
thread for the duration of one REQUEST:

  keep-alive      HTTP/1.1 persistent connections with pipelined
                  request parsing — requests are parsed off the input
                  buffer as they complete and responses are delivered
                  strictly in request order per connection (ordered
                  response slots), so a burst of back-to-back POSTs on
                  one socket overlaps handler execution.
  bounded conns   ``max_connections`` caps concurrently open sockets.
                  At capacity the loop first evicts the oldest IDLE
                  connection (no buffered input, no request in flight);
                  with nothing idle it pauses accepting (the listener
                  leaves the selector — new clients queue in the TCP
                  backlog) and resumes as soon as a slot frees.
  deadlines       per-connection read/write deadlines preserve the
                  thread-server's slow-loris semantics byte for byte: a
                  connection that never sends a request line (or stalls
                  mid-headers, or sits idle between keep-alive
                  requests) is closed silently after
                  ``socket_timeout_s``; one that stalls MID-BODY after
                  delivering complete headers is answered 408 and
                  closed; a peer that stops reading while a response is
                  buffered is closed once the write stalls past the
                  same deadline.
  handler reuse   parsed requests run the UNCHANGED
                  ``BaseHTTPRequestHandler`` route classes
                  (``serve/http.py _Handler``, ``serve/gateway.py
                  _GatewayHandler``) against in-memory rfile/wfile
                  pairs — the routes, status lines, and headers move
                  over without behavior change, and the worker pool
                  bounds handler concurrency instead of the OS thread
                  count.

Oversized bodies are rejected without buffering: a Content-Length over
``max_body_bytes`` dispatches immediately with an EMPTY body and the
handler's own 413 path (which checks the header before reading rfile)
answers before the client has shipped the payload — same contract as
the threaded server, no attacker-sized allocation.

``stats()`` feeds ``dvt_serve_open_connections`` and the connection
counters (accepted / evicted / accept-pauses / keep-alive reuse) on
``/metrics`` — ``make edge-smoke`` asserts keep-alive reuse and the
slow-loris/408 contract over real sockets (docs/SERVING.md "Async
edge").
"""

from __future__ import annotations

import io
import json
import selectors
import socket
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from http.client import parse_headers

from deep_vision_tpu.analysis.sanitizer import new_lock
from deep_vision_tpu.obs.log import event, get_logger

_log = get_logger("dvt.serve.edge")

DEFAULT_MAX_CONNECTIONS = 1024
_MAX_HEAD_BYTES = 64 * 1024
_RECV_CHUNK = 256 * 1024
_TICK_S = 0.05  # deadline-check granularity

_HEAD = "head"   # awaiting request line + headers
_BODY = "body"   # headers parsed, awaiting Content-Length bytes


class _Slot:
    """One request's ordered response slot on its connection.

    Buffered responses (``chunks is None``) fill ``data`` once and flip
    ``done``.  Streaming responses (HTTP/1.1 chunked transfer) set
    ``chunks`` to a deque the worker appends framed pieces to while the
    loop drains the head slot incrementally; ``done`` flips only after
    the terminating ``0\\r\\n\\r\\n`` frame (or, on a mid-stream handler
    error, without it — a truncated chunked body is how HTTP signals an
    incomplete response — with ``close`` set so the connection drops)."""

    __slots__ = ("done", "data", "close", "chunks")

    def __init__(self):
        self.done = False
        self.data = b""
        self.close = False
        self.chunks: deque | None = None


class _Conn:
    """Per-connection parse + write state, owned by the loop thread."""

    __slots__ = ("sock", "fd", "addr", "inbuf", "outbuf", "state",
                 "need", "method", "path", "version", "headers",
                 "body_parts", "pending", "requests", "last_activity",
                 "closing", "want_write")

    def __init__(self, sock, addr, now: float):
        self.sock = sock
        self.fd = sock.fileno()
        self.addr = addr
        self.inbuf = bytearray()
        self.outbuf = bytearray()
        self.state = _HEAD
        self.need = 0
        self.method = ""
        self.path = ""
        self.version = "HTTP/1.1"
        self.headers = None
        self.body_parts: list = []
        self.pending: deque = deque()  # _Slot, in request order
        self.requests = 0
        self.last_activity = now
        self.closing = False
        self.want_write = False

    def idle(self) -> bool:
        """Evictable: nothing buffered either way, no request in
        flight, between requests."""
        return (self.state == _HEAD and not self.inbuf
                and not self.outbuf and not self.pending)


class EdgeServer:
    """One selector event loop + worker pool behind a listening socket.

    Drop-in for the ``ThreadingHTTPServer`` slot in ``ServeServer`` /
    ``GatewayServer``: exposes ``server_address``, ``serve_forever()``,
    ``shutdown()``, ``server_close()`` and carries arbitrary context
    attributes (registry / engines / plane / gateway / ...) that the
    handler classes read via ``self.server.<attr>``.
    """

    def __init__(self, address: tuple, handler_cls, *,
                 max_connections: int = DEFAULT_MAX_CONNECTIONS,
                 workers: int = 8, name: str = "edge"):
        self.handler_cls = handler_cls
        self.max_connections = max(1, int(max_connections))
        self.name = name
        self._listener = socket.create_server(
            address, backlog=128, reuse_port=False)
        self._listener.setblocking(False)
        self.server_address = self._listener.getsockname()[:2]
        self._selector = selectors.DefaultSelector()
        self._selector.register(self._listener, selectors.EVENT_READ,
                                "accept")
        # loop wakeup: workers post completed responses then poke this
        self._wake_r, self._wake_w = socket.socketpair()
        self._wake_r.setblocking(False)
        self._wake_w.setblocking(False)
        self._selector.register(self._wake_r, selectors.EVENT_READ,
                                "wake")
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, int(workers)),
            thread_name_prefix=f"{name}-worker")
        self._ready_lock = new_lock("serve.edge.EdgeServer._ready_lock")
        self._ready: list = []  # guarded-by: _ready_lock
        self._conns: dict[int, _Conn] = {}  # loop thread only
        self._accept_paused = False
        self._stop_event = threading.Event()
        self._loop_done = threading.Event()
        self._closed = False
        # counters: loop-thread writes only; stats() reads are atomic
        # int loads, so no lock (same pattern as the engine's _forming)
        self.accepted = 0
        self.evicted_idle = 0
        self.accept_pauses = 0
        self.requests_handled = 0
        self.keepalive_reuses = 0
        self.timeouts_408 = 0
        self.closed_idle = 0
        self.overlong_heads = 0
        # streaming counters are WORKER-thread writes (unlike the loop
        # counters above), so they ride the existing response lock
        self.streams_started = 0  # guarded-by: _ready_lock
        self.stream_errors = 0  # guarded-by: _ready_lock
        self.draining = False  # handler context default; tiers override

    # -- lifecycle (ThreadingHTTPServer-compatible surface) ----------------

    def serve_forever(self):
        """Run the event loop until ``shutdown()``; blocks the caller
        (``ServeServer.start_background`` gives it a thread)."""
        try:
            while not self._stop_event.is_set():
                self._tick()
        finally:
            self._teardown()
            self._loop_done.set()

    def shutdown(self):
        """Stop the loop from another thread; open connections are
        closed abruptly (the SIGKILL shape chaos tests rely on)."""
        self._stop_event.set()
        self._wake()
        self._loop_done.wait(5.0)

    def server_close(self):
        if self._closed:
            return
        self._closed = True
        # if the loop never ran (shutdown before serve_forever), the
        # teardown here is the only close these sockets get
        if not self._loop_done.is_set():
            self._stop_event.set()
            try:
                self._listener.close()
            except OSError:
                pass
        self._pool.shutdown(wait=False)

    # -- the event loop ----------------------------------------------------

    def _tick(self):  # dvtlint: hot
        for key, _mask in self._selector.select(_TICK_S):
            if key.data == "accept":
                self._accept()
            elif key.data == "wake":
                self._drain_wake()
            else:
                self._io(key.data, _mask)
        self._flush_ready()
        self._check_deadlines()

    def _accept(self):  # dvtlint: hot
        # ONE accept per readiness event: the selector is level-
        # triggered, so a still-pending backlog re-reports the listener
        # next tick.  This keeps the capacity check honest — it only
        # runs when a connection really is waiting, so an idle victim
        # is never evicted for a phantom arrival.
        if len(self._conns) >= self.max_connections \
                and not self._evict_idle():
            self._pause_accept()
            return
        try:
            sock, addr = self._listener.accept()
        except (BlockingIOError, InterruptedError):
            return
        except OSError:
            return  # listener closed under us mid-shutdown
        sock.setblocking(False)
        try:
            sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        except OSError:
            pass
        conn = _Conn(sock, addr, time.monotonic())
        self._conns[conn.fd] = conn
        self._selector.register(sock, selectors.EVENT_READ, conn)
        self.accepted += 1

    def _evict_idle(self) -> bool:
        """Close the oldest idle connection to admit a new one."""
        victim = None
        for conn in self._conns.values():
            if not conn.idle():
                continue
            if victim is None or conn.last_activity \
                    < victim.last_activity:
                victim = conn
        if victim is None:
            return False
        self.evicted_idle += 1
        self._close_conn(victim)
        return True

    def _pause_accept(self):
        if not self._accept_paused:
            self._accept_paused = True
            self.accept_pauses += 1
            self._selector.unregister(self._listener)
            event(_log, "edge_accept_paused", edge=self.name,
                  open_connections=len(self._conns))

    def _resume_accept(self):
        if self._accept_paused \
                and len(self._conns) < self.max_connections:
            self._accept_paused = False
            self._selector.register(self._listener,
                                    selectors.EVENT_READ, "accept")
            event(_log, "edge_accept_resumed", edge=self.name,
                  open_connections=len(self._conns))

    def _drain_wake(self):
        try:
            while self._wake_r.recv(4096):
                pass
        except (BlockingIOError, OSError):
            pass

    def _wake(self):
        try:
            self._wake_w.send(b"\x00")
        except (BlockingIOError, OSError):
            pass  # wakeup already pending, or loop torn down

    def _io(self, conn: _Conn, mask: int):  # dvtlint: hot
        if conn.sock is None:
            return
        if mask & selectors.EVENT_READ:
            try:
                data = conn.sock.recv(_RECV_CHUNK)
            except (BlockingIOError, InterruptedError):
                data = None
            except OSError:
                self._close_conn(conn)
                return
            if data == b"":
                self._close_conn(conn)  # peer EOF
                return
            if data:
                conn.last_activity = time.monotonic()
                conn.inbuf += data
                if not self._parse(conn):
                    return  # connection closed during parse
        if mask & selectors.EVENT_WRITE and conn.sock is not None:
            self._write(conn)

    # -- HTTP/1.1 incremental parsing --------------------------------------

    def _parse(self, conn: _Conn) -> bool:  # dvtlint: hot
        """Consume as many complete requests from ``conn.inbuf`` as are
        buffered (pipelining).  Returns False when the connection was
        closed (parse error / oversized head)."""
        while conn.sock is not None and not conn.closing:
            if conn.state == _HEAD:
                end = conn.inbuf.find(b"\r\n\r\n")
                if end < 0:
                    if len(conn.inbuf) > _MAX_HEAD_BYTES:
                        self.overlong_heads += 1
                        self._respond_plain(
                            conn, 431, "Request Header Fields Too Large",
                            {"error": "request head exceeds "
                                      f"{_MAX_HEAD_BYTES} bytes"})
                        conn.closing = True
                        return True
                    return True  # need more bytes
                head = bytes(conn.inbuf[:end])
                del conn.inbuf[:end + 4]
                if not self._parse_head(conn, head):
                    return False
                if conn.state == _HEAD:
                    continue  # request had no body: dispatched already
            if conn.state == _BODY:
                take = min(conn.need, len(conn.inbuf))
                if take:
                    conn.body_parts.append(bytes(conn.inbuf[:take]))
                    del conn.inbuf[:take]
                    conn.need -= take
                if conn.need > 0:
                    return True  # body still streaming in
                body = b"".join(conn.body_parts)
                conn.body_parts = []
                conn.state = _HEAD
                self._dispatch(conn, body)
        return True

    def _parse_head(self, conn: _Conn, head: bytes) -> bool:
        """Request line + headers → either dispatch (no body / over-cap
        body) or switch to body accumulation.  Returns False when the
        connection was closed on a malformed request."""
        line, _, rest = head.partition(b"\r\n")
        parts = line.split()
        if len(parts) == 2:  # HTTP/0.9-style "GET /path"
            parts.append(b"HTTP/1.0")
        if len(parts) != 3:
            self._respond_plain(conn, 400, "Bad Request",
                                {"error": "malformed request line"})
            conn.closing = True
            return True
        try:
            conn.method = parts[0].decode("ascii")
            conn.path = parts[1].decode("iso-8859-1")
            conn.version = parts[2].decode("ascii")
            conn.headers = parse_headers(io.BytesIO(rest + b"\r\n"))
        except (UnicodeDecodeError, ValueError):
            self._respond_plain(conn, 400, "Bad Request",
                                {"error": "malformed request head"})
            conn.closing = True
            return True
        try:
            length = int(conn.headers.get("Content-Length") or 0)
        except ValueError:
            length = 0
        cap = getattr(self, "max_body_bytes", None)
        if cap is not None and length > cap:
            # dispatch NOW with an empty body: the handler's own 413
            # path checks Content-Length before reading rfile, so the
            # reply goes out before the client ships the payload and
            # nothing attacker-sized is ever buffered
            self._dispatch(conn, b"")
            return True
        if length > 0:
            conn.state = _BODY
            conn.need = length
            conn.body_parts = []
        else:
            self._dispatch(conn, b"")
        return True

    # -- request execution (worker pool) ------------------------------------

    def _dispatch(self, conn: _Conn, body: bytes):  # dvtlint: hot
        slot = _Slot()
        conn.pending.append(slot)
        conn.requests += 1
        self.requests_handled += 1
        if conn.requests > 1:
            self.keepalive_reuses += 1
        self._pool.submit(self._execute, conn, slot, conn.method,
                          conn.path, conn.version, conn.headers, body)

    def _execute(self, conn, slot, method, path, version, headers,
                 body):
        """Worker thread: run the handler shim, post the response back
        to the loop through the connection's ordered slot."""
        try:
            data, close, stream = self._handle(method, path, version,
                                               headers, body, conn.addr)
        except Exception as e:  # noqa: BLE001 — a handler bug must answer 500, not hang the slot
            data = _plain_response(
                500, "Internal Server Error", version,
                {"error": f"{type(e).__name__}: {e}"}, close=True)
            close = True
            stream = None
        if stream is None:
            slot.data = data
            slot.close = close
            slot.done = True
            with self._ready_lock:
                self._ready.append(conn)
            self._wake()
            return
        self._stream_slot(conn, slot, data, close, stream)

    def _stream_slot(self, conn, slot, head: bytes, close: bool, stream):
        """Worker thread: pump a chunked response through the slot one
        frame at a time — the loop flushes each frame as it lands, so a
        result set larger than any buffer bound streams in O(1) memory.
        Appends and the loop's poplefts hit opposite ends of the deque
        (atomic under the GIL — the same ordering contract buffered
        slots already rely on for ``data``/``done``)."""
        slot.chunks = deque((head,))
        with self._ready_lock:
            self.streams_started += 1
            self._ready.append(conn)
        self._wake()
        try:
            for piece in stream:
                if conn.sock is None:
                    break  # client went away: stop producing
                if not piece:
                    continue
                slot.chunks.append(_chunk_frame(piece))
                with self._ready_lock:
                    self._ready.append(conn)
                self._wake()
        except Exception:  # noqa: BLE001 — mid-stream generator bug: truncate the chunked body (the HTTP incomplete-response signal) and drop the connection
            with self._ready_lock:
                self.stream_errors += 1
            slot.close = True
            slot.done = True
            with self._ready_lock:
                self._ready.append(conn)
            self._wake()
            return
        finally:
            close_fn = getattr(stream, "close", None)
            if close_fn is not None:
                close_fn()
        slot.chunks.append(_CHUNK_END)
        slot.close = close
        slot.done = True
        with self._ready_lock:
            self._ready.append(conn)
        self._wake()

    def _handle(self, method, path, version, headers, body, addr
                ) -> tuple[bytes, bool]:
        """Run one parsed request through the unchanged
        ``BaseHTTPRequestHandler`` routes against BytesIO files.

        ``send_response``/``send_header``/``end_headers`` write the
        identical status line + header bytes the threaded server
        produced, so the routes move over without behavior change."""
        cls = self.handler_cls
        h = cls.__new__(cls)
        h.server = self
        h.client_address = addr
        h.command = method
        h.path = path
        h.request_version = "HTTP/1.1" if version >= "HTTP/1.1" \
            else version
        h.requestline = f"{method} {path} {version}"
        h.headers = headers
        h.rfile = io.BytesIO(body)
        h.wfile = io.BytesIO()
        # handlers test this to DEFER chunked bodies to the edge loop
        # (http._Handler._reply_stream) instead of writing them inline
        h._edge_stream = True
        conn_hdr = (headers.get("Connection") or "").lower()
        h.close_connection = (
            "close" in conn_hdr
            or (version < "HTTP/1.1"
                and "keep-alive" not in conn_hdr))
        fn = getattr(h, "do_" + method, None)
        if fn is None:
            return _plain_response(
                501, "Unsupported method", version,
                {"error": f"Unsupported method ({method!r})"},
                close=True), True, None
        fn()
        # a streaming route leaves head bytes in wfile and the body
        # generator on h._stream; buffered routes leave _stream unset
        return (h.wfile.getvalue(), bool(h.close_connection),
                getattr(h, "_stream", None))

    # -- loop-side response delivery ----------------------------------------

    def _flush_ready(self):  # dvtlint: hot
        with self._ready_lock:
            ready, self._ready = self._ready, []
        seen = set()
        for conn in ready:
            if conn.fd in seen:
                continue
            seen.add(conn.fd)
            if conn.sock is None:
                continue  # client went away; drop the response
            self._pump(conn)

    def _pump(self, conn: _Conn):  # dvtlint: hot
        """Move completed responses (in request order) into the output
        buffer, then write greedily.  A streaming head slot drains
        whatever frames its worker has produced so far even while not
        done — that's what makes chunked responses flow instead of
        buffering whole — but later slots still wait their turn."""
        while conn.pending:
            slot = conn.pending[0]
            # read done BEFORE draining chunks: the worker appends its
            # last frame before flipping done, so done-then-drain can
            # never strand a frame behind a popped slot
            done = slot.done
            if slot.chunks is not None:
                while slot.chunks:
                    conn.outbuf += slot.chunks.popleft()
            if not done:
                break  # head-of-line still executing/streaming
            if slot.chunks is None:
                conn.outbuf += slot.data
            conn.pending.popleft()
            if slot.close:
                conn.closing = True
                conn.pending.clear()
                break
        self._write(conn)

    def _write(self, conn: _Conn):  # dvtlint: hot
        if conn.sock is None:
            return
        while conn.outbuf:
            try:
                sent = conn.sock.send(conn.outbuf)
            except (BlockingIOError, InterruptedError):
                break
            except OSError:
                self._close_conn(conn)
                return
            if sent <= 0:
                break
            del conn.outbuf[:sent]
            conn.last_activity = time.monotonic()
        if conn.outbuf and not conn.want_write:
            conn.want_write = True
            self._selector.modify(
                conn.sock, selectors.EVENT_READ | selectors.EVENT_WRITE,
                conn)
        elif not conn.outbuf and conn.want_write:
            conn.want_write = False
            self._selector.modify(conn.sock, selectors.EVENT_READ, conn)
        if not conn.outbuf and conn.closing and not conn.pending:
            self._close_conn(conn)

    # -- deadlines -----------------------------------------------------------

    def _check_deadlines(self):  # dvtlint: hot
        timeout_s = getattr(self, "socket_timeout_s", None)
        if not timeout_s:
            return
        now = time.monotonic()
        for conn in list(self._conns.values()):
            if conn.sock is None:
                continue
            if now - conn.last_activity < timeout_s:
                continue
            if conn.outbuf:
                # write deadline: the peer stopped reading while a
                # response is buffered — drop the connection
                self._close_conn(conn)
            elif conn.pending:
                continue  # request executing in the pool: not a stall
            elif conn.state == _BODY:
                # complete headers, stalled body: answer 408 and close
                # (the threaded server's TimeoutError-in-do_POST path)
                self.timeouts_408 += 1
                self._respond_plain(
                    conn, 408, "Request Timeout",
                    {"error": "timed out reading request body"})
                conn.closing = True
            else:
                # no request line (slow-loris), stalled headers, or an
                # idle keep-alive connection: close silently — the
                # client sees EOF, exactly like the threaded server
                self.closed_idle += 1
                self._close_conn(conn)

    # -- plumbing ------------------------------------------------------------

    def _respond_plain(self, conn: _Conn, status: int, reason: str,
                       payload: dict):
        """Loop-generated response (no handler): 408/400/431 paths."""
        conn.outbuf += _plain_response(status, reason, "HTTP/1.1",
                                       payload, close=True)
        self._write(conn)

    def _close_conn(self, conn: _Conn):
        sock, conn.sock = conn.sock, None
        if sock is None:
            return
        try:
            self._selector.unregister(sock)
        except (KeyError, ValueError):
            pass
        try:
            sock.close()
        except OSError:
            pass
        self._conns.pop(conn.fd, None)
        conn.pending.clear()
        self._resume_accept()

    def _teardown(self):
        for conn in list(self._conns.values()):
            self._close_conn(conn)
        for sock in (self._listener, self._wake_r, self._wake_w):
            try:
                self._selector.unregister(sock)
            except (KeyError, ValueError):
                pass
            try:
                sock.close()
            except OSError:
                pass
        self._selector.close()
        self._pool.shutdown(wait=False)

    # -- observability -------------------------------------------------------

    def stats(self) -> dict:
        return {"open_connections": len(self._conns),
                "max_connections": self.max_connections,
                "accepted": self.accepted,
                "evicted_idle": self.evicted_idle,
                "accept_pauses": self.accept_pauses,
                "accept_paused": self._accept_paused,
                "requests": self.requests_handled,
                "keepalive_reuses": self.keepalive_reuses,
                "timeouts_408": self.timeouts_408,
                "closed_idle": self.closed_idle,
                "overlong_heads": self.overlong_heads,
                "streams_started": self.streams_started,
                "stream_errors": self.stream_errors,
                "workers": self._pool._max_workers}


#: chunked transfer terminator (RFC 9112 §7.1): zero-length chunk
_CHUNK_END = b"0\r\n\r\n"


def _chunk_frame(data: bytes) -> bytes:
    """One HTTP/1.1 chunked-transfer frame: hex length, CRLF, payload,
    CRLF."""
    return f"{len(data):x}\r\n".encode("ascii") + data + b"\r\n"


def _plain_response(status: int, reason: str, version: str,
                    payload: dict, close: bool = False) -> bytes:
    """A minimal loop-side HTTP/1.1 response (JSON body)."""
    blob = json.dumps(payload).encode()
    head = (f"HTTP/1.1 {status} {reason}\r\n"
            f"Content-Type: application/json\r\n"
            f"Content-Length: {len(blob)}\r\n")
    if close:
        head += "Connection: close\r\n"
    return head.encode("ascii") + b"\r\n" + blob
