"""Post-training int8 quantization for the serving tier.

Scheme (the post-training corner of Jacob et al. 2018): symmetric
per-channel int8 for every conv/dense kernel (absmax over all axes but
the trailing ``cout``), a symmetric per-tensor scale for the ingest
activations, and "simulated-integer" execution — bucket programs keep
the weights INT8-RESIDENT in HBM and dequantize inside the traced
apply (``w_i8.astype(f32) * scale``), which XLA fuses into the weight
read, so the HBM footprint is the int8 one while accumulation stays
float32 and outputs leave the program as float32 (the accuracy-gate
contract in tests/test_quant.py).

What stays float: 1-D leaves (biases, BN scale/shift — a few hundred
bytes that would cost accuracy for no footprint win) and every
``batch_stats`` leaf.  The quantized variables tree

    {"params": <int8/f32 mixed>, "param_scales": <f32 scales>,
     "batch_stats": ...}

is an opaque pytree to everything downstream: the WeightCache's
spill/re-admit (serve/models.py) and ``for_device``/``for_mesh`` views
are leaf-wise ``tree_map``s, so int8 leaves round-trip bit-identically,
and ``param_bytes()`` reports the true ~0.26× footprint for free — on
a 2-D mesh view, the per-chip int8 shard.  Model-parallel layouts
compose: kernels quantize per-OUT-channel, so sharding a kernel's
trailing ``cout`` over ``model`` (the rule tables' and fallback
sharder's choice) splits the int8 leaf while its 1-D scale vector
replicates — the in-trace ``w_i8 * scale`` broadcast stays local to
each shard, no extra collectives.  Strict rule tables must still cover
the ``param_scales/...`` paths (the built-ins' catch-all does).

Calibration runs a held-out batch (or a deterministic synthetic one)
through an instrumented forward (``capture_intermediates``) to collect
per-path activation absmax ranges plus the post-normalize input absmax
that prices the ingest scale.  It is pure: the same batches always
produce identical scales (tests/test_quant.py determinism gate).
"""

from __future__ import annotations

import dataclasses
import glob
import os

import numpy as np


@dataclasses.dataclass(frozen=True)
class Calibration:
    """What the calibration pass measured (all host floats, JSON-safe).

    ``act_scale`` is the per-tensor symmetric scale the ingest kernel
    quantizes normalized activations with (``q = round(x/act_scale)``);
    ``ranges`` maps each captured intermediate's path to its absmax over
    the calibration batches (the per-tensor activation ranges a future
    fully-integer backend would consume)."""

    act_scale: float
    act_absmax: float
    ranges: dict
    batches: int
    batch_size: int
    source: str

    def describe(self) -> dict:
        """Compact JSON block for ``ServingModel.describe()`` — the full
        per-path ``ranges`` dict stays on the object (it can be hundreds
        of entries for deep nets)."""
        return {"act_scale": self.act_scale,
                "act_absmax": self.act_absmax,
                "activation_ranges": len(self.ranges),
                "calib_batches": self.batches,
                "calib_batch_size": self.batch_size,
                "calib_source": self.source}


def _quantize_leaf(w):
    """One param leaf → (stored leaf, scale leaf).

    Conv/dense kernels (ndim ≥ 2, float) become symmetric per-channel
    int8 over the trailing (cout) axis; everything else passes through
    with a scalar identity scale so the two trees stay congruent for
    ``tree_map``.  All-zero channels get scale 1.0 (quantize to 0
    exactly) instead of a 0/0."""
    import jax

    a = np.asarray(jax.device_get(w))
    if a.ndim >= 2 and a.dtype.kind == "f":
        a32 = a.astype(np.float32)
        absmax = np.max(np.abs(a32), axis=tuple(range(a.ndim - 1)))
        scale = np.where(absmax > 0.0, absmax / 127.0, 1.0)
        scale = scale.astype(np.float32)
        q = np.clip(np.rint(a32 / scale), -127.0, 127.0).astype(np.int8)
        return q, scale
    return a, np.asarray(1.0, np.float32)


def quantize_params(params) -> tuple:
    """params pytree → (quantized pytree, scale pytree), same structure.

    Quantized leaves are int8 with a (cout,)-shaped f32 scale that
    broadcasts over the kernel's trailing axis; unquantized leaves keep
    their dtype with a 0-d identity scale."""
    import jax

    pairs = jax.tree_util.tree_map(_quantize_leaf, params)
    is_pair = lambda t: isinstance(t, tuple)  # noqa: E731
    q = jax.tree_util.tree_map(lambda t: t[0], pairs, is_leaf=is_pair)
    s = jax.tree_util.tree_map(lambda t: t[1], pairs, is_leaf=is_pair)
    return q, s


def dequantize_params(qparams, scales, dtype=None):  # dvtlint: traced
    """Traced inverse of :func:`quantize_params`: int8 leaves expand to
    ``dtype`` (default float32) inside the bucket program — XLA fuses
    the cast+multiply into the weight HBM read, so the f32 copy never
    persists — and float leaves pass through untouched."""
    import jax
    import jax.numpy as jnp

    if dtype is None:
        dtype = jnp.float32

    def leaf(w, s):
        if w.dtype == jnp.int8:
            return w.astype(dtype) * s.astype(dtype)
        return w

    return jax.tree_util.tree_map(leaf, qparams, scales)


def synthetic_calibration_batches(input_shape, n_batches: int = 2,
                                  batch_size: int = 8) -> list:
    """Deterministic uint8 calibration data for workflows without a
    held-out set (bench, smoke, random-init tests): a fresh
    ``RandomState(0)`` every call, so two calibrations of the same model
    see byte-identical batches → identical scales."""
    rng = np.random.RandomState(0)
    return [rng.randint(0, 256, (batch_size, *input_shape), dtype=np.uint8)
            for _ in range(n_batches)]


def load_calibration_dir(calib_dir: str, input_shape,
                         n_batches: int = 2,
                         batch_size: int = 8) -> list:
    """Held-out calibration data: ``*.npy`` files (or ``*.npz``
    archives — the first array whose key is ``image``/``images``, else
    the first array) under ``calib_dir``, each a uint8 HWC image or
    NHWC batch of ``input_shape`` images, loaded in sorted order
    (deterministic) and re-batched — the layout ``--gate-dir`` holdouts
    share, so one directory feeds both the accuracy gate and int8
    calibration."""
    paths = sorted(glob.glob(os.path.join(calib_dir, "*.npy"))
                   + glob.glob(os.path.join(calib_dir, "*.npz")))
    if not paths:
        raise FileNotFoundError(
            f"no *.npy/*.npz calibration files under {calib_dir}")
    imgs = []
    want = tuple(input_shape)
    for p in paths:
        a = np.load(p)
        if isinstance(a, np.lib.npyio.NpzFile):
            with a as z:
                keys = list(z.files)
                if not keys:
                    raise ValueError(f"{p}: empty npz archive")
                key = next((k for k in ("image", "images")
                            if k in keys), keys[0])
                a = z[key]
        if a.ndim == len(want):
            a = a[None]
        if a.ndim != len(want) + 1 or tuple(a.shape[1:]) != want:
            raise ValueError(
                f"{p}: expected uint8 images of shape {want} "
                f"(or batches thereof), got {a.shape}")
        imgs.append(np.asarray(a, np.uint8))
        if sum(len(i) for i in imgs) >= n_batches * batch_size:
            break
    flat = np.concatenate(imgs)[:n_batches * batch_size]
    if len(flat) < batch_size:
        raise ValueError(
            f"{calib_dir} holds {len(flat)} calibration images; "
            f"need at least one batch of {batch_size}")
    return [flat[i:i + batch_size]
            for i in range(0, len(flat) - batch_size + 1, batch_size)]


def _path_str(path) -> str:
    parts = []
    for p in path:
        key = getattr(p, "key", getattr(p, "idx", getattr(p, "name", p)))
        parts.append(str(key))
    return "/".join(parts)


def calibrate(model, variables, batches, kind: str) -> Calibration:
    """Instrumented forward over ``batches`` (uint8 NHWC) → Calibration.

    Each batch is normalized exactly like the serving wire
    (ops/preprocess.serve_normalize for ``kind``), then run through
    ``model.apply(..., capture_intermediates=True)``; the input absmax
    over all batches prices the per-tensor ingest scale and every
    captured intermediate contributes its per-path absmax range.  Pure
    function of (weights, batches): no RNG, no clock."""
    import jax
    import jax.numpy as jnp

    from deep_vision_tpu.ops.preprocess import serve_normalize

    if not batches:
        raise ValueError("calibration needs at least one batch")
    act_absmax = 0.0
    ranges: dict[str, float] = {}
    for b in batches:
        x = serve_normalize(jnp.asarray(np.asarray(b, np.uint8)), kind)
        act_absmax = max(act_absmax,
                         float(jax.device_get(jnp.max(jnp.abs(x)))))
        _, st = model.apply(variables, x, train=False,
                            capture_intermediates=True,
                            mutable=["intermediates"])
        flat, _ = jax.tree_util.tree_flatten_with_path(
            st["intermediates"])
        for path, leaf in flat:
            if not jnp.issubdtype(leaf.dtype, jnp.floating):
                continue
            key = _path_str(path)
            ranges[key] = max(
                ranges.get(key, 0.0),
                float(jax.device_get(jnp.max(jnp.abs(leaf)))))
    act_absmax = act_absmax if act_absmax > 0.0 else 1.0
    return Calibration(act_scale=act_absmax / 127.0,
                       act_absmax=act_absmax,
                       ranges=dict(sorted(ranges.items())),
                       batches=len(batches),
                       batch_size=int(np.asarray(batches[0]).shape[0]),
                       source="")


def quantize_for_serving(model, variables, *, kind: str, input_shape,
                         calib_batches: int = 2,
                         calib_dir: str | None = None,
                         batch_size: int = 8) -> tuple:
    """The registry's one-call int8 load path → (qvariables, Calibration).

    Calibrates on ``calib_dir``'s held-out images when given (the real
    deployment path), else on deterministic synthetic batches (bench /
    smoke / random-init tests), then quantizes the weights.  The
    returned tree is what ``CheckpointServingModel._variables`` becomes:
    int8 weights + their scales + untouched batch_stats."""
    if calib_dir:
        batches = load_calibration_dir(calib_dir, input_shape,
                                       n_batches=calib_batches,
                                       batch_size=batch_size)
        source = calib_dir
    else:
        batches = synthetic_calibration_batches(
            input_shape, n_batches=calib_batches, batch_size=batch_size)
        source = "synthetic"
    calib = calibrate(model, variables, batches, kind)
    calib = dataclasses.replace(calib, source=source)
    qparams, scales = quantize_params(variables["params"])
    qvariables = {"params": qparams, "param_scales": scales}
    if variables.get("batch_stats"):
        qvariables["batch_stats"] = variables["batch_stats"]
    return qvariables, calib
