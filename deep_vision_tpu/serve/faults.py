"""Deterministic fault-injection plane for the serving engine.

Crash-only software (Candea & Fox, HotOS'03) argues the recovery path
must be the *tested* path — which requires failures you can produce on
demand, in-tree, deterministically.  A ``FaultPlane`` parses a spec
string into per-stage injection points that the engine (and the HTTP
front-end) consult at well-defined places in the request lifecycle:

    stage     where it fires
    -------   ------------------------------------------------------
    decode    http.py request decoding, before admission
    batcher   top of the batcher loop (mode ``die`` kills the thread)
    staging   after the batch's host buffer is checked out
    dispatch  immediately before the H2D + compiled call
    compute   the compiled program execution (and every retry of it)
    d2h       the drainer's bulk device_get
    gateway   the gateway's per-attempt backend call
              (serve/gateway.py ``_single``) — the NETWORK between
              gateway and backend, not the backend itself

    mode       effect
    ---------  -----------------------------------------------------
    exception  raise ``InjectedFault`` at the injection point
    latency    sleep ``delay_ms`` (spike, request still succeeds)
    hang       block up to ``hang_s`` or until cancelled (exercises
               the watchdog's exec-timeout fast-fail)
    nan        corrupt the fetched output with NaNs (caught by the
               engine's output validation → isolation path)
    poison     mark the ``nth`` submitted request poison: any cohort
               containing it fails at the compute stage, so
               bisect-retry must quarantine exactly that request
    die        raise ``KillThread`` (BaseException) so the stage's
               worker thread exits and the watchdog must restart it
    conn_reset raise ``ConnectionResetError`` (an OSError, exactly
               what a peer RST surfaces as) — the gateway's breaker/
               retry-budget machinery must absorb it
    slow_drip  sleep ``delay_ms`` mid-attempt — a congested link
               dripping bytes; pushes attempts past hedging and
               timeout thresholds without failing them outright
    blackhole  block up to ``hang_s`` (or until cancelled), then
               raise ``TimeoutError`` — packets leaving, nothing
               coming back, the worst network failure mode

Spec syntax (``--faults`` / env ``DVT_SERVE_FAULTS``): semicolon-
separated faults, each ``stage:mode[:key=value]...`` — e.g.

    compute:poison:nth=3
    compute:exception:times=1;d2h:latency:delay_ms=20
    batcher:die:times=1
    d2h:hang:hang_s=30:after=2

Keys: ``p`` (fire probability, seeded RNG → reproducible), ``after``
(skip the first N eligible hits), ``times`` (fire at most N times),
``delay_ms``, ``hang_s``, ``nth``.  A plane with an empty spec is
disabled and costs one attribute read per guarded call site — the
hot path stays hot.
"""

from __future__ import annotations

import dataclasses
import os
import random
import threading

from deep_vision_tpu.analysis.sanitizer import new_lock
import time

STAGES = ("decode", "batcher", "staging", "dispatch", "compute", "d2h",
          "gateway")
MODES = ("exception", "latency", "hang", "nan", "poison", "die",
         "conn_reset", "slow_drip", "blackhole")

ENV_SPEC = "DVT_SERVE_FAULTS"
ENV_SEED = "DVT_SERVE_FAULT_SEED"


class InjectedFault(RuntimeError):
    """Raised by an injection point (mode=exception, a poisoned cohort,
    or NaN-corrupted output caught by validation)."""


class KillThread(BaseException):
    """mode=die: BaseException so per-batch ``except Exception`` guards
    can't swallow it — it escapes the worker loop and kills the thread,
    leaving the watchdog to notice and restart."""


@dataclasses.dataclass
class Quarantined:
    """Structured error delivered to a request the engine isolated.

    ``reason`` is ``"poison"`` (bisect-retry converged on this request)
    or ``"retry_budget"`` (isolation ran out of retries before
    converging).  Falsy like ``Shed`` so ``if result:`` reads as
    "was served"."""

    reason: str
    detail: str = ""

    def __bool__(self):
        return False


@dataclasses.dataclass
class _Fault:
    stage: str
    mode: str
    p: float = 1.0
    after: int = 0
    times: int | None = None
    delay_ms: float = 50.0
    hang_s: float = 30.0
    nth: int = 0
    seen: int = 0
    fired: int = 0


def parse_faults(spec: str) -> list[_Fault]:
    """``stage:mode[:k=v]...[;...]`` → validated fault list."""
    faults = []
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        fields = part.split(":")
        if len(fields) < 2:
            raise ValueError(f"fault '{part}': need stage:mode")
        stage, mode = fields[0], fields[1]
        if stage not in STAGES:
            raise ValueError(f"fault '{part}': unknown stage '{stage}' "
                             f"(one of {', '.join(STAGES)})")
        if mode not in MODES:
            raise ValueError(f"fault '{part}': unknown mode '{mode}' "
                             f"(one of {', '.join(MODES)})")
        f = _Fault(stage, mode)
        for kv in fields[2:]:
            if "=" not in kv:
                raise ValueError(f"fault '{part}': bad option '{kv}'")
            k, v = kv.split("=", 1)
            if k == "p":
                f.p = float(v)
            elif k == "after":
                f.after = int(v)
            elif k == "times":
                f.times = int(v)
            elif k == "delay_ms":
                f.delay_ms = float(v)
            elif k == "hang_s":
                f.hang_s = float(v)
            elif k == "nth":
                f.nth = int(v)
            else:
                raise ValueError(f"fault '{part}': unknown key '{k}'")
        faults.append(f)
    return faults


class FaultPlane:
    """Seeded, thread-safe injection-point registry.

    One plane per engine.  ``enabled`` is False for an empty spec, and
    every call site guards on it first, so production (no faults) pays
    a single attribute read per site.
    """

    def __init__(self, spec: str = "", seed: int = 0):
        self.spec = spec or ""
        self.seed = int(seed)
        self.faults = parse_faults(self.spec)
        self.enabled = bool(self.faults)
        self._rng = random.Random(self.seed)
        self._lock = new_lock("serve.faults.FaultPlane._lock")
        self._submits = 0  # guarded-by: _lock
        #: set by the engine's watchdog / stop() to break injected hangs
        self.cancel = threading.Event()

    @classmethod
    def from_env(cls, environ=None) -> "FaultPlane":
        env = os.environ if environ is None else environ
        return cls(env.get(ENV_SPEC, ""),
                   int(env.get(ENV_SEED, "0") or 0))

    # -- request tagging ---------------------------------------------------

    def mark_poison(self) -> bool:
        """Called once per submitted request (in submit order): True tags
        this request as the poison a ``compute:poison:nth=K`` spec names."""
        if not self.enabled:
            return False
        with self._lock:
            idx = self._submits
            self._submits += 1
            return any(f.mode == "poison" and f.nth == idx
                       for f in self.faults)

    def cohort_poisoned(self, requests) -> bool:
        """True when any request in the cohort carries the poison tag."""
        return self.enabled and any(getattr(r, "poison", False)
                                    for r in requests)

    # -- injection ---------------------------------------------------------

    def _arm(self, stage: str) -> _Fault | None:
        """First fault eligible to fire at ``stage`` right now (poison is
        request-keyed, handled via mark_poison/cohort_poisoned)."""
        with self._lock:
            for f in self.faults:
                if f.stage != stage or f.mode == "poison":
                    continue
                f.seen += 1
                if f.seen <= f.after:
                    continue
                if f.times is not None and f.fired >= f.times:
                    continue
                if f.p < 1.0 and self._rng.random() >= f.p:
                    continue
                f.fired += 1
                return f
        return None

    def inject(self, stage: str, *, stop=None, cancel=None) -> str | None:
        """Fire any armed fault for ``stage``.

        Raises for ``exception``/``die``; sleeps for ``latency``; blocks
        for ``hang`` until ``cancel``/``stop``/``self.cancel`` is set or
        ``hang_s`` elapses.  Returns the fired mode (``"nan"`` tells the
        d2h call site to corrupt its fetched payload), or None.
        """
        if not self.enabled:
            return None
        f = self._arm(stage)
        if f is None:
            return None
        if f.mode == "exception":
            raise InjectedFault(
                f"injected {stage} exception #{f.fired} (spec '{self.spec}')")
        if f.mode == "die":
            raise KillThread(f"injected {stage} thread death #{f.fired}")
        if f.mode == "conn_reset":
            # OSError subclass: the caller's network-failure handling
            # (gateway breaker, retry budget) must treat it as real
            raise ConnectionResetError(
                f"injected {stage} conn-reset #{f.fired}")
        if f.mode in ("latency", "slow_drip"):
            time.sleep(f.delay_ms / 1e3)
        elif f.mode == "hang":
            self._wait_cancelled(f.hang_s, stop, cancel)
        elif f.mode == "blackhole":
            self._wait_cancelled(f.hang_s, stop, cancel)
            raise TimeoutError(
                f"injected {stage} blackhole #{f.fired} "
                f"({f.hang_s:g}s of silence)")
        return f.mode

    def _wait_cancelled(self, seconds: float, stop, cancel):
        t_end = time.monotonic() + seconds
        while time.monotonic() < t_end:
            if self.cancel.is_set():
                break
            if cancel is not None and cancel.is_set():
                break
            if stop is not None and stop.is_set():
                break
            time.sleep(0.005)

    # -- observability -----------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {"spec": self.spec, "seed": self.seed,
                    "injected": {f"{f.stage}:{f.mode}": f.fired
                                 for f in self.faults if f.fired}}
